"""Runtime stats provider: stage-boundary snapshots of the PR 3 rollups.

Reference role: the ``RuntimeInfoProvider`` handed to Trino's
``AdaptivePlanner`` — a read-only view of what the workers actually did,
decoupled from how the coordinator collects it. The provider wraps the
coordinator's slot-keyed task-stats map (``QueryExecution.task_stats``) and
answers the questions the re-planning rules ask: is this stage's output
final, how many rows did it actually produce, and how were its output
bytes distributed across partitions.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional


class RuntimeStatsProvider:
    """Point-in-time view of worker-reported task stats, grouped by stage.

    ``task_entries_fn`` returns the coordinator's current slot records
    (``{"fragment": int, "state": str, "stats": {...}}`` — one per task
    slot, so FTE retries/speculation never double count); ``sweep_fn``
    (optional) forces one fresh status sweep before the snapshot so a
    stage-boundary decision never acts on stale numbers;
    ``expected_tasks_fn`` (optional) returns how many tasks the stage was
    scheduled with — REQUIRED knowledge for flush detection, because a
    task whose create-response seeding failed and whose polls keep timing
    out simply has no slot record, and summing the slots that happen to
    exist would pass a partial number off as truth.
    """

    # a stage's outputs are FINAL once every task is at least FLUSHING:
    # the task body has finished and its output rows/bytes are recorded
    # before the FLUSHING transition (server/task.py)
    FLUSHED_STATES = ("FLUSHING", "FINISHED")

    def __init__(self, task_entries_fn: Callable[[], List[dict]],
                 sweep_fn: Optional[Callable[[], object]] = None,
                 expected_tasks_fn: Optional[Callable[[int], int]] = None):
        self._task_entries_fn = task_entries_fn
        self._sweep_fn = sweep_fn
        self._expected_tasks_fn = expected_tasks_fn
        self._by_frag: Dict[int, List[dict]] = {}

    def snapshot(self) -> "RuntimeStatsProvider":
        """Refresh the view (one status sweep + regroup); returns self so
        call sites can chain ``provider.snapshot().output_rows(fid)``."""
        if self._sweep_fn is not None:
            self._sweep_fn()
        by_frag: Dict[int, List[dict]] = {}
        for e in self._task_entries_fn():
            by_frag.setdefault(e["fragment"], []).append(e)
        self._by_frag = by_frag
        return self

    def stage_flushed(self, fragment_id: int) -> bool:
        """True when every task of the stage reported FLUSHING or later —
        its output rows/bytes are final even while buffers still drain.
        A stage with fewer slot records than scheduled tasks is NOT
        flushed, whatever the present records say."""
        entries = self._by_frag.get(fragment_id)
        if not entries:
            return False
        if self._expected_tasks_fn is not None:
            expected = self._expected_tasks_fn(fragment_id)
            if expected <= 0 or len(entries) < expected:
                return False
        return all(e.get("state") in self.FLUSHED_STATES for e in entries)

    def output_rows(self, fragment_id: int) -> Optional[int]:
        """ACTUAL rows the stage produced, or None while any task still
        runs (a partial sum must never masquerade as truth)."""
        if not self.stage_flushed(fragment_id):
            return None
        return sum(
            int((e.get("stats") or {}).get("outputRows", 0))
            for e in self._by_frag.get(fragment_id, ()))

    def _partition_series(self, fragment_id: int,
                          key: str) -> Optional[List[int]]:
        if not self.stage_flushed(fragment_id):
            return None
        total: Optional[List[int]] = None
        for e in self._by_frag.get(fragment_id, ()):
            pb = (e.get("stats") or {}).get(key)
            if pb is None:
                continue
            if total is None:
                total = [0] * len(pb)
            for i, b in enumerate(pb[: len(total)]):
                total[i] += int(b)
        return total

    def partition_bytes(self, fragment_id: int) -> Optional[List[int]]:
        """Per-partition output bytes summed across the stage's tasks
        (hash-partitioned producers only), or None while running / when no
        task reported a partition breakdown."""
        return self._partition_series(fragment_id, "partitionBytes")

    def partition_rows(self, fragment_id: int) -> Optional[List[int]]:
        """Per-partition LIVE output rows — the skew-detection signal
        (bytes are serde-compressed, and a constant hot key compresses to
        almost nothing, inverting the byte signal)."""
        return self._partition_series(fragment_id, "partitionRows")
