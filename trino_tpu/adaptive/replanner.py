"""The adaptive re-planner: rewrite not-yet-scheduled fragments from stats.

Reference: ``sql/planner/AdaptivePlanner.java`` (re-optimizes the remaining
plan between stage completions using ``RuntimeInfoProvider``) +
``DetermineJoinDistributionType`` re-fired on actual cardinalities. The
coordinator calls :meth:`AdaptivePlanner.adapt_fragment` at every stage
boundary — after the phased-execution build waits, immediately before the
fragment's tasks are created — so every rewrite touches only fragments
whose tasks do not exist yet. Superseded producer stages (their output
shape no longer matches the adapted consumer) are re-run as NEW fragments;
the caller cancels the originals.

Rules, in application order:

1. capacity reseeding (``adaptive_capacity_reseed``): exchange sources of
   the candidate fragment stamp ``runtime_rows`` from completed upstream
   stages — downstream estimates start from truth;
2. join-distribution switch (``adaptive_join_distribution``): with the
   build side stamped, the STATIC distribution rule
   (``stats.join_repartitions``) re-fires; a contradiction flips
   broadcast↔partitioned via the fragmenter's adapted-subtree cuts;
3. skew mitigation (``adaptive_skew_threshold``): hot partitions detected
   from per-partition output bytes re-run both join producers salted —
   probe rows of hot partitions spread across all partitions, build rows
   of hot partitions replicate everywhere (exactness argument in
   ``parallel/exchange.spread_partition_ids``).
"""
from __future__ import annotations

import copy
import dataclasses
from typing import Dict, List, Optional, Tuple

from trino_tpu.adaptive.runtime_stats import RuntimeStatsProvider
from trino_tpu.sql.planner import plan as P
from trino_tpu.sql.planner.fragmenter import (
    PlanFragment, RemoteSourceNode, adapt_broadcast_to_partitioned,
    adapt_partitioned_to_broadcast)

# a partition is "hot" only above BOTH the relative threshold
# (adaptive_skew_threshold x the mean of the OTHER partitions) and this
# absolute row floor — tiny stages are trivially imbalanced and never
# worth a producer re-run. Detection runs on ROWS, not bytes: serde
# compression flattens a constant hot key to almost no bytes, inverting
# the byte signal, while join cost tracks rows.
SKEW_MIN_HOT_ROWS = 4096
# replicating hot build partitions to every task costs hot_bytes x tasks;
# past this budget the mitigation would cost more than the skew
SKEW_REPLICATE_MAX_BYTES = 64 << 20


@dataclasses.dataclass
class PlanChange:
    """One versioned plan change (reference: the plan-version snapshots
    AdaptivePlanner records on the query for EXPLAIN/UI)."""

    version: int
    rule: str  # join-distribution | capacity-reseed | skew-mitigation
    fragment: int  # the adapted (consumer) fragment
    description: str  # e.g. "broadcast->partitioned"
    # new producer fragments this change introduced (already in by_id)
    new_fragments: List[int] = dataclasses.field(default_factory=list)
    # producer fragments whose tasks the change orphaned (caller cancels)
    supersedes: List[int] = dataclasses.field(default_factory=list)
    detail: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "rule": self.rule,
            "fragment": self.fragment,
            "description": self.description,
            "newFragments": list(self.new_fragments),
            "supersedes": list(self.supersedes),
            "detail": dict(self.detail),
        }


def _is_leaf(root: P.PlanNode) -> bool:
    """A fragment is re-runnable only when it is a LEAF (scans + local
    operators, no RemoteSourceNode): its inputs re-enumerate from splits,
    whereas an exchange-fed fragment's upstream buffers were already
    drained by the original attempt."""
    return not any(isinstance(n, RemoteSourceNode) for n in P.walk_plan(root))


class AdaptivePlanner:
    """Applies the adaptive rules to one candidate fragment at a time."""

    def __init__(self, session, stats: RuntimeStatsProvider, n_workers: int,
                 id_alloc):
        self.session = session
        self.props = getattr(session, "properties", None) or {}
        self.stats = stats
        self.n_workers = n_workers
        self.id_alloc = id_alloc
        self._version = 0

    def _next_version(self) -> int:
        self._version += 1
        return self._version

    # ------------------------------------------------------------ the hook
    def adapt_fragment(
        self, frag: PlanFragment, by_id: Dict[int, PlanFragment],
    ) -> Tuple[List[PlanFragment], List[PlanChange], List[str]]:
        """Adapt one not-yet-scheduled fragment against the current runtime
        stats. Returns ``(new_fragments, changes, errors)``: the caller
        schedules the new producer fragments BEFORE ``frag``, cancels every
        fragment a change supersedes, records the changes, and reports the
        errors. Rules are exception-isolated from EACH OTHER: a later rule
        blowing up must not discard the audit record of a change an
        earlier rule already applied in place."""
        errors: List[str] = []
        if not bool(self.props.get("adaptive_execution_enabled", True)):
            return [], [], errors
        if _is_leaf(frag.root):
            # no exchange sources — no upstream stage to learn from, and
            # nothing any rule could rewrite; skip the stats sweep
            return [], [], errors
        reseed_on = bool(self.props.get("adaptive_capacity_reseed", False))
        join_rule_on = bool(
            self.props.get("adaptive_join_distribution", True))
        skew_on = int(self.props.get("adaptive_skew_threshold", 8) or 0) > 0
        has_remote_join = any(
            isinstance(n, P.JoinNode)
            and isinstance(n.right, RemoteSourceNode)
            for n in P.walk_plan(frag.root))
        if not reseed_on and not has_remote_join:
            # a join-free consumer (e.g. a hash final-agg stage) with
            # reseeding off: no rule can fire — skip the status sweep
            # instead of paying a full poll round per stage boundary
            return [], [], errors
        self.stats.snapshot()
        changes: List[PlanChange] = []
        new_frags: List[PlanFragment] = []
        if reseed_on:
            try:
                ch = self._reseed_sources(frag)
                if ch is not None:
                    changes.append(ch)
            except Exception as e:  # noqa: BLE001 — rule-isolated
                errors.append(f"capacity-reseed: {e}")
        # snapshot AFTER reseeding (stamps are metadata, not structure):
        # a structural rewrite that fails plan validation restores this —
        # the containment contract (errored plan/adapt span + keep the
        # pre-adaptation plan, never fail the query)
        from trino_tpu.sql.planner.sanity import validation_enabled

        validate = validation_enabled(self.session)
        snapshot = (
            (copy.deepcopy(frag.root), frag.partitioning)
            if validate and has_remote_join and (join_rule_on or skew_on)
            else None)
        if join_rule_on and has_remote_join:
            try:
                flipped = self._maybe_flip_join(frag, by_id)
            except Exception as e:  # noqa: BLE001 — rule-isolated
                errors.append(f"join-distribution: {e}")
                flipped = None
            if flipped is not None and validate:
                flipped = self._contain_invalid(
                    frag, by_id, snapshot, flipped,
                    "join-distribution", errors)
            if flipped is not None:
                frags, ch = flipped
                new_frags.extend(frags)
                changes.append(ch)
                # restructured: one rewrite per round
                return new_frags, changes, errors
        if skew_on and has_remote_join:
            try:
                mitigated = self._maybe_mitigate_skew(frag, by_id)
            except Exception as e:  # noqa: BLE001 — rule-isolated
                errors.append(f"skew-mitigation: {e}")
                mitigated = None
            if mitigated is not None and validate:
                mitigated = self._contain_invalid(
                    frag, by_id, snapshot, mitigated,
                    "skew-mitigation", errors)
            if mitigated is not None:
                frags, ch = mitigated
                new_frags.extend(frags)
                changes.append(ch)
        return new_frags, changes, errors

    def _contain_invalid(self, frag, by_id, snapshot, produced, rule,
                         errors):
        """Validate the post-rewrite fragment graph; a PlanSanityError is
        CONTAINED: restore the pre-adaptation plan from ``snapshot``, pull
        the rule's new fragments back out of ``by_id``, and record the
        error (the coordinator turns it into an errored ``plan/adapt``
        span) — a runtime rewrite must never fail a query that would have
        run fine unadapted. Returns ``produced`` when valid, None when
        rolled back."""
        from trino_tpu.sql.planner.sanity import validate_adapted

        frags, _ch = produced
        try:
            validate_adapted(frag, frags, by_id, phase=f"adaptive:{rule}")
        # any exception, not just PlanSanityError: a plan malformed enough
        # to crash the walker itself (IndexError in a node property, ...)
        # must roll back the same way — the caller swallows whatever
        # escapes here, which would leave the half-rewritten plan live
        except Exception as e:  # noqa: BLE001 — containment contract
            # restore a fresh COPY: a later rule may rewrite (and fail)
            # again, and its restore must not see this rule's mutations
            frag.root, frag.partitioning = (
                copy.deepcopy(snapshot[0]), snapshot[1])
            for f in frags:
                by_id.pop(f.id, None)
            errors.append(f"{rule}: contained plan-validation failure "
                          f"(pre-adaptation plan kept): {e}")
            return None
        return produced

    # --------------------------------------------- rule 2: reseed sources
    def _reseed_sources(self, frag: PlanFragment) -> Optional[PlanChange]:
        """Stamp every exchange source whose producing stage completed with
        its ACTUAL output rows — the TableScanNode.runtime_rows analog on
        fragment boundaries (estimation downstream starts from truth)."""
        stamped: Dict[int, int] = {}
        for node in P.walk_plan(frag.root):
            if not isinstance(node, RemoteSourceNode):
                continue
            if node.runtime_rows is not None:
                continue
            rows = self.stats.output_rows(node.fragment_id)
            if rows is not None:
                node.runtime_rows = rows
                stamped[node.fragment_id] = rows
        if not stamped:
            return None
        return PlanChange(
            version=self._next_version(), rule="capacity-reseed",
            fragment=frag.id,
            description=f"reseeded {len(stamped)} exchange source(s) "
                        "from actual stage rows",
            detail={"runtimeRows": {str(k): v for k, v in stamped.items()}})

    # --------------------------------- rule 1: join-distribution switch
    def _broadcast_limit(self) -> int:
        """The SAME limit resolution the static rule uses — recorded in
        the flip's PlanChange detail, never re-derived independently."""
        from trino_tpu.sql.planner import stats as stats_mod

        return stats_mod.resolved_broadcast_limit(self.props)

    def _maybe_flip_join(
        self, frag: PlanFragment, by_id: Dict[int, PlanFragment],
    ) -> Optional[Tuple[List[PlanFragment], PlanChange]]:
        from trino_tpu.sql.planner.optimizer import reoptimize_distribution

        for j in P.walk_plan(frag.root):
            if not isinstance(j, P.JoinNode) or not j.left_keys:
                continue
            if j.join_type not in ("inner", "semi", "anti", "left"):
                continue
            right = j.right
            if not isinstance(right, RemoteSourceNode):
                continue
            if right.exchange_type not in ("broadcast", "partitioned"):
                continue
            bfrag = by_id.get(right.fragment_id)
            if bfrag is None or not _is_leaf(bfrag.root):
                continue  # the build must be re-runnable from splits
            actual = self.stats.output_rows(right.fragment_id)
            if actual is None:
                continue  # stage still running: nothing to contradict
            # stamp truth, then re-fire the STATIC distribution rule —
            # the adaptive decision IS the planner's own rule on actuals
            prev_stamp = right.runtime_rows
            right.runtime_rows = actual
            try:
                decision = reoptimize_distribution(
                    self.session, j, self.n_workers)
                if (right.exchange_type == "broadcast"
                        and decision == "partitioned"
                        and frag.partitioning == "source"
                        and self._scans_confined_to_probe(frag, j)):
                    build_root = copy.deepcopy(bfrag.root)
                    frags = adapt_broadcast_to_partitioned(
                        frag, j, build_root, self.id_alloc)
                    desc = "broadcast->partitioned"
                elif (right.exchange_type == "partitioned"
                      and frag.partitioning == "hash"
                      and decision == "broadcast"):
                    build_root = copy.deepcopy(bfrag.root)
                    frags = adapt_partitioned_to_broadcast(
                        frag, j, build_root, self.id_alloc)
                    desc = "partitioned->broadcast"
                else:
                    # actuals agree with the scheduled shape: no change —
                    # and the stamp used to decide must not leak into the
                    # plan unless the user opted into reseeding (the flip
                    # itself is always audited via its PlanChange, stamp
                    # included)
                    if not bool(self.props.get("adaptive_capacity_reseed",
                                               False)):
                        right.runtime_rows = prev_stamp
                    continue
            except Exception:
                # a crashed rule must not leak the stamp either: the
                # caller records the error and the plan stays as-was
                right.runtime_rows = prev_stamp
                raise
            change = PlanChange(
                version=self._next_version(), rule="join-distribution",
                fragment=frag.id, description=desc,
                new_fragments=[f.id for f in frags],
                supersedes=[bfrag.id],
                detail={"join": j.id, "buildRows": actual,
                        "limit": self._broadcast_limit()})
            for f in frags:
                by_id[f.id] = f
            return frags, change
        return None

    @staticmethod
    def _scans_confined_to_probe(frag: PlanFragment, j: P.JoinNode) -> bool:
        """The broadcast→partitioned cut moves the probe subtree out of the
        fragment and its task descriptors carry NO splits afterwards — so
        every scan the fragment owns must live inside the probe subtree
        (a scan elsewhere, e.g. under a UNION sibling, would silently read
        nothing)."""
        probe_scans = {n.id for n in P.walk_plan(j.left)
                       if isinstance(n, P.TableScanNode)}
        frag_scans = {n.id for n in P.walk_plan(frag.root)
                      if isinstance(n, P.TableScanNode)}
        return frag_scans == probe_scans

    # -------------------------------------------- rule 3: skew mitigation
    def _maybe_mitigate_skew(
        self, frag: PlanFragment, by_id: Dict[int, PlanFragment],
    ) -> Optional[Tuple[List[PlanFragment], PlanChange]]:
        threshold = int(self.props.get("adaptive_skew_threshold", 8) or 0)
        if frag.partitioning != "hash" or self.n_workers < 2:
            return None
        for j in P.walk_plan(frag.root):
            if not isinstance(j, P.JoinNode) or not j.left_keys:
                continue
            if j.join_type not in ("inner", "semi", "anti", "left"):
                continue
            left, right = j.left, j.right
            if not (isinstance(left, RemoteSourceNode)
                    and isinstance(right, RemoteSourceNode)):
                continue
            if (left.exchange_type != "partitioned"
                    or right.exchange_type != "partitioned"):
                continue
            pfrag = by_id.get(left.fragment_id)
            bfrag = by_id.get(right.fragment_id)
            if pfrag is None or bfrag is None:
                continue
            if not (_is_leaf(pfrag.root) and _is_leaf(bfrag.root)):
                continue  # both producers must be re-runnable
            probe_pr = self.stats.partition_rows(left.fragment_id)
            build_pr = self.stats.partition_rows(right.fragment_id)
            if probe_pr is None or build_pr is None:
                continue  # producers still running / no breakdown yet
            hot = sorted(set(self._hot_partitions(probe_pr, threshold))
                         | set(self._hot_partitions(build_pr, threshold)))
            if not hot or len(hot) >= len(probe_pr):
                continue
            build_pb = self.stats.partition_bytes(right.fragment_id) or []
            replicate_cost = sum(
                build_pb[h] for h in hot if h < len(build_pb)
            ) * self.n_workers
            if replicate_cost > SKEW_REPLICATE_MAX_BYTES:
                continue  # replication would cost more than the skew
            p2 = PlanFragment(
                next(self.id_alloc), "source", copy.deepcopy(pfrag.root),
                output_partition_channels=list(
                    pfrag.output_partition_channels or ()))
            p2.skew_spread_partitions = hot
            b2 = PlanFragment(
                next(self.id_alloc), "source", copy.deepcopy(bfrag.root),
                output_partition_channels=list(
                    bfrag.output_partition_channels or ()))
            b2.skew_replicate_partitions = hot
            left.fragment_id, right.fragment_id = p2.id, b2.id
            change = PlanChange(
                version=self._next_version(), rule="skew-mitigation",
                fragment=frag.id,
                description=f"salted {len(hot)} hot partition(s) "
                            f"{hot}",
                new_fragments=[p2.id, b2.id],
                supersedes=[pfrag.id, bfrag.id],
                detail={"join": j.id, "hotPartitions": hot,
                        "probePartitionRows": list(probe_pr),
                        "buildPartitionRows": list(build_pr)})
            by_id[p2.id], by_id[b2.id] = p2, b2
            return [p2, b2], change
        return None

    @staticmethod
    def _hot_partitions(prows: List[int], threshold: int) -> List[int]:
        """Partitions holding more than ``threshold`` x the mean rows of
        the OTHER partitions (and at least SKEW_MIN_HOT_ROWS — tiny
        stages are noise). Excluding the candidate from the mean keeps the
        ratio meaningful on small clusters: with 2 partitions a fully
        skewed stage is max/mean 2.0 but max/mean-of-others unbounded."""
        total = sum(prows)
        if total <= 0 or len(prows) < 2:
            return []
        return [
            p for p, b in enumerate(prows)
            if b >= SKEW_MIN_HOT_ROWS
            and b > threshold * max((total - b) / (len(prows) - 1), 1.0)
        ]
