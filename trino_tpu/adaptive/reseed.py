"""Capacity-hint reseeding from staged truth (the compiled-tier half of
adaptive rule 2).

The static hints of ``sql/planner/stats.py`` guess expansion-join outputs
and hash-exchange block sizes from connector stats with fudge factors
biased high — over-allocating HBM when right and STILL recompiling when
wrong (the double-and-recompile loop). But by the time the compiled tiers
jit, phase 1 has already STAGED every scan host-side: the actual key
columns are sitting in host memory. This module prices the hints from
them —

- expansion joins: per-probe-row build-key multiplicities via one
  ``np.unique`` + ``searchsorted`` give the exact match count (hash
  collisions and pre-filter rows only ever INFLATE it, so the capacity is
  a true upper bound — never a recompile);
- hash exchanges: the per-(source shard, destination partition) send-block
  histogram uses the same splitmix64 combine as the device exchange
  (``parallel/exchange.partition_ids`` / ``exec/memory.partition_page_host``),
  so skewed keys price their actual hot-partition block instead of the
  2x-uniform guess.

Consumed by ``CompiledQuery.build`` and ``DistributedQuery.build`` when the
``adaptive_capacity_reseed`` session property is set: reseeded keys REPLACE
the static guesses (reference: AdaptivePlanner swapping estimated stats for
runtime stats).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from trino_tpu.exec.memory import _mix64_np as _mix64
from trino_tpu.sql.planner import plan as P
from trino_tpu.sql.planner.stats import _pow2

# the caps are exact-or-over already; the pow2 rounding of _pow2 (shared
# with the static hints in sql/planner/stats.py, so static and reseeded
# capacities always round identically) is the only headroom they need
_MIN_CAP = 1024
_MIN_XCHG_CAP = 256


@dataclasses.dataclass
class _SideKeys:
    """Host view of one join side's key columns, in staged row order."""

    hash: np.ndarray  # uint64[n], NULLs mapped to the shared null hash
    live: np.ndarray  # bool[n], staged sel AND key non-null (match math)
    sel: np.ndarray  # bool[n], staged sel only (exchange/emit math —
    # null-key rows still ship to the null partition and still emit
    # outer-join slots)
    n_rows: int  # staged rows INCLUDING dead/pad slots (shard math)


def _trace_channel(node: P.PlanNode, ch: int) -> Optional[Tuple[int, int]]:
    """(scan node id, scan channel) a channel traces to through row-local
    operators, or None. Filters/limits along the way only REDUCE rows, so
    counting on the staged (pre-filter) column stays an upper bound."""
    if isinstance(node, P.TableScanNode):
        return node.id, ch
    if isinstance(node, P.ProjectNode):
        from trino_tpu.sql import ir

        e = node.expressions[ch]
        if isinstance(e, ir.ColumnRef):
            return _trace_channel(node.source, e.index)
        return None
    if isinstance(node, (P.FilterNode, P.CompactNode, P.LimitNode,
                         P.TopNNode, P.SortNode)):
        return _trace_channel(node.source, ch)
    return None


def _trace_rows(node: P.PlanNode, staged: Dict[int, object]) -> Optional[int]:
    """Upper-bound LIVE row count of a row-local subtree from its staged
    scan, or None when the subtree is not scan-rooted."""
    if isinstance(node, P.TableScanNode):
        page = staged.get(node.id)
        if page is None:
            return None
        if page.sel is None:
            return int(page.num_rows)
        return int(np.asarray(page.sel).sum())
    if isinstance(node, (P.FilterNode, P.ProjectNode, P.CompactNode,
                         P.LimitNode, P.TopNNode, P.SortNode)):
        return _trace_rows(node.source, staged)
    return None


def _side_keys(staged: Dict[int, object], side: P.PlanNode,
               channels) -> Optional[_SideKeys]:
    """Combined key hash + liveness for one join side, or None when any
    key is untraceable / varchar (probe and build dictionaries are
    page-local — code equality across sides is meaningless)."""
    from trino_tpu.exec.memory import _NULL_HASH

    scan_id = None
    cols = []
    for ch in channels:
        hit = _trace_channel(side, ch)
        if hit is None:
            return None
        sid, sc = hit
        if scan_id is None:
            scan_id = sid
        elif sid != scan_id:
            return None  # keys from two scans: row orders don't align
        page = staged.get(sid)
        if page is None:
            return None
        col = page.columns[sc]
        if col.type.is_varchar:
            return None
        cols.append(col)
    page = staged[scan_id]
    n = int(page.num_rows)
    live = (np.ones(n, bool) if page.sel is None
            else np.asarray(page.sel).astype(bool))
    h = np.zeros(n, np.uint64)
    valid = live.copy()
    for col in cols:
        # low limb only — the cross-side placement contract of
        # partition_page_host / parallel/exchange (hash-equal is a
        # superset of key-equal, which only inflates match counts). NULL
        # keys hash to the shared null constant so they still co-locate
        # for partition counting, but drop out of ``valid`` — they never
        # match.
        k = _mix64(np.asarray(col.values).astype(np.int64))
        if col.nulls is not None:
            nulls = np.asarray(col.nulls).astype(bool)
            k = np.where(nulls, np.uint64(_NULL_HASH), k)
            valid &= ~nulls
        h = _mix64(h ^ k)
    return _SideKeys(hash=h, live=valid, sel=live, n_rows=n)


def _match_counts(probe: _SideKeys, build: _SideKeys) -> np.ndarray:
    """Build-key multiplicity per LIVE probe row (0 for dead/null rows)."""
    bh = build.hash[build.live]
    if len(bh) == 0:
        return np.zeros(probe.n_rows, np.int64)
    uniq, counts = np.unique(bh, return_counts=True)
    idx = np.searchsorted(uniq, probe.hash)
    idx = np.clip(idx, 0, len(uniq) - 1)
    hit = (uniq[idx] == probe.hash) & probe.live
    return np.where(hit, counts[idx], 0).astype(np.int64)


def _group_max(values: np.ndarray, groups: np.ndarray, n_groups: int) -> int:
    """max over groups of the per-group sum of ``values``."""
    sums = np.bincount(groups, weights=values.astype(np.float64),
                       minlength=n_groups)
    return int(sums.max()) if len(sums) else 0


def _shard_ids(k: _SideKeys, n_devices: int) -> np.ndarray:
    """Device shard per staged row: scans stage contiguous equal-length
    shards (stage_sharded_scans pads every shard to the same length)."""
    per_shard = max(k.n_rows // max(n_devices, 1), 1)
    return np.minimum(np.arange(k.n_rows) // per_shard, n_devices - 1)


def _expansion_capacity(node: P.JoinNode, probe: _SideKeys,
                        build: _SideKeys, n_devices: int,
                        partitioned: bool) -> int:
    counts = _match_counts(probe, build)
    if node.join_type == "left":
        # outer probes emit >= one slot each (unmatched and null-key
        # rows included)
        counts = np.where(probe.sel, np.maximum(counts, 1), counts)
    if n_devices <= 1:
        total = int(counts.sum())
        return _pow2(max(total, _MIN_CAP))
    if partitioned:
        # after the co-partitioning exchange, device p joins partition p:
        # its expansion output is exactly partition p's match count
        pid = (probe.hash % np.uint64(n_devices)).astype(np.int64)
        worst = _group_max(counts, pid, n_devices)
    else:
        # broadcast build: device s probes its own shard against the
        # whole build
        worst = _group_max(counts, _shard_ids(probe, n_devices), n_devices)
    return _pow2(max(worst, _MIN_CAP))


def _exchange_block_capacity(k: _SideKeys, n_devices: int) -> int:
    """Exact send-block size for a hash exchange of these rows: the max
    over (source shard, destination partition) of rows sent — the skewed
    hot partition prices its real block instead of the 2x-uniform guess."""
    pid = (k.hash % np.uint64(n_devices)).astype(np.int64)
    shard = _shard_ids(k, n_devices)
    flat = shard * n_devices + pid
    counts = np.bincount(flat[k.sel], minlength=n_devices * n_devices)
    worst = int(counts.max()) if len(counts) else 0
    return _pow2(max(worst, _MIN_XCHG_CAP))


_JTILE_MIN = 512
_JTILE_MAX = 8192


def _merge_tile_hint(probe: _SideKeys, build: _SideKeys) -> int:
    """Build-window rows per probe block for the Pallas tiled merge
    (``jtile:<join id>``), priced from the SAME staged key histograms
    that size the capacities: a probe block of B sorted keys spans about
    ``B * nb/np * max_multiplicity`` build slots, so skewed builds get
    wider DMA windows up front instead of paying extra window iterations
    per block. Rounded to the kernel's 128-lane granularity via pow2,
    clamped to [512, 8192] (VMEM double-buffer budget)."""
    from trino_tpu.ops.merge_pallas import BLOCK_PROBE

    bh = build.hash[build.live]
    n_probe = max(int(probe.sel.sum()), 1)
    if len(bh) == 0:
        return _JTILE_MIN
    _, counts = np.unique(bh, return_counts=True)
    mult = int(counts.max())
    est = BLOCK_PROBE * len(bh) * mult // n_probe
    return min(max(_pow2(max(est, 1)), _JTILE_MIN), _JTILE_MAX)


def reseed_capacity_hints(session, root: P.PlanNode,
                          staged: Dict[int, object],
                          n_devices: int = 1) -> Dict[str, int]:
    """Capacity hints priced from the staged scan pages (actual rows/keys)
    for every expansion join and hash exchange whose keys trace to staged
    columns, plus ``jtile:*`` merge-window hints for the fused join
    tier's Pallas kernel. Returns only the keys it could compute —
    callers ``update()`` them over the static guesses."""
    from trino_tpu.sql.planner import stats

    # jtile hints are consumed ONLY by the opt-in Pallas merge kernel —
    # don't pay the per-join host histogram passes when nothing reads them
    props = getattr(session, "properties", None) or {}
    price_jtile = bool(props.get("fused_join_pallas"))
    hints: Dict[str, int] = {}
    for n in P.walk_plan(root):
        if isinstance(n, P.JoinNode):
            # one histogram pass per side, computed lazily and shared by
            # every hint family (capacity, exchange block, merge tile)
            sides: List = []

            def side_keys(n=n, sides=sides):
                if not sides:
                    sides.append((_side_keys(staged, n.left, n.left_keys),
                                  _side_keys(staged, n.right, n.right_keys)))
                return sides[0]

            partitioned = bool(
                n_devices > 1 and n.left_keys
                and stats.join_repartitions(session, n, n_devices))
            if price_jtile and n.left_keys:
                probe, build = side_keys()
                if probe is not None and build is not None:
                    hints[f"jtile:{n.id}"] = _merge_tile_hint(probe, build)
            if P.uses_expansion_kernel(n):
                if n.left_keys:
                    probe, build = side_keys()
                    if probe is not None and build is not None:
                        hints[f"join:{n.id}"] = _expansion_capacity(
                            n, probe, build, n_devices, partitioned)
                elif not n.singleton:
                    lrows = _trace_rows(n.left, staged)
                    rrows = _trace_rows(n.right, staged)
                    if lrows is not None and rrows is not None:
                        per = (-(-lrows // n_devices)
                               if n_devices > 1 else lrows)
                        hints[f"join:{n.id}"] = _pow2(
                            max(per * rrows, _MIN_CAP))
            if partitioned:
                probe, build = side_keys()
                if probe is not None:
                    hints[f"xchgl:{n.id}"] = _exchange_block_capacity(
                        probe, n_devices)
                if build is not None:
                    hints[f"xchgr:{n.id}"] = _exchange_block_capacity(
                        build, n_devices)
        elif isinstance(n, P.AggregationNode) and n.step == "single" \
                and n_devices > 1 and n.group_channels:
            if stats.agg_repartitions(session, n, n_devices):
                k = _side_keys(staged, n.source, n.group_channels)
                if k is not None:
                    hints[f"xchg:{n.id}"] = _exchange_block_capacity(
                        k, n_devices)
    return hints


def staged_pages_from_arrays(staged_arrays: Dict[int, List],
                             specs: Dict[int, object]) -> Dict[int, object]:
    """Reconstruct host Pages from the SPMD tier's sharded staging arrays
    (leading device axis flattened back to rows; pad slots stay dead via
    the sel column) — the reseed view of ``stage_sharded_scans`` output."""
    from trino_tpu.exec.page_tree import unflatten_page

    pages = {}
    for nid, arrs in staged_arrays.items():
        flat = [np.asarray(a).reshape((-1,) + np.asarray(a).shape[2:])
                for a in arrs]
        pages[nid] = unflatten_page(specs[nid], flat)
    return pages


def reseed_enabled(session) -> bool:
    props = getattr(session, "properties", None) or {}
    return bool(props.get("adaptive_capacity_reseed", False))


def apply_reseed(session, root, staged: Dict[int, object], n_devices: int,
                 capacity_hints: Dict[str, int]) -> Dict[str, int]:
    """The one reseed integration both compiled tiers call: compute the
    staged-truth hints, REPLACE the static guesses in ``capacity_hints``
    in place, and record the adaptation (a ``plan/adapt`` span + the
    adaptive metric) ONLY when something was actually reseeded — an empty
    result must not masquerade as an adaptation in the trace."""
    reseeded = reseed_capacity_hints(session, root, staged, n_devices)
    if reseeded:
        from trino_tpu.obs import metrics as M
        from trino_tpu.obs import trace as tracing

        capacity_hints.update(reseeded)
        with tracing.span("plan/adapt") as sp:
            sp.set("rule", "capacity-reseed")
            sp.set("reseeded", len(reseeded))
        M.ADAPTIVE_ADAPTATIONS.inc(1, "capacity-reseed")
    return reseeded
