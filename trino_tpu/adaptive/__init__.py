"""Adaptive query execution: runtime re-planning from the operator-stats
spine (reference: ``sql/planner/AdaptivePlanner.java`` + the FTE adaptive
partitioning of SURVEY §7.3).

PR 3 built the distributed stats pipeline (worker-reported OperatorStats,
task→stage→query rollups); this package makes the engine ACT on it: a
runtime-stats provider snapshots the stage rollups at stage boundaries, and
the adaptive re-planner rewrites **not-yet-scheduled** downstream fragments
between stage completions —

1. join-distribution switch: flip broadcast↔partitioned when a build
   side's ACTUAL rows contradict the estimate across the
   ``join_max_broadcast_rows`` threshold (``replanner.py``);
2. capacity-hint reseeding: exchange sources stamp actual upstream output
   rows (the ``TableScanNode.runtime_rows`` analog on fragment
   boundaries), and the compiled tiers size expansion-join / hash-exchange
   capacities from staged-truth histograms instead of static guesses —
   killing the double-and-recompile loop (``reseed.py``);
3. skew mitigation: hot repartition keys detected from per-partition
   output bytes are salted — the probe producer spreads hot partitions
   across all tasks while the build producer replicates them everywhere
   (``replanner.py`` + ``parallel/exchange.spread_partition_ids``).

Every adaptation is recorded as a versioned plan change on the query
(``GET /v1/query/{id}`` planVersions, EXPLAIN ANALYZE ``[adapted: ...]``
annotations, a ``plan/adapt`` span, ``trino_tpu_adaptive_*`` metrics),
gated by the ``adaptive_*`` session properties.
"""
from trino_tpu.adaptive.replanner import AdaptivePlanner, PlanChange
from trino_tpu.adaptive.runtime_stats import RuntimeStatsProvider

__all__ = ["AdaptivePlanner", "PlanChange", "RuntimeStatsProvider"]
