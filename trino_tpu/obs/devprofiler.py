"""Device execution profiler: kernel ledger, compile ledger, utilization.

Reference role: the device half of Trino's operator stats — Trino's
``OperatorStats`` carries ``addInputWall``/``getOutputWall`` per driver;
here the analogous split is *wall vs device* per dispatch.  PAPER.md's
framing maps Trino's runtime codegen onto XLA/Pallas compilation, which
makes compile events and kernel launches first-class engine work.  The
phase ledger (obs/timeline.py) made every wall-clock millisecond
attributable and the memory ledger (obs/memledger.py) every byte; this
module attributes the *inside* of the ``device-execute`` and
``device-staging`` phases.

Three stores per process (design mirrors obs/memledger.py: bounded
rings, O(1) append under a short lock, fan-out outside the lock):

- a **kernel ledger** — per-query rollups keyed
  ``(plan_node_id, operator, tier)`` recording launch count, wall
  seconds, device seconds, and input/output bytes.  ``wall − device`` is
  the per-operator dispatch overhead — the number ROADMAP item 2's
  fragment megakernels must beat.  Device seconds are
  ``block_until_ready``-bracketed only when the ``device_profiling``
  session property is on; otherwise they are estimated from wall
  (``estimated=True`` rows) so the serving plane never pays a sync.
- a **compile ledger** — a bounded ring of jit/Pallas compile events,
  each naming its tier (``eager``/``compiled``/``spmd``), plan
  fingerprint (cache/plan_key.py spine), shape signature, compile
  seconds, and cache ``hit``/``miss``.  Mirrored into the flight
  recorder so FAILED-query postmortems show recompile storms.
- a **utilization sampler** — monotonic process counters (launches,
  busy seconds, compiles in flight) sampled on the worker announce tick
  into a watermark-style ring (launches/sec, device-busy fraction).

Hot-path contract: ``count_launch`` is a couple of integer adds under
one short lock — safe on the point-lookup serving path.  Metrics and
recorder fan-out happen at *fold* time (query completion) or compile
time (rare), never per-dispatch.

This module is import-clean standalone (stdlib only at import time) so
doc gates can load it without the package/jax.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

# compile events are rare (one per fresh jit); 256 ≈ hours of history
COMPILE_CAPACITY = 256
# announce loop samples every 0.5 s -> ~2 minutes of per-node history
UTILIZATION_CAPACITY = 240
# per-query kernel rollups kept after the query folds (LRU)
MAX_QUERY_PROFILES = 64

TIERS = ("eager", "compiled", "spmd")


def merge_kernel_rows(dst: Dict[tuple, dict],
                      rows: List[dict]) -> Dict[tuple, dict]:
    """Fold serialized kernel rows (``kernel_rows`` wire shape) into a
    ``(planNodeId, operator, tier, nodeId)``-keyed accumulator."""
    for row in rows or []:
        key = (row.get("planNodeId", ""), row.get("operator", ""),
               row.get("tier", "eager"), row.get("nodeId", ""))
        agg = dst.get(key)
        if agg is None:
            agg = {"planNodeId": key[0], "operator": key[1],
                   "tier": key[2], "nodeId": key[3], "launches": 0,
                   "wallS": 0.0, "deviceS": 0.0, "inputBytes": 0,
                   "outputBytes": 0, "estimated": False}
            dst[key] = agg
        agg["launches"] += int(row.get("launches", 0))
        agg["wallS"] += float(row.get("wallS", 0.0))
        agg["deviceS"] += float(row.get("deviceS", 0.0))
        agg["inputBytes"] += int(row.get("inputBytes", 0))
        agg["outputBytes"] += int(row.get("outputBytes", 0))
        agg["estimated"] = bool(agg["estimated"] or row.get("estimated"))
    return dst


class DeviceProfiler:
    """One process's device profiler (coordinator AND every worker —
    same pattern as the per-process memory ledger)."""

    def __init__(self, node_id: str = "",
                 compile_capacity: int = COMPILE_CAPACITY,
                 utilization_capacity: int = UTILIZATION_CAPACITY,
                 max_query_profiles: int = MAX_QUERY_PROFILES):
        self.node_id = node_id
        self._lock = threading.Lock()
        self._compiles: "deque[dict]" = deque(maxlen=compile_capacity)
        self._utilization: "deque[dict]" = deque(
            maxlen=utilization_capacity)
        # queryId -> {(planNodeId, operator, tier, nodeId) -> rollup}
        self._queries: "OrderedDict[str, Dict[tuple, dict]]" = OrderedDict()
        self._max_query_profiles = max_query_profiles
        # monotonic utilization counters (cheap adds on the hot path)
        self._launches_total = 0
        self._busy_s_total = 0.0
        self._compiles_total = 0
        self._compile_inflight = 0
        # previous sample point for rate computation
        self._last_sample_ts: Optional[float] = None
        self._last_launches = 0
        self._last_busy_s = 0.0
        self._recorder = None

    # ------------------------------------------------------------ wiring
    def attach_recorder(self, recorder) -> None:
        """Mirror compile events into the process flight recorder so a
        FAILED-query postmortem shows whether a recompile storm preceded
        the failure (satellite of the flight-recorder contract)."""
        self._recorder = recorder

    # --------------------------------------------------------- hot path
    def count_launch(self, wall_s: float, busy_s: float,
                     n: int = 1) -> None:
        """Zero-sync accounting for one (or ``n``) device dispatches:
        two adds under a short lock, no metrics fan-out.  Safe on the
        point-lookup serving path with ``device_profiling`` off."""
        with self._lock:
            self._launches_total += n
            self._busy_s_total += busy_s if busy_s > 0 else wall_s

    # ----------------------------------------------------- compile ring
    def compile_started(self) -> None:
        with self._lock:
            self._compile_inflight += 1

    def record_compile(self, tier: str, fingerprint: str, shape_sig: str,
                       compile_s: float, cache: str,
                       query_id: str = "", started: bool = False) -> None:
        """Append one compile event (``cache`` is ``"hit"`` or
        ``"miss"``); fan out to the tiered compile-seconds histogram and
        the flight recorder OUTSIDE the ledger lock.

        ``started=True`` pairs with a prior :meth:`compile_started` and
        decrements the in-flight gauge counter."""
        rec = {"ts": time.time(), "nodeId": self.node_id,
               "queryId": query_id, "tier": tier,
               "fingerprint": fingerprint, "shapeSig": shape_sig,
               "compileS": round(float(compile_s), 6), "cache": cache}
        with self._lock:
            self._compiles.append(rec)
            self._compiles_total += 1
            if started and self._compile_inflight > 0:
                self._compile_inflight -= 1
        # fan-out outside the lock — accounting never fails work
        try:
            from trino_tpu.obs import metrics as M

            M.COMPILE_SECONDS_TIERED.observe(float(compile_s), tier, cache)
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass
        if self._recorder is not None:
            try:
                self._recorder.record(
                    "compile", "device/compile-event", tier=tier,
                    cache=cache, fingerprint=fingerprint,
                    shapeSig=shape_sig, compileS=round(float(compile_s), 6),
                    queryId=query_id)
            except Exception:  # noqa: BLE001 — best-effort forensics
                pass

    # ------------------------------------------------------- query fold
    def record_query_kernels(self, query_id: str, rows: List[dict],
                             node_id: Optional[str] = None) -> None:
        """Fold a query's kernel rows (from executors / task rollups)
        into the per-query store, and bump the per-operator launch and
        dispatch-overhead metrics ONCE per fold — not per dispatch."""
        if not rows:
            return
        node = node_id if node_id is not None else self.node_id
        stamped = [dict(r, nodeId=r.get("nodeId") or node) for r in rows]
        with self._lock:
            store = self._queries.get(query_id)
            if store is None:
                store = {}
                self._queries[query_id] = store
                while len(self._queries) > self._max_query_profiles:
                    self._queries.popitem(last=False)
            else:
                self._queries.move_to_end(query_id)
            merge_kernel_rows(store, stamped)
        # metrics fan-out outside the lock, once per fold
        try:
            from trino_tpu.obs import metrics as M

            for row in stamped:
                op = row.get("operator", "")
                launches = int(row.get("launches", 0))
                if launches:
                    M.KERNEL_LAUNCHES.inc(launches, op)
                overhead = max(
                    0.0, float(row.get("wallS", 0.0))
                    - float(row.get("deviceS", 0.0)))
                if overhead > 0:
                    M.KERNEL_DISPATCH_OVERHEAD.inc(overhead, op)
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass

    # ----------------------------------------------------- announce tick
    def sample_utilization(self) -> dict:
        """One announce-loop tick: turn the monotonic counters into
        launches/sec and device-busy fraction since the last tick."""
        now = time.time()
        with self._lock:
            launches = self._launches_total
            busy_s = self._busy_s_total
            inflight = self._compile_inflight
            prev_ts = self._last_sample_ts
            dt = (now - prev_ts) if prev_ts is not None else 0.0
            d_launches = launches - self._last_launches
            d_busy = busy_s - self._last_busy_s
            self._last_sample_ts = now
            self._last_launches = launches
            self._last_busy_s = busy_s
            sample = {
                "ts": now, "nodeId": self.node_id,
                "launchesTotal": launches,
                "launchesPerS": round(d_launches / dt, 3) if dt > 0 else 0.0,
                "busyFraction": round(min(1.0, d_busy / dt), 4)
                if dt > 0 else 0.0,
                "compileInflight": inflight,
                "compilesTotal": self._compiles_total,
            }
            self._utilization.append(sample)
        return sample

    # ------------------------------------------------------------- reads
    def kernel_rows(self, query_id: Optional[str] = None) -> List[dict]:
        """Per-(query, planNode, operator, tier, node) rollup rows — the
        ``system.runtime.kernels`` source."""
        with self._lock:
            if query_id is not None:
                stores = {query_id: self._queries.get(query_id, {})}
            else:
                stores = {qid: dict(s) for qid, s in self._queries.items()}
            rows = []
            for qid, store in stores.items():
                for agg in store.values():
                    row = dict(agg)
                    row["queryId"] = qid
                    row["dispatchOverheadS"] = round(
                        max(0.0, row["wallS"] - row["deviceS"]), 6)
                    rows.append(row)
        rows.sort(key=lambda r: (r["queryId"], r["planNodeId"],
                                 r["operator"], r["nodeId"]))
        return rows

    def compile_rows(self, query_id: Optional[str] = None,
                     limit: Optional[int] = None) -> List[dict]:
        """Oldest-first copy of the compile ring (optionally filtered
        to one query) — the ``system.runtime.compiles`` source."""
        with self._lock:
            records = list(self._compiles)
        if query_id is not None:
            records = [r for r in records if r.get("queryId") == query_id]
        if limit is not None and len(records) > limit:
            records = records[-limit:]
        return records

    def utilization_rows(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            samples = list(self._utilization)
        if limit is not None and len(samples) > limit:
            samples = samples[-limit:]
        return samples

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return {"launchesTotal": self._launches_total,
                    "busySTotal": round(self._busy_s_total, 6),
                    "compilesTotal": self._compiles_total,
                    "compileInflight": self._compile_inflight}

    def profile_snapshot(self, query_id: str) -> dict:
        """The ``/v1/query/{id}/profile`` block for THIS process: the
        query's kernel rollups + its compile events + recent
        utilization."""
        return {"nodeId": self.node_id,
                "kernels": self.kernel_rows(query_id),
                "compiles": self.compile_rows(query_id),
                "utilization": self.utilization_rows(limit=8)}

    def __len__(self) -> int:
        with self._lock:
            return len(self._compiles)


def shape_signature(arrays) -> str:
    """Short, stable signature of input array shapes/dtypes — the compile
    ledger's ``shapeSig`` (mirrors jit's retrace key conceptually)."""
    import hashlib

    parts = []
    for arr in arrays:
        shape = tuple(getattr(arr, "shape", ()) or ())
        dtype = str(getattr(arr, "dtype", type(arr).__name__))
        parts.append(f"{dtype}{list(shape)}")
    sig = ";".join(parts)
    digest = hashlib.sha256(sig.encode()).hexdigest()[:12]
    return f"{digest}:{len(parts)}"


# the per-process profiler (coordinator AND every worker — same pattern
# as MEMORY_LEDGER); servers stamp node_id at startup
DEVICE_PROFILER = DeviceProfiler()
