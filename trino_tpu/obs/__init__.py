"""Observability: query-lifecycle tracing + typed metrics registry.

``trace`` — per-query span tracer with W3C-style context propagation over
the control plane (coordinator schedule -> worker task spans).
``metrics`` — Counter/Gauge/Histogram registry behind ``/v1/metrics``.
``listeners`` — in-tree event-listener consumers (slow-query log).
"""
from trino_tpu.obs import metrics, trace  # noqa: F401
from trino_tpu.obs.metrics import REGISTRY  # noqa: F401
from trino_tpu.obs.trace import Tracer, activate, build_tree, span  # noqa: F401
