"""OTLP-JSON export over HTTP: traces + metric snapshots to a collector.

Reference: the reference engine's OpenTelemetry wiring — an
``io.opentelemetry.api.trace.Tracer`` injected through
``QueuedStatementResource``/``DispatchManager``/``SqlTaskManager`` whose
spans a standard OTLP exporter ships to any collector. Here the engine's
in-process span records (obs/trace.py) are translated to the OTLP-JSON
wire shape (``resourceSpans``/``scopeSpans``; the OTLP/HTTP JSON encoding)
and POSTed to ``TRINO_TPU_OTLP_ENDPOINT`` by a background batch exporter,
so traces land in Jaeger/Tempo/any otel collector without new deps.

Contract (the never-block-the-hot-path clause):

- OFF unless ``TRINO_TPU_OTLP_ENDPOINT`` is set at server construction;
- ``export_spans``/``export_metrics_snapshot`` enqueue onto a BOUNDED
  queue and return immediately — overflow DROPS the batch and bumps
  ``trino_tpu_otlp_dropped_total{reason="overflow"}``;
- the background thread drains batches and POSTs with a short timeout;
  an unreachable/non-2xx collector drops (``reason="send-error"``) and
  the engine never notices.

Trace/span ids are already OTLP-shaped (32/16 lowercase hex — see
``trace._hex_id``), so worker task spans exported with the PROPAGATED
trace id parent into the coordinator's trace inside the collector, the
same cross-process tree ``GET /v1/query/{id}/trace`` assembles locally.

``StubCollector`` is the in-process receiving half used by the tier-1
smoke test (and handy for local development): a tiny HTTP server that
stores every posted payload.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

ENDPOINT_ENV = "TRINO_TPU_OTLP_ENDPOINT"
DEFAULT_QUEUE_MAX = 256  # batches (one batch = one query's / task's spans)


def exporter_from_env(service_name: str,
                      instance_id: Optional[str] = None):
    """The server-construction hook: an exporter when
    ``TRINO_TPU_OTLP_ENDPOINT`` is set, else None (export off — the
    default — costs nothing on the query path)."""
    endpoint = os.environ.get(ENDPOINT_ENV)
    if not endpoint:
        return None
    exporter = OtlpExporter(endpoint, service_name, instance_id)
    exporter.start()
    return exporter


def _kv(key: str, value) -> dict:
    """One OTLP attribute key-value."""
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def _otlp_span(span_dict: dict, now: float) -> dict:
    start_ns = int(float(span_dict.get("start") or now) * 1e9)
    dur = span_dict.get("durationS")
    end_ns = start_ns + int(float(dur) * 1e9) if dur is not None \
        else int(now * 1e9)
    return {
        "traceId": "",  # stamped by the batch builder
        "spanId": span_dict.get("spanId") or "",
        "parentSpanId": span_dict.get("parentId") or "",
        "name": span_dict.get("name") or "span",
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
        "attributes": [
            _kv(k, v)
            for k, v in (span_dict.get("attributes") or {}).items()],
    }


def spans_payload(span_dicts: List[dict], trace_id: str,
                  resource: Dict[str, object]) -> dict:
    """One OTLP-JSON ``ExportTraceServiceRequest`` body."""
    now = time.time()
    spans = []
    for s in span_dicts:
        sp = _otlp_span(s, now)
        sp["traceId"] = trace_id
        spans.append(sp)
    return {
        "resourceSpans": [{
            "resource": {
                "attributes": [_kv(k, v) for k, v in resource.items()]},
            "scopeSpans": [{
                "scope": {"name": "trino_tpu"},
                "spans": spans,
            }],
        }],
    }


def _hist_collect(hist: Dict[str, dict], name: str, labels: Dict[str, str],
                  value: float, help_text: str) -> None:
    """Fold one expanded histogram sample (``_bucket``/``_sum``/
    ``_count``) back into a per-(base name, label set) accumulator."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            break
    else:
        return  # histogram-typed sample with an unknown suffix: drop
    base = name[:-len(suffix)]
    rec = hist.setdefault(base, {"help": help_text, "points": {}})
    base_labels = {k: v for k, v in labels.items() if k != "le"}
    key = tuple(sorted(base_labels.items()))
    pt = rec["points"].setdefault(
        key, {"labels": base_labels, "buckets": [], "sum": 0.0, "count": 0})
    if suffix == "_bucket":
        le = labels.get("le", "+Inf")
        if le != "+Inf":  # +Inf is redundant with _count
            pt["buckets"].append((float(le), value))
    elif suffix == "_sum":
        pt["sum"] = float(value)
    else:
        pt["count"] = int(value)


def _hist_metrics(hist: Dict[str, dict], now_ns: str) -> List[dict]:
    """Real OTLP histogram metrics from the accumulated expansion:
    cumulative Prometheus ``le`` counts become per-bucket counts
    (``bucketCounts`` has ``len(explicitBounds) + 1`` entries — the last
    is the overflow bucket above the highest bound)."""
    out: List[dict] = []
    for base in sorted(hist):
        rec = hist[base]
        points = []
        for key in sorted(rec["points"]):
            pt = rec["points"][key]
            finite = sorted(pt["buckets"])
            counts: List[int] = []
            prev = 0.0
            for _, cum in finite:
                counts.append(max(0, int(cum - prev)))
                prev = cum
            counts.append(max(0, int(pt["count"] - prev)))
            points.append({
                "bucketCounts": [str(c) for c in counts],
                "explicitBounds": [b for b, _ in finite],
                "sum": pt["sum"],
                "count": str(pt["count"]),
                "timeUnixNano": now_ns,
                "attributes": [_kv(k, v)
                               for k, v in pt["labels"].items()],
            })
        out.append({
            "name": base,
            "description": rec["help"],
            "histogram": {"aggregationTemporality": 2,  # CUMULATIVE
                          "dataPoints": points},
        })
    return out


def metrics_payload(samples: List[tuple],
                    resource: Dict[str, object]) -> dict:
    """One OTLP-JSON ``ExportMetricsServiceRequest`` body from the typed
    registry's sample expansion (``registry_samples()``): counters ship
    as cumulative monotonic sums, histograms are reassembled from their
    expanded ``_bucket``/``_sum``/``_count`` series into real OTLP
    histogram points (explicitBounds + per-bucket counts + sum + count),
    everything else as gauges."""
    now_ns = str(int(time.time() * 1e9))
    by_name: Dict[str, dict] = {}
    hist: Dict[str, dict] = {}
    for name, type_name, labels, value, help_text in samples:
        if type_name == "histogram":
            _hist_collect(hist, name, labels, value, help_text)
            continue
        m = by_name.get(name)
        if m is None:
            points_key = "sum" if type_name == "counter" else "gauge"
            body: dict = {"dataPoints": []}
            if type_name == "counter":
                body["aggregationTemporality"] = 2  # CUMULATIVE
                body["isMonotonic"] = True
            m = {"name": name, "description": help_text, points_key: body}
            by_name[name] = m
        body = m.get("sum") or m["gauge"]
        body["dataPoints"].append({
            "asDouble": float(value),
            "timeUnixNano": now_ns,
            "attributes": [_kv(k, v) for k, v in labels.items()],
        })
    metrics = list(by_name.values()) + _hist_metrics(hist, now_ns)
    return {
        "resourceMetrics": [{
            "resource": {
                "attributes": [_kv(k, v) for k, v in resource.items()]},
            "scopeMetrics": [{
                "scope": {"name": "trino_tpu"},
                "metrics": metrics,
            }],
        }],
    }


class OtlpExporter:
    """Bounded-queue background exporter for one server instance
    (coordinator and worker construct their own, so a single test
    process hosting both exports each with its own resource identity)."""

    def __init__(self, endpoint: str, service_name: str,
                 instance_id: Optional[str] = None,
                 queue_max: int = DEFAULT_QUEUE_MAX,
                 flush_interval_s: float = 0.2,
                 metrics_interval_s: Optional[float] = None,
                 timeout_s: float = 3.0):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.instance_id = instance_id
        self.timeout_s = timeout_s
        self.flush_interval_s = flush_interval_s
        # periodic registry snapshots to {endpoint}/v1/metrics, from the
        # exporter's own thread (0 disables; spans are unaffected)
        if metrics_interval_s is None:
            try:
                metrics_interval_s = float(os.environ.get(
                    "TRINO_TPU_OTLP_METRICS_INTERVAL", "10"))
            except ValueError:
                metrics_interval_s = 10.0
        self.metrics_interval_s = metrics_interval_s
        self._last_metrics = time.monotonic()
        self._queue: "deque[tuple]" = deque()
        self._queue_max = queue_max
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._inflight = 0
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ enqueue
    def _resource(self, extra: Optional[Dict[str, object]]) -> dict:
        resource: Dict[str, object] = {"service.name": self.service_name}
        if self.instance_id:
            resource["service.instance.id"] = self.instance_id
        if extra:
            resource.update(extra)
        return resource

    def export_spans(self, span_dicts: List[dict], trace_id: str,
                     resource: Optional[Dict[str, object]] = None) -> bool:
        """Non-blocking: queue one span batch (a completed query's or
        task's tracer dump). Returns False when the bounded queue was
        full and the batch dropped."""
        if not span_dicts:
            return True
        payload = spans_payload(span_dicts, trace_id,
                                self._resource(resource))
        return self._enqueue("/v1/traces", payload, len(span_dicts))

    def export_metrics_snapshot(
            self, resource: Optional[Dict[str, object]] = None) -> bool:
        """Non-blocking: queue one snapshot of the whole metrics
        registry (called by servers on their announce cadence or by
        tests; OFF-path — never from query execution)."""
        from trino_tpu.obs.metrics import registry_samples

        payload = metrics_payload(registry_samples(),
                                  self._resource(resource))
        return self._enqueue("/v1/metrics", payload, 1)

    def _enqueue(self, path: str, payload: dict, weight: int) -> bool:
        with self._lock:
            if len(self._queue) >= self._queue_max:
                dropped = True
            else:
                self._queue.append((path, payload, weight))
                dropped = False
        if dropped:
            from trino_tpu.obs import metrics as M

            M.OTLP_DROPPED.inc(weight, "overflow")
            return False
        self._wake.set()
        return True

    # -------------------------------------------------------------- loop
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"otlp-exporter-{self.service_name}")
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            if (self.metrics_interval_s > 0
                    and time.monotonic() - self._last_metrics
                    >= self.metrics_interval_s):
                self._last_metrics = time.monotonic()
                self.export_metrics_snapshot()
            self._drain()
        self._drain()  # final flush on shutdown

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._queue:
                    return
                path, payload, weight = self._queue.popleft()
                self._inflight += 1
            try:
                self._post(path, payload, weight)
            finally:
                with self._lock:
                    self._inflight -= 1

    def _post(self, path: str, payload: dict, weight: int) -> None:
        import urllib.request

        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.endpoint + path, data=body, method="POST")
        req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                if 200 <= resp.status < 300:
                    return
        except Exception:  # noqa: BLE001 — the engine never feels a
            pass  # collector outage; the drop counter is the signal
        from trino_tpu.obs import metrics as M

        M.OTLP_DROPPED.inc(weight, "send-error")

    # ------------------------------------------------------------- admin
    def pending(self) -> int:
        with self._lock:
            return len(self._queue) + self._inflight

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until the queue fully drains (tests/shutdown only)."""
        self._wake.set()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.pending() == 0:
                return True
            self._wake.set()
            time.sleep(0.01)
        return self.pending() == 0

    def shutdown(self, timeout: float = 5.0) -> None:
        self.flush(timeout)
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)


class StubCollector:
    """In-process OTLP/HTTP collector for tests and local development:
    accepts ``POST /v1/traces`` + ``POST /v1/metrics`` and stores the
    parsed payloads. Point ``TRINO_TPU_OTLP_ENDPOINT`` at
    ``collector.endpoint``."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        collector = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(n)
                try:
                    payload = json.loads(body)
                except ValueError:
                    self.send_response(400)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                with collector._lock:
                    if self.path == "/v1/traces":
                        collector.trace_payloads.append(payload)
                    elif self.path == "/v1/metrics":
                        collector.metric_payloads.append(payload)
                    else:
                        collector.other_payloads.append((self.path, payload))
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

        self._lock = threading.Lock()
        self.trace_payloads: List[dict] = []
        self.metric_payloads: List[dict] = []
        self.other_payloads: List[tuple] = []
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.endpoint = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)

    def start(self) -> "StubCollector":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    def spans(self) -> List[dict]:
        """Every received span, flattened, with its resource attributes
        attached as ``_resource`` (dict) for assertions."""
        out: List[dict] = []
        with self._lock:
            payloads = list(self.trace_payloads)
        for payload in payloads:
            for rs in payload.get("resourceSpans", ()):
                resource = {
                    a["key"]: next(iter(a["value"].values()))
                    for a in rs.get("resource", {}).get("attributes", ())}
                for ss in rs.get("scopeSpans", ()):
                    for sp in ss.get("spans", ()):
                        rec = dict(sp)
                        rec["_resource"] = resource
                        out.append(rec)
        return out

    def wait_for_spans(self, count: int, timeout: float = 10.0) -> List[dict]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            spans = self.spans()
            if len(spans) >= count:
                return spans
            time.sleep(0.02)
        return self.spans()
