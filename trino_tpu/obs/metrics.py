"""Typed metrics registry + Prometheus text exposition.

Reference role: airlift's ``@Managed`` counters exported through the
JMX-to-/metrics bridge (``trino-jmx`` + MetricsResource), replaced by an
explicit registry: every metric is DECLARED once, module-level, with a
type, help string, and label names — so the exporter, the docs checker
(``tools/check_metric_docs.py``), and the endpoint all read from one source
of truth and ad-hoc string rendering can't drift.

Three instrument types (the Prometheus core set the engine needs):

- ``Counter`` — monotonically increasing totals (bytes exchanged, retries);
- ``Gauge`` — point-in-time values (queries by state, worker count, uptime);
- ``Histogram`` — fixed-bucket latency distributions with ``_bucket`` /
  ``_sum`` / ``_count`` series (per-state query wall time).

The registry is process-global (``REGISTRY``): coordinator and worker are
separate processes, so each exports its own totals, exactly like the
reference's per-node JMX. Server-derived gauges are refreshed from the
owning server immediately before rendering, under ``RENDER_LOCK``
(server/events.render_metrics), and cleared afterwards so a same-process
worker render never re-exports another server's values.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

# fixed latency buckets (seconds) — chosen to straddle the engine's range:
# sub-10ms metadata statements through multi-minute sf100 scans
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, double-quote, and
    newline must be escaped inside label values (exposition format spec)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _series(name: str, labels: Dict[str, str], value) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{escape_label_value(v)}"' for k, v in labels.items())
        return f"{name}{{{inner}}} {_format_value(value)}"
    return f"{name} {_format_value(value)}"


class Metric:
    """Shared shape: name, help, label names, thread-safe child map keyed
    by label values."""

    type_name = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def _labelkey(self, labelvalues: Sequence[str]) -> Tuple[str, ...]:
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {labelvalues!r}")
        return tuple(str(v) for v in labelvalues)

    def clear(self) -> None:
        with self._lock:
            self._children.clear()

    # -- rendering ---------------------------------------------------------
    def header(self) -> List[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.type_name}"]

    def samples(self) -> List[tuple]:
        """Every touched series as ``(sample_name, labels_dict, value)``
        — the one expansion both the text rendering and the row view
        (``registry_samples``) consume, so they cannot diverge."""
        with self._lock:
            children = dict(self._children)
        return [(self.name, dict(zip(self.labelnames, key)), float(v))
                for key, v in sorted(children.items())]

    def render(self) -> List[str]:
        raise NotImplementedError


class Counter(Metric):
    type_name = "counter"

    def inc(self, amount: float = 1, *labelvalues) -> None:
        key = self._labelkey(labelvalues)
        with self._lock:
            self._children[key] = self._children.get(key, 0) + amount

    def value(self, *labelvalues) -> float:
        with self._lock:
            return self._children.get(self._labelkey(labelvalues), 0)

    def render(self) -> List[str]:
        return _render_flat(self)


class Gauge(Metric):
    type_name = "gauge"

    def set(self, value: float, *labelvalues) -> None:
        with self._lock:
            self._children[self._labelkey(labelvalues)] = value

    def inc(self, amount: float = 1, *labelvalues) -> None:
        key = self._labelkey(labelvalues)
        with self._lock:
            self._children[key] = self._children.get(key, 0) + amount

    def value(self, *labelvalues) -> float:
        with self._lock:
            return self._children.get(self._labelkey(labelvalues), 0)

    def render(self) -> List[str]:
        return _render_flat(self)


def _render_flat(metric: Metric) -> List[str]:
    """Counter/Gauge rendering: header always (the name is declared), a
    series per touched label set. Never-touched metrics emit NO series —
    a worker must not export the coordinator-derived gauges pinned at 0
    (which would read as 'this node has 0 uptime / 0 workers' on
    per-instance dashboards)."""
    lines = metric.header()
    for name, labels, v in metric.samples():
        lines.append(_series(name, labels, v))
    return lines


class Histogram(Metric):
    """Cumulative fixed-bucket histogram (``le`` buckets + sum + count)."""

    type_name = "histogram"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, *labelvalues) -> None:
        key = self._labelkey(labelvalues)
        with self._lock:
            counts, total, n = self._children.get(
                key, ([0] * len(self.buckets), 0.0, 0))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._children[key] = (counts, total + value, n + 1)

    def snapshot(self, *labelvalues):
        """(bucket_counts, sum, count) for one label set (tests/listeners)."""
        with self._lock:
            counts, total, n = self._children.get(
                self._labelkey(labelvalues), ([0] * len(self.buckets), 0.0, 0))
            return list(counts), total, n

    def samples(self) -> List[tuple]:
        """Prometheus histogram expansion: one ``_bucket`` sample per
        ``le`` bound (cumulative, +Inf = observation count) plus ``_sum``
        and ``_count`` per label set."""
        with self._lock:
            children = {k: (list(c), t, n)
                        for k, (c, t, n) in self._children.items()}
        out: List[tuple] = []
        for key, (counts, total, n) in sorted(children.items()):
            base = dict(zip(self.labelnames, key))
            for b, c in zip(self.buckets, counts):
                out.append((f"{self.name}_bucket",
                            {**base, "le": _format_value(b)}, float(c)))
            out.append((f"{self.name}_bucket", {**base, "le": "+Inf"},
                        float(n)))
            out.append((f"{self.name}_sum", dict(base), float(total)))
            out.append((f"{self.name}_count", dict(base), float(n)))
        return out

    def render(self) -> List[str]:
        lines = self.header()
        for name, labels, v in self.samples():
            lines.append(_series(name, labels, v))
        return lines


# serializes refresh+render across ALL renderers in the process — the
# coordinator's gauge refresh (server/events.render_metrics) and any direct
# render_registry() caller (worker /v1/metrics) — so no scrape can observe
# a half-refreshed gauge. Reentrant: render_metrics holds it around its
# refresh window while calling render_registry.
RENDER_LOCK = threading.RLock()


class MetricsRegistry:
    """Ordered collection of declared metrics; renders the whole process's
    exposition page."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _register(self, metric: Metric) -> Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing  # module re-imports keep the same instance
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help, labelnames=()) -> Counter:
        return self._register(Counter(name, help, labelnames))

    def gauge(self, name, help, labelnames=()) -> Gauge:
        return self._register(Gauge(name, help, labelnames))

    def histogram(self, name, help, labelnames=(),
                  buckets=LATENCY_BUCKETS_S) -> Histogram:
        return self._register(Histogram(name, help, labelnames, buckets))

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._metrics)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        with RENDER_LOCK:
            lines: List[str] = []
            for m in metrics:
                lines.extend(m.render())
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()

# ----------------------------------------------------------- engine metrics
# Declared here (not at use sites) so every exported name is statically
# discoverable: tools/check_metric_docs.py imports this module and compares
# REGISTRY.names() against the README table.

# coordinator state gauges (refreshed per render via collect callbacks —
# see server/events.render_metrics). Names are byte-compatible with the
# seed's hand-rolled renderer.
QUERIES = REGISTRY.gauge(
    "trino_tpu_queries", "tracked queries by lifecycle state", ("state",))
QUERIES_TOTAL = REGISTRY.counter(
    "trino_tpu_queries_total", "queries submitted since server start")
RESULT_ROWS = REGISTRY.gauge(
    "trino_tpu_result_rows", "result rows held by FINISHED tracked queries")
WORKERS = REGISTRY.gauge(
    "trino_tpu_workers", "alive workers in the discovery registry")
UPTIME_SECONDS = REGISTRY.gauge(
    "trino_tpu_uptime_seconds", "seconds since server start")

# engine counters (process-global, incremented at the instrumented sites)
EXCHANGE_BYTES = REGISTRY.counter(
    "trino_tpu_exchange_bytes_total",
    "serialized page bytes pulled from upstream task buffers")
EXCHANGE_REQUESTS = REGISTRY.counter(
    "trino_tpu_exchange_requests_total",
    "exchange pull HTTP requests issued")
EXCHANGE_RETRIES = REGISTRY.counter(
    "trino_tpu_exchange_retries_total",
    "exchange pull attempts retried after transient failures")
SPOOL_READS = REGISTRY.counter(
    "trino_tpu_spool_reads_total",
    "task outputs served from the durable spool instead of a live buffer")
SPOOL_BYTES = REGISTRY.counter(
    "trino_tpu_spool_bytes_total",
    "page bytes read from durable spool files (kept separate from "
    "exchange bytes, which count network pulls from live buffers)")
# page serde (data/serde.py): per-column wire bytes by codec —
# zlib (blocks that actually shrank), none (incompressible blocks stored
# raw), logical (uncompressed column-block bytes, the denominator of the
# realized compression ratio)
SERDE_BYTES = REGISTRY.counter(
    "trino_tpu_serde_bytes_total",
    "page serde column-block bytes by direction and codec (codec = zlib "
    "compressed-wire | none raw-stored | logical uncompressed input/"
    "output; ratio = (zlib + none) / logical)", ("direction", "codec"))
# spooled result protocol (server/segments.py): result segments written
# by workers/the coordinator, served to clients, and reclaimed by the
# ack/TTL/orphan lifecycle
RESULT_SEGMENTS_WRITTEN = REGISTRY.counter(
    "trino_tpu_result_segments_written_total",
    "spooled result segments written to this process's segment store")
RESULT_SEGMENT_BYTES = REGISTRY.counter(
    "trino_tpu_result_segment_bytes_total",
    "spooled result segment bytes by direction (written = rolled into "
    "the segment store; served = read out by segment GETs)",
    ("direction",))
RESULT_SEGMENTS_RECLAIMED = REGISTRY.counter(
    "trino_tpu_result_segments_reclaimed_total",
    "result segments deleted, by reason (ack = client fetched and acked; "
    "ttl = expired un-acked, including failed queries' early drops; "
    "orphan = stale files swept at server start)", ("reason",))
RESULT_SEGMENT_RECLAIMED_BYTES = REGISTRY.counter(
    "trino_tpu_result_segment_reclaimed_bytes_total",
    "bytes reclaimed by result-segment deletion, by reason "
    "(ack | ttl | orphan)", ("reason",))
SPOOLED_RESULT_QUERIES = REGISTRY.counter(
    "trino_tpu_spooled_result_queries_total",
    "queries whose results were served as a spooled segment manifest, by "
    "mode (worker-direct = root-fragment producers wrote the segments "
    "and the coordinator never touched the data; coordinator = the "
    "coordinator spooled from its own segment store)", ("mode",))
INLINE_RESULT_REJECTIONS = REGISTRY.counter(
    "trino_tpu_inline_result_rejections_total",
    "queries failed by the inline-result memory guard "
    "(inline_result_max_bytes exceeded with spooled results disabled — "
    "the coordinator refuses to materialize, instead of OOMing the "
    "dispatch plane)")
COMPILE_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_compile_cache_hits_total",
    "compiled-query runs reusing an already-built XLA executable")
COMPILE_CACHE_MISSES = REGISTRY.counter(
    "trino_tpu_compile_cache_misses_total",
    "compiled-query runs that traced+compiled (first run or capacity "
    "regrowth)")
COMPILE_SECONDS = REGISTRY.counter(
    "trino_tpu_compile_seconds_total",
    "wall seconds of compiled-query runs that traced+compiled (kept out "
    "of device seconds so one-time compiles don't skew throughput math)")
STAGING_SECONDS = REGISTRY.counter(
    "trino_tpu_staging_seconds_total",
    "host-side staging seconds charged to queries: the compiled tier "
    "charges dynamic-filter resolution + host domain application "
    "(bench's staging_df_s — the host work a run repeats without the "
    "device cache; CUMULATIVE across scan threads under the pipelined "
    "fan-out, so it can exceed the staging wall); the worker tier "
    "charges the per-split scan+assemble wall of FRESH stagings "
    "(device-cache hits charge nothing)")
DEVICE_SECONDS = REGISTRY.counter(
    "trino_tpu_device_seconds_total",
    "device execution wall seconds (fragment bodies / compiled runs)")
STAGED_ROWS = REGISTRY.counter(
    "trino_tpu_staged_rows_total", "rows staged from connectors into pages")
TASKS_TOTAL = REGISTRY.counter(
    "trino_tpu_tasks_total", "tasks created on this node")

# per-operator-kind rollups, fed from each task's accumulated OperatorStats
# at task completion (server/task.py) — the per-kernel attribution a
# serving stack needs ("which operator ate the rows/ms on this node")
OPERATOR_WALL_SECONDS = REGISTRY.histogram(
    "trino_tpu_operator_wall_seconds",
    "per-task operator wall time by operator kind, observed at task "
    "completion", ("operator",))
OPERATOR_ROWS = REGISTRY.counter(
    "trino_tpu_operator_rows_total",
    "rows output by operator kind, accumulated at task completion",
    ("operator",))

# device profiler (obs/devprofiler.py): per-operator launch + dispatch
# overhead counters bumped at query fold time (never per-dispatch), and
# the tiered compile-seconds histogram fed by every compile event
KERNEL_LAUNCHES = REGISTRY.counter(
    "trino_tpu_kernel_launches_total",
    "device dispatches by operator kind, folded from the kernel ledger "
    "at query completion", ("operator",))
KERNEL_DISPATCH_OVERHEAD = REGISTRY.counter(
    "trino_tpu_kernel_dispatch_overhead_seconds",
    "per-operator wall minus device seconds (host dispatch overhead — "
    "the number fragment megakernels must beat), folded from the kernel "
    "ledger at query completion", ("operator",))
COMPILE_SECONDS_TIERED = REGISTRY.histogram(
    "trino_tpu_compile_seconds",
    "per-event jit/Pallas compile seconds by execution tier and "
    "compile-cache outcome (hit events observe ~0)", ("tier", "cache"))

# query caching subsystem (trino_tpu/cache/): coordinator result cache,
# logical-plan cache, and the connector-side datagen cache
RESULT_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_result_cache_hits_total",
    "queries answered from the coordinator result cache (including "
    "single-flight followers served by a concurrent leader)")
RESULT_CACHE_MISSES = REGISTRY.counter(
    "trino_tpu_result_cache_misses_total",
    "cache-eligible queries that executed and (re)filled the result cache")
RESULT_CACHE_BYPASSES = REGISTRY.counter(
    "trino_tpu_result_cache_bypasses_total",
    "cache-enabled queries that bypassed the result cache (DML/DDL, "
    "non-deterministic functions, table functions, unversioned tables)")
RESULT_CACHE_EVICTIONS = REGISTRY.counter(
    "trino_tpu_result_cache_evictions_total",
    "result-cache entries evicted by the LRU byte budget")
RESULT_CACHE_BYTES = REGISTRY.gauge(
    "trino_tpu_result_cache_bytes",
    "estimated bytes of result pages held by the coordinator result cache")
RESULT_CACHE_SINGLE_FLIGHT_WAITS = REGISTRY.counter(
    "trino_tpu_result_cache_single_flight_waits_total",
    "queries that parked on a concurrent identical query's in-flight "
    "execution instead of executing themselves")
PLAN_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_plan_cache_hits_total",
    "queries that reused a cached optimized logical plan (skipping "
    "parse/analyze/plan/optimize)")
PLAN_CACHE_MISSES = REGISTRY.counter(
    "trino_tpu_plan_cache_misses_total",
    "plan-cache lookups that planned from scratch (first sight, changed "
    "session properties, or a data-version mismatch)")
# materialized views (trino_tpu/matview/): the transparent planner
# substitution pass and the REFRESH swap
MV_SUBSTITUTIONS = REGISTRY.counter(
    "trino_tpu_mv_substitutions_total",
    "materialized-view substitution decisions by the planner pass "
    "(result = substituted | stale | access-denied | invalid): "
    "'substituted' rewrote a matched plan subtree into a storage-table "
    "scan; every other result fell back to the base plan", ("result",))
MV_REFRESH_SECONDS = REGISTRY.histogram(
    "trino_tpu_mv_refresh_seconds",
    "REFRESH MATERIALIZED VIEW wall time: plan + execute the definition "
    "+ atomic storage swap (+ the optional device-cache warm staging)")
GENCACHE_HITS = REGISTRY.counter(
    "trino_tpu_gencache_hits_total",
    "generator scan ranges served entirely from the datagen cache")
GENCACHE_MISSES = REGISTRY.counter(
    "trino_tpu_gencache_misses_total",
    "generator scan ranges that synthesized at least one column")
GENCACHE_EVICTIONS = REGISTRY.counter(
    "trino_tpu_gencache_evictions_total",
    "datagen cache entries evicted by the LRU byte budget")

# device table cache (trino_tpu/devcache/): warm-HBM buffer pool of staged
# scan artifacts, keyed by connector data_version — the repeat-traffic
# staging killer. Evictions count LRU budget pressure, revocable-tier
# yields to running queries, AND stale-version drops after DML.
DEVICE_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_device_cache_hits_total",
    "table stagings served from the device cache (including single-flight "
    "followers served by a concurrent leader's transfer)")
DEVICE_CACHE_MISSES = REGISTRY.counter(
    "trino_tpu_device_cache_misses_total",
    "cache-eligible table stagings that transferred host pages to device "
    "and (budget permitting) filled the cache")
DEVICE_CACHE_EVICTIONS = REGISTRY.counter(
    "trino_tpu_device_cache_evictions_total",
    "device-cache entries dropped (LRU byte budget, revocable-tier yield "
    "to a running query, or a stale data_version after DML)")
DEVICE_CACHE_BYTES = REGISTRY.gauge(
    "trino_tpu_device_cache_bytes",
    "device bytes held by the warm-HBM table cache (the revocable tier)")
DEVICE_CACHE_BUILD_HITS = REGISTRY.counter(
    "trino_tpu_device_cache_build_hits_total",
    "joins served a SORTED build-side artifact from the device cache (the "
    "warm repeated join skipped the build sort entirely; these also count "
    "in the general device-cache hit counter — the artifacts share the "
    "revocable-tier pool and byte budget)")
# host-RAM columnar page cache (trino_tpu/devcache/hostcache.py): the
# staging tier UNDER the warm-HBM pool — decoded per-split numpy column
# sets keyed by the same data_version signature, so an HBM eviction or a
# re-shard refills from host memory (transfer only) instead of re-running
# the connector scan and decode
HOST_CACHE_HITS = REGISTRY.counter(
    "trino_tpu_host_cache_hits_total",
    "split stagings served decoded columns from the host-RAM page cache "
    "(including single-flight followers served by a concurrent leader's "
    "scan) — the staging pipeline skipped the connector scan and decode")
HOST_CACHE_MISSES = REGISTRY.counter(
    "trino_tpu_host_cache_misses_total",
    "cache-eligible split stagings that ran the connector scan+decode and "
    "(budget permitting) filled the host-RAM page cache")
HOST_CACHE_EVICTIONS = REGISTRY.counter(
    "trino_tpu_host_cache_evictions_total",
    "host-cache entries dropped (LRU byte budget, revocable-tier shed, or "
    "a stale data_version after DML)")
HOST_CACHE_BYTES = REGISTRY.gauge(
    "trino_tpu_host_cache_bytes",
    "host RAM held by the columnar page cache (the second revocable tier "
    "— sheds before the warm-HBM tier under node pressure)")
# pipelined staging sub-phases (trino_tpu/exec/staging.py): the cold
# scan->decode->transfer path decomposed, so the trajectory can say WHICH
# stage of staging ate the wall. staging_seconds_total keeps its exact
# per-tier charging semantics (bench's staging_df_s identity); this
# counter is the finer-grained decomposition beside it.
STAGING_PHASE_SECONDS = REGISTRY.counter(
    "trino_tpu_staging_phase_seconds_total",
    "staging pipeline wall seconds by sub-phase: scan (parallel split "
    "read+decode fan-out), decode (host assembly: concat + dictionary "
    "merge + physical narrowing), transfer (double-buffered host->device "
    "blocks), host-cache (host-tier probe)", ("phase",))
# fused sort-merge join tier (ops/fused_join.py): kernel selections per
# join execution, labeled by the tier the cost gate chose
FUSED_JOIN_SELECTIONS = REGISTRY.counter(
    "trino_tpu_fused_join_selections_total",
    "join kernel selections by the fused-tier cost gate (tier = dense | "
    "fused | merge-sorted | merge-pallas | legacy); in the compiled/SPMD "
    "tiers a selection is counted per program TRACE, not per cached-"
    "executable run", ("tier",))
# overlapped ICI exchange (parallel/exchange.py): double-buffered send
# blocks pipelined against join compute in the SPMD tier
EXCHANGE_OVERLAPPED = REGISTRY.counter(
    "trino_tpu_exchange_overlapped_total",
    "probe-side exchanges compiled as double-buffered send-block pipelines "
    "(all-to-all of block k+1 overlapped with join compute on block k); "
    "counted per program trace, not per run", ("blocks",))

# adaptive execution (trino_tpu/adaptive/): runtime re-planning from the
# operator-stats spine, recorded per applied rule at the stage boundary
ADAPTIVE_ADAPTATIONS = REGISTRY.counter(
    "trino_tpu_adaptive_adaptations_total",
    "plan changes applied by the adaptive re-planner at stage boundaries",
    ("rule",))
ADAPTIVE_JOIN_FLIPS = REGISTRY.counter(
    "trino_tpu_adaptive_join_flips_total",
    "join-distribution switches (actual build rows contradicted the "
    "estimate across join_max_broadcast_rows)", ("direction",))
ADAPTIVE_RESEEDED_SOURCES = REGISTRY.counter(
    "trino_tpu_adaptive_reseeded_sources_total",
    "exchange sources stamped with actual upstream stage rows before "
    "their consumer fragment scheduled")
ADAPTIVE_SKEW_HOT_PARTITIONS = REGISTRY.counter(
    "trino_tpu_adaptive_skew_hot_partitions_total",
    "hot partitions salted by the adaptive skew mitigation (spread on "
    "the probe producer, replicated on the build producer)")

# serving fast path (server/prepared.py + server/fastpath.py): the
# high-QPS control-plane surface — prepared statements held by the
# coordinator registry, per-path execution counts, and EXECUTE bind time
# (the entire per-request planning cost once the parameterized plan is
# cached)
PREPARED_STATEMENTS = REGISTRY.gauge(
    "trino_tpu_prepared_statements",
    "prepared statements held by the coordinator registry (all users)")
FAST_PATH_QUERIES = REGISTRY.counter(
    "trino_tpu_fast_path_queries_total",
    "SELECT executions by control-plane path (fast-path = single-stage "
    "plan run coordinator-local, skipping task round-trips; distributed = "
    "fragment/schedule/execute across workers; local-catalog = forced "
    "coordinator-local by a process-local catalog)", ("path",))
EXECUTE_BIND_SECONDS = REGISTRY.histogram(
    "trino_tpu_execute_bind_seconds",
    "EXECUTE parameter bind time: constant-folding the USING expressions "
    "+ substituting them into the cached parameterized plan")

# dispatch plane / executor plane split (server/dispatch.py): the bounded
# dispatch queue between the HTTP front and the executor lanes, typed
# overload rejections, lane occupancy, and which plane ran each query
DISPATCH_QUEUE_DEPTH = REGISTRY.gauge(
    "trino_tpu_dispatch_queue_depth",
    "queries waiting in the bounded dispatch queue (between the HTTP "
    "front and the executor lanes)")
DISPATCH_REJECTED = REGISTRY.counter(
    "trino_tpu_dispatch_rejected_total",
    "statements rejected by the dispatch plane with the typed 429 + "
    "Retry-After overload response (reason = queue-full)", ("reason",))
DISPATCH_CACHE_SERVED = REGISTRY.counter(
    "trino_tpu_dispatch_cache_served_total",
    "queries answered entirely on the dispatch plane by the serving "
    "index (result-cache hit revalidated against connector data "
    "versions — no executor lane, no queue slot, no planning)")
EXECUTOR_LANES_BUSY = REGISTRY.gauge(
    "trino_tpu_executor_lanes_busy",
    "executor lanes currently running a query (the fixed lane pool "
    "replaced per-query thread creation)")
EXECUTOR_PLANE_QUERIES = REGISTRY.counter(
    "trino_tpu_executor_plane_queries_total",
    "dequeued queries by executing plane (inline = a dispatch-side "
    "executor lane; process = forwarded to an executor process; "
    "bounced = an executor process declined ownership and the query "
    "re-ran inline)", ("plane",))

# resource groups (server/resource_groups.py): hierarchical multi-tenant
# admission — per-group queue depth/occupancy gauges, queued-phase wait
# histogram, typed per-group rejections (queue-full = max_queued or
# global capacity at submit; queue-timeout = aged out of the group queue
# past queue_timeout_ms), and concurrency-free serving-index hits
# attributed to the group
RESOURCE_GROUP_QUEUED = REGISTRY.gauge(
    "trino_tpu_resource_group_queued",
    "queries parked in one resource group's queue", ("group",))
RESOURCE_GROUP_RUNNING = REGISTRY.gauge(
    "trino_tpu_resource_group_running",
    "queries running under one resource group (subtree rollup)",
    ("group",))
RESOURCE_GROUP_QUEUE_SECONDS = REGISTRY.histogram(
    "trino_tpu_resource_group_queue_seconds",
    "time a query waited in its resource group's queue before the "
    "weighted-fair drain admitted (or aged) it", ("group",))
RESOURCE_GROUP_REJECTED = REGISTRY.counter(
    "trino_tpu_resource_group_rejected_total",
    "queries a resource group said no to, by reason (queue-full = typed "
    "429 at submit; queue-timeout = typed EXCEEDED_QUEUE_TIMEOUT "
    "failure after aging out of the group queue)", ("group", "reason"))
RESOURCE_GROUP_SERVED = REGISTRY.counter(
    "trino_tpu_resource_group_served_total",
    "serving-index hits attributed to a resource group "
    "(concurrency-free: answered on the dispatch thread without "
    "occupying a group slot, counted so cached repeats stay auditable)",
    ("group",))

# HTTP keep-alive connection pool (server/wire.py): control-plane and
# client calls reuse pooled connections instead of a fresh TCP connect
# per request
HTTP_CONNECTIONS_OPENED = REGISTRY.counter(
    "trino_tpu_http_connections_opened_total",
    "fresh TCP connections opened by the keep-alive HTTP client pool")
HTTP_CONNECTION_REUSES = REGISTRY.counter(
    "trino_tpu_http_connection_reuses_total",
    "HTTP requests served over a pooled keep-alive connection (no TCP "
    "connect paid)")

# plan-IR sanity checking (sql/planner/sanity.py): invariant violations
# caught at plan time, labeled by the phase family that produced the bad
# plan (initial-plan | optimizer | fragmentation | adaptive). During
# adaptive re-planning a failure is CONTAINED (the pre-adaptation plan is
# kept, the query never fails), so this counter is the only loud signal.
PLAN_VALIDATION_FAILURES = REGISTRY.counter(
    "trino_tpu_plan_validation_failures_total",
    "plan invariant violations raised by the plan-IR sanity checker",
    ("phase",))

# latency distribution per terminal state (the per-state query histogram)
QUERY_SECONDS = REGISTRY.histogram(
    "trino_tpu_query_seconds",
    "query wall time by terminal state", ("state",))

# the query phase ledger (obs/timeline.py): exclusive wall per phase,
# observed once per terminal query for EVERY phase (zeros included) so
# bucket counts align across phases — the queued series is the
# queue-time histogram multi-tenant workload management reads, and the
# per-phase p99s are where a flat-p99 serving claim gets its attribution
QUERY_PHASE_SECONDS = REGISTRY.histogram(
    "trino_tpu_query_phase_seconds",
    "exclusive query wall seconds attributed to each phase by the "
    "completion-time phase ledger (queued | dispatch-queue | dispatch | "
    "parse-analyze | plan-optimize | prepare-bind | schedule | "
    "device-staging | device-execute | exchange-wait | "
    "result-serialization | segment-fetch | client-drain | "
    "unattributed)", ("phase",))

# tracing self-protection (obs/trace.py): per-tracer span cap — a
# pathological query stops RECORDING at the cap instead of growing
# coordinator/worker memory without bound
SPANS_DROPPED = REGISTRY.counter(
    "trino_tpu_spans_dropped_total",
    "spans dropped by the per-tracer span cap "
    "(TRINO_TPU_TRACE_MAX_SPANS, default 4096)")

# OTLP export (obs/otlp.py): the background batch exporter never blocks
# the query path — overflow of its bounded queue and failed sends DROP,
# counted here by reason
OTLP_DROPPED = REGISTRY.counter(
    "trino_tpu_otlp_dropped_total",
    "OTLP export spans/metric batches dropped instead of blocking "
    "(reason = overflow: bounded queue full; send-error: collector "
    "unreachable or non-2xx)", ("reason",))


# system catalog (trino_tpu/connector/system/): coordinator query-history
# ring occupancy + ring evictions (reference: QueryTracker's
# query.max-history expiry)
QUERY_HISTORY_SIZE = REGISTRY.gauge(
    "trino_tpu_query_history_size",
    "completed-query records held by the coordinator history ring "
    "(system.runtime.queries coverage of finished queries)")
QUERY_HISTORY_EVICTIONS = REGISTRY.counter(
    "trino_tpu_query_history_evictions_total",
    "completed-query records evicted from the coordinator history ring "
    "(query_max_history / query_min_expire_age_ms retention)")


# process self-metrics: the "host sick vs engine slow" discriminators
# (RSS, FDs, threads, GC) — refreshed immediately before every render so
# both coordinator and worker /v1/metrics (and system.metrics) carry a
# current reading without a background sampler thread
PROCESS_RSS_BYTES = REGISTRY.gauge(
    "trino_tpu_process_rss_bytes",
    "resident set size of this server process (VmRSS)")
PROCESS_OPEN_FDS = REGISTRY.gauge(
    "trino_tpu_process_open_fds",
    "open file descriptors held by this server process")
PROCESS_THREADS = REGISTRY.gauge(
    "trino_tpu_process_threads",
    "live Python threads in this server process")
PROCESS_GC_COLLECTIONS = REGISTRY.gauge(
    "trino_tpu_process_gc_collections",
    "Python GC collections per generation since process start "
    "(point-in-time read of gc.get_stats)", ("generation",))


# fixed byte buckets for memory-size histograms: 64KiB..64GiB in powers
# of four — straddles tiny test pages through sf100 working sets
MEMORY_BUCKETS_BYTES: Tuple[float, ...] = (
    64 << 10, 256 << 10, 1 << 20, 4 << 20, 16 << 20, 64 << 20, 256 << 20,
    1 << 30, 4 << 30, 16 << 30, 64 << 30)

# cluster memory ledger (obs/memledger.py): per-pool occupancy sampled on
# the worker announce loop, pressure-shed events by reclaiming action,
# and the per-query peak distribution observed at query completion
MEMORY_POOL_BYTES = REGISTRY.gauge(
    "trino_tpu_memory_pool_bytes",
    "live memory-pool occupancy by pool and node, sampled on the worker "
    "announce loop (device = query reservations + warm-HBM cache [+ "
    "staging scratch]; host = host-RAM page cache [+ other tracked host "
    "owners])", ("pool", "node"))
MEMORY_PRESSURE_EVENTS = REGISTRY.counter(
    "trino_tpu_memory_pressure_events_total",
    "revocable-tier pressure sheds by reclaiming action (spill = a "
    "query's pre-spill cache yield; pool-overflow = device pool over its "
    "limit; host-pressure = process RSS over the node limit; "
    "rss-escalation = host pressure escalated into host-backed device "
    "entries; yield = direct cache yields)", ("action",))
QUERY_PEAK_MEMORY_BYTES = REGISTRY.histogram(
    "trino_tpu_query_peak_memory_bytes",
    "per-query peak device-pool bytes (max over tasks/stages), observed "
    "once per terminal query", ("state",),
    buckets=MEMORY_BUCKETS_BYTES)

# data-plane flow ledger (obs/flowledger.py): every cross-boundary byte
# typed by link class, the producers' backpressure stalls, and the
# straggler detector's terminal-query verdicts
TRANSFER_BYTES = REGISTRY.counter(
    "trino_tpu_transfer_bytes_total",
    "bytes moved across a data-plane link, by link class (exchange-pull "
    "| spool-write | segment-fetch | staging-transfer | client-drain | "
    "control) and direction (send | recv, from this process's "
    "viewpoint)", ("link", "direction"))
TRANSFER_SECONDS = REGISTRY.counter(
    "trino_tpu_transfer_seconds",
    "wall seconds spent moving bytes on a data-plane link (cumulative "
    "across concurrent transfers, so bytes/seconds is the per-stream "
    "effective rate, not the aggregate)", ("link",))
BACKPRESSURE_STALL_SECONDS = REGISTRY.counter(
    "trino_tpu_backpressure_stall_seconds_total",
    "seconds producers spent blocked on full output buffers plus "
    "consumers spent on empty exchange polls, by stage", ("stage",))
STRAGGLER_TASKS = REGISTRY.counter(
    "trino_tpu_straggler_tasks_total",
    "tasks flagged by the straggler detector at query completion, by "
    "dominant cause (transfer-bound | device-bound | queue-bound)",
    ("cause",))


def current_rss_bytes():
    """This process's CURRENT resident set (VmRSS), or None where /proc
    is unavailable — callers needing a live pressure signal (the worker
    host-RAM shed) must treat None as "unknown", never as 0 (the gauge
    fallback below reports the lifetime PEAK, which would latch any
    threshold forever)."""
    try:
        with open("/proc/self/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def refresh_process_gauges() -> None:
    """Sample the process self-metrics (Linux /proc where available,
    portable fallbacks otherwise); failures leave the previous reading."""
    import gc
    import threading as _threading

    rss = current_rss_bytes()
    if rss is not None:
        PROCESS_RSS_BYTES.set(rss)
    else:
        try:
            import resource
            import sys as _sys

            # ru_maxrss is the PEAK, in bytes on macOS and KiB elsewhere
            # (this branch only runs where /proc is absent) — coarse but
            # unit-correct fallback
            unit = 1 if _sys.platform == "darwin" else 1024
            PROCESS_RSS_BYTES.set(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * unit)
        except Exception:  # noqa: BLE001 — self-metrics are best-effort
            pass
    try:
        import os as _os

        PROCESS_OPEN_FDS.set(len(_os.listdir("/proc/self/fd")))
    except OSError:
        pass
    PROCESS_THREADS.set(_threading.active_count())
    for gen, st in enumerate(gc.get_stats()):
        PROCESS_GC_COLLECTIONS.set(int(st.get("collections", 0)), str(gen))


def render_registry() -> str:
    """The whole process's exposition page (worker /v1/metrics, and the
    body of the coordinator's after its gauges refresh)."""
    refresh_process_gauges()
    return REGISTRY.render()


def registry_samples() -> List[tuple]:
    """Every touched series as ``(name, type, labels_dict, value, help)``
    tuples — the row-shaped view of the exposition page that feeds the
    ``system.metrics`` table (the jmx-connector role). Built from the
    same per-metric ``samples()`` expansion the text rendering consumes,
    so the table cannot diverge from ``/v1/metrics``."""
    refresh_process_gauges()
    with REGISTRY._lock:
        metrics = list(REGISTRY._metrics.values())
    out: List[tuple] = []
    with RENDER_LOCK:
        for m in metrics:
            out.extend((name, m.type_name, labels, value, m.help)
                       for name, labels, value in m.samples())
    return out
