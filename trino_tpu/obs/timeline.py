"""Query phase ledger: exclusive wall-time attribution from the span tree.

Reference role: the latency breakdown the reference engine surfaces as
``QueryStats``'s queued/analysis/planning/execution durations (fed from
the otel spans ``io.opentelemetry.api.trace.Tracer`` records through
``QueuedStatementResource``/``DispatchManager``/``SqlTaskManager``) —
here computed ONCE at query completion from the merged coordinator +
worker span tree, so every millisecond of a query's wall is attributed
to exactly one phase, with the gaps surfaced as an explicit
``unattributed`` residual instead of silently vanishing.

The attribution is an interval sweep, not a span-duration sum: spans
overlap (worker tasks run in parallel with the coordinator's schedule
and root-fragment windows; exchange pullers overlap each other), so each
instant of the wall interval ``[created_at, ended_at]`` is assigned to
the highest-priority phase whose spans cover it. Priorities put the
specific over the general — a worker ``device/staging`` span wins over
the coordinator's enclosing ``schedule`` wait, an ``exchange/pull`` wins
over the root-fragment execute window it lives in — so the per-phase
sums are EXCLUSIVE and total at most the wall. ``client-drain`` (result
pages fetched after the query reached a terminal state) is reported
beside the ledger, never inside it: the wall the residual is measured
against ends at ``ended_at``.

Phases (the label set of ``trino_tpu_query_phase_seconds``)::

    queued                submit -> the query starts (admission wait
                          outside the dispatch queue: resource group +
                          cluster-memory gate)
    dispatch-queue        residency in the bounded dispatch queue
                          between the HTTP front and the executor lanes
                          (server/dispatch.py) — the queueing-time
                          attribution of the dispatcher/executor split
    dispatch              coordinator control-plane connective work:
                          session setup, statement probe, cache consult,
                          routing, state transitions (the root span's
                          exclusive remainder)
    parse-analyze         parse + analyze/plan spans
    plan-optimize         optimize + fragment + plan-cache + adaptation
    prepare-bind          EXECUTE parameter fold + plan substitution
    schedule              task creation + phased-execution build waits
    device-staging        host->device transfers (any process)
    device-execute        device compute + compile (any process)
    exchange-wait         exchange pulls / spool reads
    result-serialization  result page -> row materialization (inline) or
                          result segment encode/spool (spooled protocol)
    segment-fetch         post-terminal spooled-segment fetches + acks
                          (outside the wall, beside client-drain)
    client-drain          post-terminal result fetches (outside the wall)
    unattributed          wall not covered by any span (the visible gap)
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

# ledger phases in display order; segment-fetch, client-drain, and
# unattributed are synthesized, everything else is swept from spans
PHASES: Tuple[str, ...] = (
    "queued", "dispatch-queue", "dispatch", "parse-analyze",
    "plan-optimize", "prepare-bind", "schedule", "device-staging",
    "device-execute", "exchange-wait", "result-serialization",
    "segment-fetch", "client-drain", "unattributed")

# phases synthesized OUTSIDE the wall interval: reported beside the
# ledger, excluded from in-wall sums and the coverage denominator
OUT_OF_WALL_PHASES: Tuple[str, ...] = (
    "segment-fetch", "client-drain", "unattributed")

# span name -> (sweep priority, phase). Lower priority wins where spans
# overlap: leaf work (staging/execute/exchange) beats the coordinator's
# enclosing schedule/execute windows, whose EXCLUSIVE remainder is what
# the ledger should charge them.
_P_RESULT = 0
_P_STAGING = 1
_P_DEVICE = 2
_P_EXCHANGE = 3
_P_BIND = 4
_P_PARSE = 5
_P_PLAN = 6
_P_DISPATCH = 7
_P_SCHEDULE = 8
_P_EXECUTE = 9       # execute-window remainder -> device-execute
_P_ROOT = 10         # root query span remainder -> dispatch
_P_QUEUE = 11        # dispatch-queue residency (before the root opens)
_P_SYNTH = 12        # synthesized queued segment

SPAN_PHASE: Dict[str, Tuple[int, str]] = {
    "parse": (_P_PARSE, "parse-analyze"),
    "analyze/plan": (_P_PARSE, "parse-analyze"),
    "optimize": (_P_PLAN, "plan-optimize"),
    "fragment": (_P_PLAN, "plan-optimize"),
    "plan-cache/hit": (_P_PLAN, "plan-optimize"),
    "plan/adapt": (_P_PLAN, "plan-optimize"),
    "cache/lookup": (_P_DISPATCH, "dispatch"),
    "stats/sweep": (_P_DISPATCH, "dispatch"),
    # the dispatcher/executor split (server/dispatch.py): queue
    # residency is its own phase; the serve/forward control work joins
    # the dispatch remainder
    "dispatch/queue": (_P_QUEUE, "dispatch-queue"),
    "dispatch/serve": (_P_DISPATCH, "dispatch"),
    # the forward window ENCLOSES the executor process's merged spans:
    # like the root span, only its exclusive remainder is dispatch
    "dispatch/forward": (_P_ROOT, "dispatch"),
    "prepare/bind": (_P_BIND, "prepare-bind"),
    "schedule": (_P_SCHEDULE, "schedule"),
    "device/staging": (_P_STAGING, "device-staging"),
    "device-cache/lookup": (_P_STAGING, "device-staging"),
    "staging/dynamic-filters": (_P_STAGING, "device-staging"),
    # the pipelined staging engine's sub-phases (exec/staging.py): same
    # priority and bucket as their enclosing device/staging window, so
    # the ledger's device-staging attribution is unchanged while the
    # span tree now says WHICH stage of staging ate the wall
    "staging/scan": (_P_STAGING, "device-staging"),
    "staging/decode": (_P_STAGING, "device-staging"),
    "staging/transfer": (_P_STAGING, "device-staging"),
    "staging/host-cache": (_P_STAGING, "device-staging"),
    "device/compile": (_P_DEVICE, "device-execute"),
    "device/execute": (_P_DEVICE, "device-execute"),
    "exchange/overlap": (_P_DEVICE, "device-execute"),
    # the memory ledger's spans (exec/memory.py): the budget check and
    # the pre-spill revocable-tier yield both happen INSIDE the executing
    # operator, so their wall charges to device-execute like the device
    # windows they interrupt
    "memory/reserve": (_P_DEVICE, "device-execute"),
    "memory/shed": (_P_DEVICE, "device-execute"),
    "exchange/pull": (_P_EXCHANGE, "exchange-wait"),
    "spool/read": (_P_EXCHANGE, "exchange-wait"),
    "result/serialize": (_P_RESULT, "result-serialization"),
    # spooled result protocol (server/segments.py): segment encode+write
    # is the spooled analog of result serialization; the coordinator's
    # collect window encloses the workers' own execute/write spans, so
    # like the other execute windows only its remainder is device time
    "result/spool": (_P_RESULT, "result-serialization"),
    "segment/write": (_P_RESULT, "result-serialization"),
    "segments/collect": (_P_EXECUTE, "device-execute"),
    # the execution windows: their exclusive remainder is device compute
    # on this process (root-fragment body, fast-path executor run)
    "execute/root-fragment": (_P_EXECUTE, "device-execute"),
    "execute/coordinator-local": (_P_EXECUTE, "device-execute"),
    "fastpath/execute": (_P_EXECUTE, "device-execute"),
}

_N_PRIORITIES = _P_SYNTH + 1


@dataclasses.dataclass
class QueryTimeline:
    """The computed ledger: per-phase exclusive seconds over one query's
    wall interval. ``coverage`` = attributed / wall (the >=95% acceptance
    signal); ``client_drain_s`` sits outside the wall."""

    wall_s: float
    phases: Dict[str, float]
    unattributed_s: float
    client_drain_s: float = 0.0
    # spooled result protocol: terminal -> last segment fetch/ack seen by
    # the coordinator (outside the wall, like client-drain)
    segment_fetch_s: float = 0.0

    @property
    def coverage(self) -> float:
        if self.wall_s <= 0:
            return 1.0
        return max(0.0, 1.0 - self.unattributed_s / self.wall_s)

    def to_dict(self) -> dict:
        phases = {p: round(self.phases.get(p, 0.0), 6)
                  for p in PHASES if p not in OUT_OF_WALL_PHASES}
        phases["segment-fetch"] = round(self.segment_fetch_s, 6)
        phases["client-drain"] = round(self.client_drain_s, 6)
        phases["unattributed"] = round(self.unattributed_s, 6)
        return {
            "wallS": round(self.wall_s, 6),
            "phases": phases,
            "unattributedS": round(self.unattributed_s, 6),
            "coverage": round(self.coverage, 4),
        }


def _segments(span_dicts: List[dict], t0: float, t1: float):
    """(start, end, priority, phase) segments clipped to the wall, plus
    the synthesized queued interval before the root ``query`` span (the
    coordinator's query thread) opens.

    The root span itself maps to ``dispatch`` at the LOWEST span
    priority: every instant inside it where no phase span is open, the
    coordinator thread was doing control-plane connective work on behalf
    of the query — session setup, the statement-kind probe, routing, state
    transitions, scheduler preemption between instrumented sections. Time
    OUTSIDE the span tree (pre-thread-start beyond the admission wait,
    post-lifecycle teardown, spans lost to the tracer cap) stays
    unattributed — the visible gap."""
    segs: List[Tuple[float, float, int, str]] = []
    root_start: Optional[float] = None
    for s in span_dicts:
        name = s.get("name")
        start = s.get("start")
        if start is None:
            continue
        mapped = ((_P_ROOT, "dispatch") if name == "query"
                  else SPAN_PHASE.get(name))
        if mapped is None:
            continue
        dur = s.get("durationS")
        end = t1 if dur is None else start + float(dur)
        if name == "query":
            root_start = start if root_start is None else min(root_start,
                                                              start)
        start, end = max(start, t0), min(end, t1)
        if end <= start:
            continue
        prio, phase = mapped
        segs.append((start, end, prio, phase))
    if root_start is not None and root_start > t0:
        # admission wait: submit -> the query thread's root span opens
        segs.append((t0, min(root_start, t1), _P_SYNTH, "queued"))
    if root_start is None and not segs:
        # no spans at all (failed before the query thread started): the
        # whole wall was queued
        segs.append((t0, t1, _P_SYNTH, "queued"))
    return segs


def compute_timeline(span_dicts: List[dict], created_at: float,
                     ended_at: float,
                     client_drain_s: float = 0.0) -> QueryTimeline:
    """Sweep the spans into the exclusive per-phase ledger.

    ``span_dicts`` is the merged export (coordinator tracer + worker task
    dumps — ``Span.to_dict`` records with wall-clock ``start`` and
    monotonic-measured ``durationS``); open spans are treated as running
    to ``ended_at``. The sweep walks the sorted boundary events keeping a
    live count per priority, so each elementary interval lands in exactly
    one phase and the per-phase sums can never exceed the wall."""
    t0, t1 = float(created_at), float(ended_at)
    phases: Dict[str, float] = {p: 0.0 for p in PHASES}
    wall = max(0.0, t1 - t0)
    if wall == 0.0:
        return QueryTimeline(0.0, phases, 0.0, client_drain_s)
    segs = _segments(span_dicts, t0, t1)
    # boundary events: (time, +1/-1, priority, phase)
    events: List[Tuple[float, int, int, str]] = []
    for start, end, prio, phase in segs:
        events.append((start, 1, prio, phase))
        events.append((end, -1, prio, phase))
    events.sort(key=lambda e: e[0])
    # live phase name per priority level: at each level the LAST-opened
    # phase wins (levels map 1:1 to phases except _P_SYNTH, where queued
    # and dispatch never overlap by construction)
    counts = [0] * _N_PRIORITIES
    live_phase: List[Optional[str]] = [None] * _N_PRIORITIES
    attributed = 0.0
    cursor = t0
    i = 0
    n = len(events)
    while i < n:
        t = events[i][0]
        if t > cursor:
            # charge [cursor, t) to the highest-priority live phase
            for prio in range(_N_PRIORITIES):
                if counts[prio] > 0:
                    span_len = t - cursor
                    phases[live_phase[prio]] += span_len
                    attributed += span_len
                    break
            cursor = t
        while i < n and events[i][0] == t:
            _, delta, prio, phase = events[i]
            counts[prio] += delta
            if delta > 0:
                live_phase[prio] = phase
            i += 1
    unattributed = max(0.0, wall - attributed)
    return QueryTimeline(wall, phases, unattributed, client_drain_s)


def observe_phases(timeline_dict: dict) -> None:
    """Feed one terminal query's ledger into the
    ``trino_tpu_query_phase_seconds{phase}`` histogram — EVERY phase
    observes (zeros included) so bucket counts align across phases and
    the queued series exists from the first completed query."""
    from trino_tpu.obs import metrics as M

    for phase in PHASES:
        M.QUERY_PHASE_SECONDS.observe(
            float(timeline_dict["phases"].get(phase, 0.0)), phase)


def summarize(timeline_dict: dict, min_fraction: float = 0.02,
              max_phases: int = 5) -> str:
    """One compact human line for the CLI summary / EXPLAIN ANALYZE
    header: the heaviest phases (>= ``min_fraction`` of wall, largest
    first) plus the coverage — e.g.
    ``device-execute 38ms · queued 2ms (96% attributed)``."""
    wall = float(timeline_dict.get("wallS") or 0.0)
    if wall <= 0:
        return ""
    entries = [(p, float(timeline_dict["phases"].get(p, 0.0)))
               for p in PHASES if p not in OUT_OF_WALL_PHASES]
    entries = [(p, s) for p, s in entries if s >= wall * min_fraction]
    entries.sort(key=lambda e: e[1], reverse=True)
    parts = [f"{p} {s * 1e3:.1f}ms" for p, s in entries[:max_phases]]
    cov = timeline_dict.get("coverage", 0.0)
    return f"{' · '.join(parts)} ({cov * 100:.0f}% attributed)"
