"""Query-lifecycle span tracer.

Reference: the OpenTelemetry wiring threaded through the reference engine —
``io.opentelemetry.api.trace.Tracer`` injected into
``QueuedStatementResource`` / ``DispatchManager`` / ``SqlTaskManager``, with
W3C ``traceparent`` propagation on internal HTTP so worker task spans parent
into the query's trace. Here the tracer is a small in-process recorder: one
``Tracer`` per query (coordinator side) or per task (worker side), spans are
plain records, and the coordinator assembles the cross-process tree on read
(``GET /v1/query/{id}/trace``) by merging worker span dumps.

Two usage surfaces:

- explicit: ``with tracer.span("schedule") as sp: ...`` — used where the
  owning component holds the tracer (coordinator lifecycle, task body);
- ambient: ``with span("optimize"): ...`` — used by layers that must not
  grow a tracer parameter (planner, compiled execution). Ambient spans
  attach to whatever tracer ``activate()``-d on this thread and no-op
  (recording nothing, at ~dict-lookup cost) when none is active, so
  instrumentation is safe on every path including bare-``Session`` use.
"""
from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Dict, List, Optional

_CURRENT: "contextvars.ContextVar" = contextvars.ContextVar(
    "trino_tpu_trace", default=None)

# W3C-style trace context header stamped on internal HTTP (task create,
# exchange pulls): ``<version>-<trace_id>-<parent_span_id>-<flags>``.
TRACEPARENT_HEADER = "X-Trino-Tpu-Traceparent"


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class Span:
    """One recorded operation: identity, tree position, wall interval,
    attributes. ``end`` is None while the span is open. The start/end
    timestamps are wall-clock (for cross-process ordering in the tree);
    the DURATION is measured on the monotonic clock, so an NTP step
    mid-span cannot produce negative or inflated span times."""

    __slots__ = ("span_id", "parent_id", "name", "attributes", "start",
                 "end", "_t0", "duration")

    def __init__(self, name: str, parent_id: Optional[str],
                 attributes: Optional[dict] = None):
        self.span_id = _hex_id(8)
        self.parent_id = parent_id
        self.name = name
        self.attributes: Dict[str, object] = dict(attributes or {})
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.end: Optional[float] = None
        self.duration: Optional[float] = None

    def set(self, key: str, value) -> None:
        # copy-on-write: a live trace poll (to_dict on a handler thread)
        # snapshots `attributes` while owner/puller threads set keys — the
        # atomic rebind means readers always iterate a dict that is never
        # mutated, with no per-span lock
        self.attributes = {**self.attributes, key: value}

    def close(self) -> bool:
        """Close once; True only on the closing transition (end_span uses
        this to record each span into the flight recorder exactly once
        even though lifecycle code calls it again as a safety net)."""
        if self.end is None:
            self.end = time.time()
            self.duration = time.perf_counter() - self._t0
            return True
        return False

    @property
    def duration_s(self) -> Optional[float]:
        return self.duration

    def to_dict(self) -> dict:
        return {
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "start": self.start,
            "durationS": (round(self.duration_s, 6)
                          if self.end is not None else None),
            "attributes": dict(self.attributes),
        }


class _NoopSpan:
    """Ambient-span stand-in when no tracer is active: accepts attribute
    writes and records nothing."""

    span_id = None
    parent_id = None

    def set(self, key: str, value) -> None:
        pass


NOOP_SPAN = _NoopSpan()

# per-tracer span storage cap (satellite of the phase-ledger PR): a
# pathological query — a streaming producer emitting a span per batch,
# a retry storm — must not grow coordinator/worker memory without bound.
# At the cap new spans still TIME correctly (callers get a live Span) but
# are not stored; drops are counted so the truncation is visible.
DEFAULT_MAX_SPANS = int(os.environ.get("TRINO_TPU_TRACE_MAX_SPANS", "4096"))


class Tracer:
    """Thread-safe per-query (or per-task) span recorder.

    Nesting is tracked through the ambient context (one mechanism for both
    the explicit and ambient surfaces): a span parents to the innermost
    open span of THIS tracer on the current thread, falling back to
    ``root_parent_id`` — which is how worker task spans attach under the
    coordinator's propagated schedule span. Cross-thread children (exchange
    puller threads) pass ``parent_id`` explicitly.
    """

    def __init__(self, trace_id: Optional[str] = None,
                 root_parent_id: Optional[str] = None,
                 max_spans: Optional[int] = None):
        self.trace_id = trace_id or _hex_id(16)
        self.root_parent_id = root_parent_id
        self.max_spans = DEFAULT_MAX_SPANS if max_spans is None else max_spans
        # optional per-process FlightRecorder (obs/flightrecorder.py):
        # every closed span also lands in the owning server's bounded
        # ring, which is what the failure postmortem snapshots
        self.recorder = None
        self.dropped_spans = 0
        self._spans: List[Span] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------ recording
    def start_span(self, name: str, parent_id: Optional[str] = None,
                   **attributes) -> Span:
        """Open a span WITHOUT making it the current parent (for spans that
        close on a different thread, e.g. async pulls)."""
        if parent_id is None:
            parent_id = self.current_span_id() or self.root_parent_id
        sp = Span(name, parent_id, attributes)
        with self._lock:
            if len(self._spans) >= self.max_spans:
                # cap reached: the span still times and parents correctly
                # for its caller, it just isn't RETAINED — and the drop is
                # loud (counter + per-tracer tally), never silent
                self.dropped_spans += 1
                dropped = True
            else:
                self._spans.append(sp)
                dropped = False
        if dropped:
            from trino_tpu.obs import metrics as M

            M.SPANS_DROPPED.inc()
        return sp

    def end_span(self, span: Span) -> None:
        if span.close() and self.recorder is not None:
            self.recorder.record_span(span.to_dict(), self.trace_id)

    @contextlib.contextmanager
    def span(self, name: str, parent_id: Optional[str] = None, **attributes):
        sp = self.start_span(name, parent_id=parent_id, **attributes)
        token = _CURRENT.set((self, sp.span_id))
        try:
            yield sp
        finally:
            _CURRENT.reset(token)
            self.end_span(sp)

    def current_span_id(self) -> Optional[str]:
        cur = _CURRENT.get()
        if cur is not None and cur[0] is self:
            return cur[1]
        return None

    # ------------------------------------------------------------ exporting
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def to_dicts(self) -> List[dict]:
        return [s.to_dict() for s in self.spans()]

    def traceparent(self, span_id: Optional[str] = None) -> str:
        """Header value carrying this trace's context to another process."""
        sid = span_id or self.current_span_id() or self.root_parent_id or "0" * 16
        return f"00-{self.trace_id}-{sid}-01"


def parse_traceparent(value: Optional[str]):
    """``(trace_id, parent_span_id)`` from a propagated header, or None when
    absent/malformed (a missing header just starts a detached trace)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4 or not parts[1] or not parts[2]:
        return None
    return parts[1], parts[2]


# ------------------------------------------------------- ambient trace API
def current():
    """``(tracer, span_id)`` of the innermost active ambient span, else
    None."""
    return _CURRENT.get()


@contextlib.contextmanager
def activate(tracer: Tracer, span_id: Optional[str] = None):
    """Make ``tracer`` the thread's ambient tracer so library-level
    ``span()`` calls record into it (set at thread entry points: the
    coordinator's query thread, the worker's task thread)."""
    token = _CURRENT.set((tracer, span_id or tracer.root_parent_id))
    try:
        yield tracer
    finally:
        _CURRENT.reset(token)


@contextlib.contextmanager
def span(name: str, **attributes):
    """Ambient span: records into the active tracer, no-ops without one."""
    cur = _CURRENT.get()
    if cur is None:
        yield NOOP_SPAN
        return
    tracer, parent_id = cur
    sp = tracer.start_span(name, parent_id=parent_id, **attributes)
    token = _CURRENT.set((tracer, sp.span_id))
    try:
        yield sp
    finally:
        _CURRENT.reset(token)
        tracer.end_span(sp)


# -------------------------------------------------------- tree assembly
def build_tree(span_dicts: List[dict]) -> Optional[dict]:
    """Nest exported span records into one rooted tree.

    The root is the span without a parent in the set that started earliest
    (the coordinator's ``query`` span). Spans whose parent id is unknown —
    e.g. a worker dump that arrived without its coordinator parent — attach
    under the root rather than being dropped, so the tree is always single-
    rooted and lossless."""
    if not span_dicts:
        return None
    nodes = {}
    for s in span_dicts:
        node = dict(s)
        node["children"] = []
        nodes[node["spanId"]] = node
    roots = [n for n in nodes.values()
             if n.get("parentId") not in nodes]
    roots.sort(key=lambda n: n["start"])
    root = roots[0]
    for n in nodes.values():
        if n is root:
            continue
        parent = nodes.get(n.get("parentId"))
        if parent is None:
            parent = root
        parent["children"].append(n)
    for n in nodes.values():
        n["children"].sort(key=lambda c: c["start"])
    return root


def flatten_tree(tree: Optional[dict]):
    """Depth-first span records of a ``build_tree`` result (test helper)."""
    if tree is None:
        return
    stack = [tree]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(reversed(node["children"]))
