"""In-tree event-listener consumers of the observability SPI.

Reference role: the slow-query variants of the reference's event-listener
plugins (``plugin/trino-http-event-listener`` et al.) — here a logging
listener is built directly on the span data attached to
``QueryCompletedEvent``, the first consumer of the tracing subsystem.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

from trino_tpu.server.events import EventListener, QueryCompletedEvent

logger = logging.getLogger("trino_tpu.slow_query")

# server-level default threshold (overridable per listener instance and per
# query via the slow_query_log_threshold_ms session property)
_ENV_THRESHOLD_MS = "TRINO_TPU_SLOW_QUERY_MS"
DEFAULT_THRESHOLD_MS = 30_000


class QueryLogListener(EventListener):
    """Durable query log: one JSON line per ``QueryCompletedEvent``
    (reference role: the file/http event-listener plugins —
    ``plugin/trino-http-event-listener`` et al. — collapsed to append-only
    JSONL). Each line carries the query's identity, terminal state, stats
    summary, and failure info, so the file is greppable/jq-able query
    history that survives coordinator restarts (the in-memory history ring
    does not). Registered on the coordinator when ``TRINO_TPU_QUERY_LOG``
    names a path; a write failure is confined to this listener by
    EventListenerManager's per-listener isolation — it can never fail the
    query."""

    def __init__(self, path: str):
        self.path = path

    def query_completed(self, event: QueryCompletedEvent) -> None:
        import json

        from trino_tpu.obs.flightrecorder import trim_postmortem

        record = {
            "queryId": event.query_id,
            "user": event.user,
            "state": event.state,
            "query": event.sql.strip()[:2000],
            "createTime": event.create_time,
            "endTime": event.end_time,
            "wallMs": round(event.wall_seconds * 1000.0, 3),
            "outputRows": event.output_rows,
            "error": ((event.error or "").split("\n")[0][:500] or None),
            "spanCount": len(event.spans),
            # the phase ledger: where this query's wall went, one dict
            "timeline": event.timeline,
        }
        if event.postmortem is not None:
            # FAILED queries carry the merged flight-recorder postmortem
            # (each node's ring trimmed to its tail — the live endpoints
            # keep the full rings; the durable log keeps what matters)
            record["postmortem"] = trim_postmortem(event.postmortem)
        line = json.dumps(record, ensure_ascii=False)
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(line + "\n")


class SlowQueryLogListener(EventListener):
    """Logs queries whose wall time crosses a threshold, with the trace's
    slowest spans so the log line itself answers "where did the time go"
    (plan? schedule? the root-fragment execute? an exchange pull?). The
    event carries the COORDINATOR-side spans; per-worker device spans live
    in the full tree at ``GET /v1/query/{id}/trace``, which the log line's
    query id keys into.

    Threshold resolution, most specific wins: the query's
    ``slow_query_log_threshold_ms`` session property, then this listener's
    constructor value, then the ``TRINO_TPU_SLOW_QUERY_MS`` server
    environment property, then the default."""

    TOP_SPANS = 5

    def __init__(self, threshold_ms: Optional[int] = None):
        if threshold_ms is None:
            try:
                threshold_ms = int(os.environ.get(_ENV_THRESHOLD_MS, ""))
            except ValueError:
                # malformed env value falls back like a malformed session
                # property does — registering the listener must not crash
                # server startup
                threshold_ms = DEFAULT_THRESHOLD_MS
        self.threshold_ms = threshold_ms

    def _effective_threshold_ms(self, event: QueryCompletedEvent) -> int:
        override = event.session_properties.get("slow_query_log_threshold_ms")
        if override is not None:
            try:
                return int(override)
            except (TypeError, ValueError):
                pass
        return self.threshold_ms

    def query_completed(self, event: QueryCompletedEvent) -> None:
        threshold_ms = self._effective_threshold_ms(event)
        if event.wall_seconds * 1000.0 < threshold_ms:
            return
        slowest = sorted(
            (s for s in event.spans if s.get("durationS") is not None),
            key=lambda s: s["durationS"], reverse=True)[: self.TOP_SPANS]
        breakdown = ", ".join(
            f"{s['name']}={s['durationS'] * 1000.0:.0f}ms" for s in slowest)
        logger.warning(
            "slow query %s (%s, %.0fms >= %dms) user=%s: %s | slowest spans: %s",
            event.query_id, event.state, event.wall_seconds * 1000.0,
            threshold_ms, event.user, event.sql.strip()[:200].replace("\n", " "),
            breakdown or "none recorded")
