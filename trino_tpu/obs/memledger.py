"""Cluster memory ledger: typed per-process ring of memory events.

Reference role: the observability half of Trino's memory contract
(``memory/QueryContext`` reservations rolling up to ``MemoryPool`` +
``ClusterMemoryManager``) — the admission side already exists here
(exec/memory.py, server/cluster_memory.py); this module makes every HBM
and host byte *attributable* the way the phase ledger (obs/timeline.py)
made every millisecond attributable.

Design mirrors the flight recorder (obs/flightrecorder.py): one bounded
ring per process, O(1) append under a short lock, safe on the hot path.
Three stores per ledger:

- an **event ring** of typed records — every reservation, release, cache
  admission/eviction and pressure shed, each naming its *pool* (``device``
  or ``host``), its *owner* (``query:<id>`` / ``device-cache`` /
  ``host-cache`` / ``staging`` / ``mv-storage``) and, for evict/shed, the
  reclaiming *reason*;
- a **live/peak owner table** — bytes currently held and the high-water
  mark per (pool, owner), fed both by events and by ``sync_pool`` (the
  announce loop pushes ground-truth live numbers each heartbeat, so the
  table never drifts from the sources it summarizes);
- a **watermark ring** — per-pool totals + process RSS + jax device
  memory sampled on the announce loop into a bounded time series.

Every event kind in :data:`EVENT_KINDS` must be documented in README's
memory-ledger section (``tools/check_memledger_docs.py`` gates it), and
``record_event`` must never be called while holding a lock
(``tools/lint/lock_discipline.py`` enforces it): the append itself takes
the ledger lock, and shed events fan out to the metrics registry and the
flight recorder.

This module is import-clean standalone (stdlib only at import time) so
the docs gate can load it without the package/jax.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 512
# announce loop samples every 0.5 s -> ~2 minutes of per-node history
WATERMARK_CAPACITY = 240

# every kind a ledger event may carry; tools/check_memledger_docs.py
# requires each to be documented in README's memory-ledger section
EVENT_KINDS = ("reserve", "release", "admit", "evict", "shed", "watermark")

# kinds that grow the owner's live bytes / shrink them
_GROW_KINDS = ("reserve", "admit")
_SHRINK_KINDS = ("release", "evict", "shed")

POOL_DEVICE = "device"
POOL_HOST = "host"

# the synthetic per-pool owner row carrying the pool watermark (so
# attribution = sum(named owners) / total is computable from one table)
TOTAL_OWNER = "total"


class MemoryLedger:
    """One process's memory ledger. Events are plain dicts:
    ``{"ts", "kind", "pool", "owner", "bytes", ["reason", ...]}``."""

    def __init__(self, node_id: str = "", capacity: int = DEFAULT_CAPACITY,
                 watermark_capacity: int = WATERMARK_CAPACITY):
        self.node_id = node_id
        self._ring: "deque[dict]" = deque(maxlen=capacity)
        self._watermarks: "deque[dict]" = deque(maxlen=watermark_capacity)
        self._lock = threading.Lock()
        # (pool, owner) -> live bytes / peak bytes / event count
        self._live: Dict[tuple, int] = {}
        self._peak: Dict[tuple, int] = {}
        self._events: Dict[tuple, int] = {}
        self._updated: Dict[tuple, float] = {}
        # pool -> peak of the sampled pool total (bench + queryStats)
        self._pool_peak: Dict[str, int] = {}
        self._recorder = None

    # ------------------------------------------------------------ wiring
    def attach_recorder(self, recorder) -> None:
        """Mirror shed events into the process flight recorder so OOM
        postmortems name the shed tier without a second capture path."""
        self._recorder = recorder

    # ------------------------------------------------------------ append
    def record_event(self, kind: str, pool: str, owner: str, nbytes: int,
                     reason: Optional[str] = None, **attrs) -> None:
        """Append one typed event, O(1) under a short lock.

        MUST be called with no locks held (lock-discipline rule
        ``ledger-append-under-lock``): shed events fan out to the metrics
        registry and the flight recorder beyond the ledger's own lock.
        """
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown memory-ledger event kind: {kind!r}")
        nbytes = int(nbytes)
        rec = {"ts": time.time(), "kind": kind, "pool": pool,
               "owner": owner, "bytes": nbytes}
        if reason is not None:
            rec["reason"] = reason
        rec.update(attrs)
        key = (pool, owner)
        with self._lock:
            self._ring.append(rec)
            self._events[key] = self._events.get(key, 0) + 1
            self._updated[key] = rec["ts"]
            if kind in _GROW_KINDS:
                live = self._live.get(key, 0) + nbytes
                self._live[key] = live
                if live > self._peak.get(key, 0):
                    self._peak[key] = live
            elif kind in _SHRINK_KINDS:
                self._live[key] = max(0, self._live.get(key, 0) - nbytes)
        # fan-out OUTSIDE the ledger lock: metrics + recorder take their
        # own locks, and the lint rule bans appends under any held lock
        if kind == "shed":
            try:
                from trino_tpu.obs import metrics as M

                M.MEMORY_PRESSURE_EVENTS.inc(1, reason or "shed")
            except Exception:  # noqa: BLE001 — accounting never fails work
                pass
            if self._recorder is not None:
                self._recorder.record(
                    "memory", "memory/shed", pool=pool, owner=owner,
                    bytes=nbytes, reason=reason or "shed")

    # --------------------------------------------------------- live sync
    def set_live(self, pool: str, owner: str, nbytes: int) -> None:
        """Set an owner's live bytes from a ground-truth source (announce
        loop / executor registration), keeping the peak monotone."""
        key = (pool, owner)
        nbytes = max(0, int(nbytes))
        with self._lock:
            self._live[key] = nbytes
            if nbytes > self._peak.get(key, 0):
                self._peak[key] = nbytes
            self._updated[key] = time.time()

    def sync_pool(self, pool: str, owners: Dict[str, int],
                  prefix: Optional[str] = None) -> None:
        """Replace live bytes for ``pool`` from a ground-truth snapshot:
        every owner in ``owners`` gets its value; existing owners matching
        ``prefix`` but absent from the snapshot drop to 0 (a finished
        query stops holding bytes but keeps its peak/event history)."""
        now = time.time()
        with self._lock:
            if prefix is not None:
                for key in list(self._live):
                    if (key[0] == pool and key[1].startswith(prefix)
                            and key[1] not in owners):
                        self._live[key] = 0
            for owner, nbytes in owners.items():
                key = (pool, owner)
                nbytes = max(0, int(nbytes))
                self._live[key] = nbytes
                if nbytes > self._peak.get(key, 0):
                    self._peak[key] = nbytes
                self._updated[key] = now

    def sample_watermarks(self, pools: Dict[str, int],
                          rss_bytes: Optional[int] = None,
                          device_total_bytes: Optional[int] = None) -> None:
        """One announce-loop tick: record per-pool totals (+RSS, +device
        capacity) into the time-series ring and the synthetic ``total``
        owner rows, keeping per-pool peaks for bench/queryStats."""
        now = time.time()
        sample = {"ts": now}
        with self._lock:
            for pool, nbytes in pools.items():
                nbytes = max(0, int(nbytes))
                sample[pool] = nbytes
                key = (pool, TOTAL_OWNER)
                self._live[key] = nbytes
                if nbytes > self._peak.get(key, 0):
                    self._peak[key] = nbytes
                self._updated[key] = now
                if nbytes > self._pool_peak.get(pool, 0):
                    self._pool_peak[pool] = nbytes
            if rss_bytes is not None:
                sample["rssBytes"] = int(rss_bytes)
            if device_total_bytes is not None:
                sample["deviceTotalBytes"] = int(device_total_bytes)
            self._watermarks.append(sample)

    # ------------------------------------------------------------- reads
    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        """Oldest-first copy of the event ring."""
        with self._lock:
            records = list(self._ring)
        if limit is not None and len(records) > limit:
            records = records[-limit:]
        return records

    def watermarks(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            samples = list(self._watermarks)
        if limit is not None and len(samples) > limit:
            samples = samples[-limit:]
        return samples

    def owner_rows(self) -> List[dict]:
        """Per-(pool, owner) live/peak/event rows — the
        ``system.runtime.memory`` source. Owners with no live bytes AND no
        peak are skipped (sync churn), the synthetic ``total`` rows ride
        along so attribution is computable from the table alone."""
        with self._lock:
            keys = set(self._live) | set(self._peak) | set(self._events)
            rows = []
            for pool, owner in sorted(keys):
                key = (pool, owner)
                live = self._live.get(key, 0)
                peak = self._peak.get(key, 0)
                if live <= 0 and peak <= 0:
                    continue
                rows.append({
                    "pool": pool, "owner": owner, "bytes": live,
                    "peakBytes": peak,
                    "events": self._events.get(key, 0),
                    "updatedAt": self._updated.get(key, 0.0),
                })
        return rows

    def pool_peaks(self) -> Dict[str, int]:
        """Peak sampled total per pool (bench + queryStats.memory)."""
        with self._lock:
            return dict(self._pool_peak)

    def memory_snapshot(self, top: int = 3) -> dict:
        """The postmortem block: pool watermarks, the top ``top``
        named consumers per pool by peak bytes, and the newest shed
        events (which name the shed tier + reclaiming reason)."""
        rows = self.owner_rows()
        pools: Dict[str, dict] = {}
        consumers: List[dict] = []
        for row in rows:
            if row["owner"] == TOTAL_OWNER:
                pools[row["pool"]] = {"bytes": row["bytes"],
                                      "peakBytes": row["peakBytes"]}
            else:
                consumers.append(row)
        consumers.sort(key=lambda r: (r["peakBytes"], r["bytes"]),
                       reverse=True)
        by_pool: Dict[str, List[dict]] = {}
        for row in consumers:
            bucket = by_pool.setdefault(row["pool"], [])
            if len(bucket) < top:
                bucket.append(row)
        sheds = [r for r in self.snapshot() if r["kind"] == "shed"][-8:]
        return {"nodeId": self.node_id, "pools": pools,
                "topConsumers": by_pool, "sheds": sheds}

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# the per-process ledger (coordinator AND every worker — same pattern as
# the per-process metrics registry); servers stamp node_id at startup
MEMORY_LEDGER = MemoryLedger()
