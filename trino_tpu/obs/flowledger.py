"""Data-plane flow ledger: typed per-link transfer attribution.

Reference role: the observability half of Trino's data plane — the
exchange clients' ``DirectExchangeClientStatus`` / buffer utilization
histograms and the per-task ``outputBufferUtilization`` the Web UI reads
— collapsed into one typed per-process ledger, completing the quartet:
the phase ledger (obs/timeline.py) attributed every millisecond, the
memory ledger (obs/memledger.py) every byte *at rest*, the kernel ledger
(obs/devprofiler.py) every device dispatch; this module attributes every
byte *in motion*.

Design mirrors the memory ledger: one bounded ring per process, O(1)
append under a short lock, safe on the hot path. Three stores:

- a **transfer ring** of typed records — every cross-boundary transfer
  names its *link class* (:data:`LINK_CLASSES`), its *owner*
  (``query:<id>`` / ``task:<id>`` / ``segment-store`` / ``control``),
  src/dst node, bytes, pages, wall seconds and retries;
- a **per-(link, owner) rollup table** — bytes/pages/seconds/transfers/
  retries totals, from which effective MB/s derives
  (``system.runtime.transfers`` reads this, cluster-folded over the
  announce payload like the kernel ledger);
- a **stall ring + rollup** — backpressure samples from the producers'
  blocking sites (:data:`STALL_SITES`): output-buffer enqueue full-waits
  and exchange-client empty polls, each naming its (stage, partition)
  so "producer blocked on consumer" is readable per link.

On top of the same task statistics the coordinator already collects,
:func:`detect_stragglers` flags tasks whose elapsed exceeds a
configurable multiple of their stage's median and attributes each to its
dominant cause (:data:`STRAGGLER_CAUSES`) from the per-task ledger
seconds (``transferS`` / ``deviceS`` / ``stallS``).

Every link class, stall site and straggler cause must be documented in
README's flow-ledger section (``tools/check_flow_docs.py`` gates it),
and ``record_transfer`` / ``record_stall`` must never be called while
holding a lock (``tools/lint/lock_discipline.py``): the append itself
takes the ledger lock, and records fan out to the metrics registry and
the flight recorder.

This module is import-clean standalone (stdlib only at import time) so
the docs gate can load it without the package/jax.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

DEFAULT_CAPACITY = 512
STALL_CAPACITY = 512

# every link class a transfer record may carry; tools/check_flow_docs.py
# requires each to be documented in README's flow-ledger section
LINK_CLASSES = (
    "exchange-pull",      # serialized pages pulled from an upstream
                          # task's output buffer (or its durable spool)
    "spool-write",        # result/exchange segments rolled to durable
                          # storage by this process's segment store
    "segment-fetch",      # segment bytes served to a client (full GET
                          # or a Range slice)
    "staging-transfer",   # host->device DMA blocks issued by the
                          # staging pipeline's blocked_transfer
    "client-drain",       # statement-protocol result bytes serialized
                          # to a draining client
    "control",            # cluster-internal JSON control calls
                          # (announce, task submit/status, cancel)
)

# blocking sites sampled into the backpressure stall series
STALL_SITES = (
    "buffer-enqueue",     # producer blocked: output buffer at capacity
    "exchange-poll",      # consumer starved: pull returned zero pages
)

STRAGGLER_CAUSES = (
    "transfer-bound",     # dominant ledger seconds: exchange/spool pulls
    "device-bound",       # dominant ledger seconds: device execution
    "queue-bound",        # dominant ledger seconds: backpressure stalls
)

# a task is a straggler when elapsed > multiple x stage median
DEFAULT_STRAGGLER_MULTIPLE = 3.0
# ...and elapsed clears an absolute floor, so millisecond-scale stages
# (metadata fragments, tiny-schema tests) never flag ratio noise
DEFAULT_STRAGGLER_MIN_ELAPSED_S = 0.25


class FlowLedger:
    """One process's flow ledger. Transfer records are plain dicts:
    ``{"ts", "link", "owner", "bytes", "pages", "seconds", "src", "dst",
    "direction", ["retries", "status", ...]}``."""

    def __init__(self, node_id: str = "", capacity: int = DEFAULT_CAPACITY,
                 stall_capacity: int = STALL_CAPACITY):
        self.node_id = node_id
        self._ring: "deque[dict]" = deque(maxlen=capacity)
        self._stall_ring: "deque[dict]" = deque(maxlen=stall_capacity)
        self._lock = threading.Lock()
        # (link, owner) -> cumulative rollup
        self._rollup: Dict[tuple, dict] = {}
        # (site, stage, partition) -> cumulative stall rollup
        self._stall_rollup: Dict[tuple, dict] = {}
        self._sent = 0
        self._received = 0
        self._recorder = None

    # ------------------------------------------------------------ wiring
    def attach_recorder(self, recorder) -> None:
        """Mirror retried transfers into the process flight recorder so a
        postmortem names flaky links without a second capture path."""
        self._recorder = recorder

    # ------------------------------------------------------------ append
    def record_transfer(self, link: str, owner: str, nbytes: int,
                        seconds: float, *, pages: int = 0,
                        src: Optional[str] = None, dst: Optional[str] = None,
                        direction: str = "recv", retries: int = 0,
                        status: Optional[str] = None,
                        ring: bool = True, **attrs) -> None:
        """Append one typed transfer, O(1) under a short lock.

        MUST be called with no locks held (lock-discipline rule
        ``ledger-append-under-lock``): records fan out to the metrics
        registry and the flight recorder beyond the ledger's own lock.
        ``ring=False`` updates the rollup/net totals only — the control
        link uses it so announce heartbeats (2/s/worker) never evict the
        data-plane records a postmortem wants.
        """
        if link not in LINK_CLASSES:
            raise ValueError(f"unknown flow-ledger link class: {link!r}")
        nbytes = int(nbytes)
        seconds = max(0.0, float(seconds))
        rec = {"ts": time.time(), "link": link, "owner": owner,
               "bytes": nbytes, "pages": int(pages),
               "seconds": round(seconds, 6), "direction": direction}
        if src is not None:
            rec["src"] = src
        if dst is not None:
            rec["dst"] = dst
        if retries:
            rec["retries"] = int(retries)
        if status is not None:
            rec["status"] = status
        rec.update(attrs)
        key = (link, owner)
        with self._lock:
            if ring:
                self._ring.append(rec)
            roll = self._rollup.get(key)
            if roll is None:
                roll = {"bytes": 0, "pages": 0, "seconds": 0.0,
                        "transfers": 0, "retries": 0, "lastStatus": None}
                self._rollup[key] = roll
            roll["bytes"] += nbytes
            roll["pages"] += int(pages)
            roll["seconds"] += seconds
            roll["transfers"] += 1
            roll["retries"] += int(retries)
            if status is not None:
                roll["lastStatus"] = status
            if direction == "send":
                self._sent += nbytes
            else:
                self._received += nbytes
        # fan-out OUTSIDE the ledger lock: metrics + recorder take their
        # own locks, and the lint rule bans appends under any held lock
        try:
            from trino_tpu.obs import metrics as M

            M.TRANSFER_BYTES.inc(nbytes, link, direction)
            M.TRANSFER_SECONDS.inc(seconds, link)
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass
        if retries and self._recorder is not None:
            self._recorder.record(
                "flow", "flow/retry", link=link, owner=owner, bytes=nbytes,
                retries=int(retries), status=status or "")

    def record_stall(self, site: str, stage, partition, waited_s: float, *,
                     depth_bytes: int = 0, limit_bytes: int = 0) -> None:
        """One backpressure sample: a producer blocked ``waited_s`` at
        ``site`` for (stage, partition), with the queue depth it saw.
        Same lock discipline as :meth:`record_transfer`."""
        if site not in STALL_SITES:
            raise ValueError(f"unknown flow-ledger stall site: {site!r}")
        waited_s = max(0.0, float(waited_s))
        rec = {"ts": time.time(), "site": site, "stage": stage,
               "partition": partition, "waitedS": round(waited_s, 6),
               "depthBytes": int(depth_bytes), "limitBytes": int(limit_bytes)}
        key = (site, stage, partition)
        with self._lock:
            self._stall_ring.append(rec)
            roll = self._stall_rollup.get(key)
            if roll is None:
                roll = {"waits": 0, "stallS": 0.0, "lastDepthBytes": 0}
                self._stall_rollup[key] = roll
            roll["waits"] += 1
            roll["stallS"] += waited_s
            roll["lastDepthBytes"] = int(depth_bytes)
        try:
            from trino_tpu.obs import metrics as M

            M.BACKPRESSURE_STALL_SECONDS.inc(waited_s, str(stage))
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass

    # ------------------------------------------------------------- reads
    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        """Oldest-first copy of the transfer ring."""
        with self._lock:
            records = list(self._ring)
        if limit is not None and len(records) > limit:
            records = records[-limit:]
        return records

    def stall_samples(self, limit: Optional[int] = None) -> List[dict]:
        """Oldest-first copy of the stall ring (the backpressure
        timeline: queue depth + wait duration per sample)."""
        with self._lock:
            records = list(self._stall_ring)
        if limit is not None and len(records) > limit:
            records = records[-limit:]
        return records

    def transfer_rows(self) -> List[dict]:
        """Per-(link, owner) rollup rows with derived effective MB/s —
        the ``system.runtime.transfers`` source and the announce payload's
        ``flows`` block."""
        with self._lock:
            items = [(k, dict(v)) for k, v in self._rollup.items()]
        rows = []
        for (link, owner), roll in sorted(items):
            seconds = roll["seconds"]
            rows.append({
                "link": link, "owner": owner, "bytes": roll["bytes"],
                "pages": roll["pages"], "transfers": roll["transfers"],
                "seconds": round(seconds, 6),
                "mbPerS": round(roll["bytes"] / seconds / 1e6, 3)
                          if seconds > 0 else None,
                "retries": roll["retries"],
                "lastStatus": roll["lastStatus"],
            })
        return rows

    def stall_rows(self) -> List[dict]:
        """Per-(site, stage, partition) stall rollups (announce payload's
        ``flowStalls`` block + the EXPLAIN ANALYZE annotations)."""
        with self._lock:
            items = [(k, dict(v)) for k, v in self._stall_rollup.items()]
        return [{
            "site": site, "stage": stage, "partition": partition,
            "waits": roll["waits"], "stallS": round(roll["stallS"], 6),
            "lastDepthBytes": roll["lastDepthBytes"],
        } for (site, stage, partition), roll in sorted(
            items, key=lambda kv: (kv[0][0], str(kv[0][1]), str(kv[0][2])))]

    def net_totals(self) -> Dict[str, int]:
        """Lifetime bytes this process sent/received across every link —
        the ``system.runtime.nodes`` net columns."""
        with self._lock:
            return {"sent": self._sent, "received": self._received}

    def owner_bytes(self, owner_prefix: str,
                    links: Optional[Iterable[str]] = None) -> int:
        """Total bytes attributed to owners matching ``owner_prefix``
        (optionally restricted to ``links``) — the conservation check's
        read side."""
        links = tuple(links) if links is not None else None
        with self._lock:
            return sum(
                roll["bytes"] for (link, owner), roll in self._rollup.items()
                if owner.startswith(owner_prefix)
                and (links is None or link in links))

    def flow_snapshot(self, last: int = 16) -> dict:
        """The postmortem / recorder-endpoint block: per-link rollups,
        net totals, the newest ``last`` transfer records (what was moving
        when the process died) and the stall rollups."""
        by_link: Dict[str, dict] = {}
        for row in self.transfer_rows():
            agg = by_link.setdefault(row["link"], {
                "bytes": 0, "pages": 0, "seconds": 0.0, "transfers": 0,
                "retries": 0})
            agg["bytes"] += row["bytes"]
            agg["pages"] += row["pages"]
            agg["seconds"] = round(agg["seconds"] + row["seconds"], 6)
            agg["transfers"] += row["transfers"]
            agg["retries"] += row["retries"]
        for agg in by_link.values():
            agg["mbPerS"] = (round(agg["bytes"] / agg["seconds"] / 1e6, 3)
                             if agg["seconds"] > 0 else None)
        return {"nodeId": self.node_id, "links": by_link,
                "net": self.net_totals(), "recent": self.snapshot(last),
                "stalls": self.stall_rows()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# ----------------------------------------------------- straggler detector
def _median(values: List[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    return (ordered[mid] if n % 2
            else (ordered[mid - 1] + ordered[mid]) / 2.0)


def straggler_cause(stats: dict) -> str:
    """The dominant cause for one task from its ledger seconds: the
    largest of transfer (exchange/spool pull wall), device (device
    execution) and queue (backpressure stall) seconds. Ties — including
    the all-zero degenerate case — resolve to ``device-bound``, the
    'the task itself was slow' reading."""
    transfer_s = float(stats.get("transferS", 0.0))
    device_s = float(stats.get("deviceS", 0.0))
    stall_s = float(stats.get("stallS", 0.0))
    if transfer_s > device_s and transfer_s > stall_s:
        return "transfer-bound"
    if stall_s > device_s and stall_s > transfer_s:
        return "queue-bound"
    return "device-bound"


def detect_stragglers(
        tasks: Iterable[dict],
        multiple: float = DEFAULT_STRAGGLER_MULTIPLE,
        min_elapsed_s: float = DEFAULT_STRAGGLER_MIN_ELAPSED_S) -> List[dict]:
    """Flag straggler tasks from coordinator task records.

    ``tasks`` are ``{"taskId", "fragment" | "stageId", "workerUri",
    "stats": {...}}`` records (``QueryExecution.task_records()`` shape).
    Per stage, a task is a straggler when its ``elapsedS`` exceeds
    ``multiple`` x the stage median AND clears ``min_elapsed_s``
    (absolute floor: millisecond stages never flag ratio noise). A stage
    with fewer than two tasks has no distribution and never flags. Each
    flagged task carries its dominant cause (:func:`straggler_cause`)."""
    by_stage: Dict[object, List[dict]] = {}
    for rec in tasks:
        stage = rec.get("stageId", rec.get("fragment"))
        by_stage.setdefault(stage, []).append(rec)
    flagged: List[dict] = []
    for stage_id, recs in by_stage.items():
        if len(recs) < 2:
            continue
        elapsed = [float((r.get("stats") or {}).get("elapsedS", 0.0))
                   for r in recs]
        median = _median(elapsed)
        threshold = max(median * float(multiple), float(min_elapsed_s))
        for rec, el in zip(recs, elapsed):
            if median <= 0.0 or el <= threshold:
                continue
            stats = rec.get("stats") or {}
            flagged.append({
                "taskId": rec.get("taskId"),
                "stageId": stage_id,
                "workerUri": rec.get("workerUri"),
                "elapsedS": round(el, 6),
                "stageMedianS": round(median, 6),
                "ratio": round(el / median, 3),
                "multiple": float(multiple),
                "cause": straggler_cause(stats),
                "completedSplits": int(stats.get("completedSplits", 0)),
            })
    flagged.sort(key=lambda r: r["ratio"], reverse=True)
    return flagged


# the per-process ledger (coordinator AND every worker — same pattern as
# the per-process metrics registry); servers stamp node_id at startup
FLOW_LEDGER = FlowLedger()
