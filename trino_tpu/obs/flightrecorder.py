"""Failure flight recorder: a bounded per-process ring of recent records.

Reference role: the post-hoc context the reference engine scatters across
coordinator logs, ``QueryInfo.failureInfo`` and per-task diagnostics —
collapsed into one always-on, bounded, in-memory ring per process
(coordinator AND every worker). The ring holds the last N span / event /
admission records regardless of which query produced them, so when a
query FAILS or times out the postmortem shows the PROCESS context around
the failure (what else was running, what the admission gate did, which
task spans closed last) — exactly what a chaos run's kill-a-worker
scenario needs and what a span tree scoped to the dead query cannot show.

On query FAILED the coordinator snapshots its own ring and pulls each
involved worker's ring (``GET /v1/task/{id}/recorder``), merging them
into one postmortem attached to ``GET /v1/query/{id}/trace?recorder=1``,
to ``QueryCompletedEvent.postmortem`` (which the JSONL query log
persists, trimmed), and kept on the execution for later inspection.

Recording is O(1) append under a short lock — safe on the hot path.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

DEFAULT_CAPACITY = 512

# records shipped per node inside a JSONL query-log line (the full rings
# stay available on the live endpoints; the durable log keeps the tail)
LOG_RECORDS_PER_NODE = 64


class FlightRecorder:
    """One process's ring. Records are plain dicts:
    ``{"ts", "kind": "span"|"event"|"admission", "name", ...}``."""

    def __init__(self, node_id: str = "", capacity: int = DEFAULT_CAPACITY):
        self.node_id = node_id
        self._ring: "deque[dict]" = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, kind: str, name: str, **attrs) -> None:
        rec = {"ts": time.time(), "kind": kind, "name": name}
        rec.update(attrs)
        with self._lock:
            self._ring.append(rec)

    def record_span(self, span_dict: dict, trace_id: str) -> None:
        """One closed span (obs/trace hooks this into ``Tracer.end_span``
        via ``tracer.recorder``)."""
        with self._lock:
            self._ring.append({
                "ts": span_dict.get("start"),
                "kind": "span",
                "name": span_dict.get("name"),
                "traceId": trace_id,
                "spanId": span_dict.get("spanId"),
                "durationS": span_dict.get("durationS"),
                "attributes": span_dict.get("attributes") or {},
            })

    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        """Oldest-first copy of the ring (optionally only the newest
        ``limit`` records)."""
        with self._lock:
            records = list(self._ring)
        if limit is not None and len(records) > limit:
            records = records[-limit:]
        return records

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def pull_worker_rings(locations, timeout: float = 3.0,
                      pool=None) -> List[dict]:
    """Fetch the flight-recorder ring of every distinct worker involved
    in a query. ``locations`` are exchange-client ``TaskLocation``s (one
    representative task id per worker base url is enough — the endpoint
    returns the PROCESS ring). A gone worker contributes an error stub
    instead of sinking the postmortem; fetches run in parallel on the
    server's shared IO ``pool`` when given (serially otherwise — callers
    on the hot path always pass the pool; the per-call executor this
    replaced churned a fresh thread pool per capture)."""
    import json

    from trino_tpu.server import wire

    by_url = {}
    for loc in locations:
        if loc is not None:
            by_url.setdefault(loc.base_url, loc.task_id)
    if not by_url:
        return []

    def fetch(item):
        url, task_id = item
        try:
            status, body, _ = wire.http_request(
                "GET", f"{url}/v1/task/{task_id}/recorder", timeout=timeout)
            if status < 400:
                payload = json.loads(body)
                return {"url": url, "nodeId": payload.get("nodeId"),
                        "records": payload.get("records", []),
                        # memory-ledger snapshot rides the same pull so a
                        # postmortem names each node's top consumers
                        "memory": payload.get("memory"),
                        # flow-ledger snapshot rides along too: per-link
                        # rollups + the node's last transfers/stalls
                        "flows": payload.get("flows")}
            return {"url": url, "error": f"status {status}"}
        except Exception as e:  # noqa: BLE001 — a dead worker IS the story
            return {"url": url, "error": str(e)[:300]}

    items = sorted(by_url.items())
    if pool is not None:
        try:
            return list(pool.map(fetch, items))
        except RuntimeError:  # pool already shut down: fall through
            pass
    return [fetch(item) for item in items]


def trim_postmortem(postmortem: Optional[dict],
                    per_node: int = LOG_RECORDS_PER_NODE) -> Optional[dict]:
    """A bounded copy for the durable JSONL query log: keep each node's
    newest ``per_node`` records and note how many were cut."""
    if postmortem is None:
        return None

    def trim_node(node: dict) -> dict:
        out = {k: v for k, v in node.items() if k != "records"}
        records = node.get("records")
        if records is not None:
            out["records"] = records[-per_node:]
            if len(records) > per_node:
                out["truncated"] = len(records) - per_node
        return out

    out = {k: v for k, v in postmortem.items()
           if k not in ("coordinator", "workers")}
    if "coordinator" in postmortem:
        out["coordinator"] = trim_node(postmortem["coordinator"])
    if "workers" in postmortem:
        out["workers"] = [trim_node(w) for w in postmortem["workers"]]
    return out
