"""Columnar wire format: Page <-> bytes, with per-column compression.

Reference: ``core/trino-main/.../execution/buffer/PageSerializer.java:59`` /
``PageDeserializer`` and ``PagesSerdeFactory.java:53-59`` (per-block encodings
+ LZ4/ZSTD frame + optional AES). Here: a compact header + per-column blocks
(dtype tag, null bitmap, raw values, dictionary vocabulary for varchar),
compressed with zlib (the image has no lz4 module; the codec byte leaves room
to add one). Used by the DCN streaming shuffle tier, the spooled exchange,
and the spooled result segments (SURVEY.md §2.6) — intra-slice repartition
never serializes (it rides ICI inside the compiled program).

Version 3 compresses each COLUMN block independently and stores a block
RAW when zlib does not shrink it (the reference's
``PageSerializer`` marker-byte contract: an incompressible block skips
the codec). Float/int entropy columns — exactly the shape of a big
result export — previously paid compress+inflate both ways for nothing;
now they pay neither, and the per-codec byte counters
(``trino_tpu_serde_bytes_total{direction,codec}``) make the realized
compression ratio observable. Version 2 payloads (whole-body zlib)
still deserialize — spool files written by an older process stay
readable.

Format (little-endian):
  magic u32 | version u8 | codec u8 | num_columns u16 | num_rows u32
  then per column: block_codec u8 | block_len u32 | block bytes
  (block_codec = CODEC_ZLIB when compressed, CODEC_NONE when stored raw)
  where each block decodes to:
    type_name: u16 len + utf8
    has_nulls: u8; if 1: packed bitmap ceil(n/8) bytes
    dtype_code: u8 (PHYSICAL dtype — may be narrower than the logical type)
    values: n * itemsize bytes
    if varchar: dict_len u32, then dict_len strings (u32 len + utf8)
"""
from __future__ import annotations

import struct
import zlib
from typing import List

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.data.dictionary import Dictionary
from trino_tpu.data.page import Column, Page

MAGIC = 0x7E51_00D5
CODEC_NONE = 0
CODEC_ZLIB = 1

_CODEC_NAMES = {CODEC_NONE: "none", CODEC_ZLIB: "zlib"}

# Physical dtype tags: a column may ride a narrower dtype than its logical
# type's (data/page.py Column), so the wire format carries the actual one.
_DTYPE_CODES = {
    np.dtype(np.bool_): 0, np.dtype(np.int8): 1, np.dtype(np.int16): 2,
    np.dtype(np.int32): 3, np.dtype(np.int64): 4,
    np.dtype(np.float32): 5, np.dtype(np.float64): 6,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


def _serialize_column(col: Column, n: int, parts: List[bytes]) -> None:
    name = str(col.type).encode()
    parts.append(struct.pack("<H", len(name)))
    parts.append(name)
    if col.nulls is not None:
        parts.append(b"\x01")
        parts.append(np.packbits(np.asarray(col.nulls)).tobytes())
    else:
        parts.append(b"\x00")
    vals_np = np.ascontiguousarray(np.asarray(col.values))
    dtype_code = _DTYPE_CODES[vals_np.dtype]
    if col.hi is not None:
        # long-decimal two-limb column: flag bit 7 on the dtype code, hi
        # limb block follows the low words (reference: Int128 flat storage)
        parts.append(struct.pack("<B", dtype_code | 0x80))
        parts.append(vals_np.tobytes())
        parts.append(np.ascontiguousarray(np.asarray(col.hi)).tobytes())
    else:
        parts.append(struct.pack("<B", dtype_code))
        parts.append(vals_np.tobytes())
    if col.type.is_varchar:
        assert col.dictionary is not None
        vocab = col.dictionary.values
        parts.append(struct.pack("<I", len(vocab)))
        for s in vocab:
            b = s.encode()
            parts.append(struct.pack("<I", len(b)))
            parts.append(b)
    if col.type.is_nested:
        # children: u32 flat row count, then the child column recursively
        # (reference: ArrayBlockEncoding/MapBlockEncoding nest the element
        # block encodings the same way)
        for child in col.children:
            parts.append(struct.pack("<I", len(child)))
            _serialize_column(child, len(child), parts)


def serialize_page(page: Page, codec: int = CODEC_ZLIB) -> bytes:
    from trino_tpu.obs import metrics as M

    n = page.num_rows
    out: List[bytes] = [
        struct.pack("<IBBHI", MAGIC, 3, codec, page.channel_count, n)]
    logical = 0
    wire_by_codec = {CODEC_NONE: 0, CODEC_ZLIB: 0}
    for col in page.columns:
        parts: List[bytes] = []
        _serialize_column(col, n, parts)
        body = b"".join(parts)
        logical += len(body)
        block_codec, block = CODEC_NONE, body
        if codec == CODEC_ZLIB:
            comp = zlib.compress(body, level=1)
            if len(comp) < len(body):
                # incompressible-column fast path: only blocks zlib
                # actually SHRANK ship compressed — entropy data (float
                # measures, high-cardinality ints) stores raw and skips
                # the inflate on the read side too
                block_codec, block = CODEC_ZLIB, comp
        wire_by_codec[block_codec] += len(block)
        out.append(struct.pack("<BI", block_codec, len(block)))
        out.append(block)
    for bc, nbytes in wire_by_codec.items():
        if nbytes:
            M.SERDE_BYTES.inc(nbytes, "encode", _CODEC_NAMES[bc])
    if logical:
        M.SERDE_BYTES.inc(logical, "encode", "logical")
    return b"".join(out)


def deserialize_page(data: bytes) -> Page:
    from trino_tpu.obs import metrics as M

    magic, version, codec, ncols, nrows = struct.unpack_from("<IBBHI", data, 0)
    if magic != MAGIC:
        raise ValueError("bad page magic")
    columns: List[Column] = []
    if version == 2:
        # legacy whole-body frame (pre-incompressible-fast-path spool
        # files): one zlib pass over every column block together
        body = data[12:]
        if codec == CODEC_ZLIB:
            body = zlib.decompress(body)
        off = 0
        for _ in range(ncols):
            col, off = _deserialize_column(body, off, nrows)
            columns.append(col)
        return Page(columns)
    if version != 3:
        raise ValueError(
            f"unsupported page format version {version} (expected 2 or 3)")
    off = 12
    logical = 0
    wire_by_codec = {CODEC_NONE: 0, CODEC_ZLIB: 0}
    for _ in range(ncols):
        block_codec, block_len = struct.unpack_from("<BI", data, off)
        off += 5
        block = data[off:off + block_len]
        off += block_len
        wire_by_codec[block_codec] = (
            wire_by_codec.get(block_codec, 0) + block_len)
        if block_codec == CODEC_ZLIB:
            block = zlib.decompress(block)
        elif block_codec != CODEC_NONE:
            raise ValueError(f"unknown column block codec {block_codec}")
        logical += len(block)
        col, _end = _deserialize_column(block, 0, nrows)
        columns.append(col)
    for bc, nbytes in wire_by_codec.items():
        if nbytes:
            M.SERDE_BYTES.inc(nbytes, "decode", _CODEC_NAMES[bc])
    if logical:
        M.SERDE_BYTES.inc(logical, "decode", "logical")
    return Page(columns)


def _deserialize_column(body: bytes, off: int, nrows: int):
    (name_len,) = struct.unpack_from("<H", body, off)
    off += 2
    typ = T.parse_type(body[off : off + name_len].decode())
    off += name_len
    has_nulls = body[off]
    off += 1
    nulls = None
    if has_nulls:
        nbytes = (nrows + 7) // 8
        bits = np.unpackbits(
            np.frombuffer(body, dtype=np.uint8, count=nbytes, offset=off)
        )[:nrows].astype(np.bool_)
        nulls = jnp.asarray(bits)
        off += nbytes
    code = body[off]
    has_hi = bool(code & 0x80)
    dt = _CODE_DTYPES[code & 0x7F]
    off += 1
    vals = np.frombuffer(body, dtype=dt, count=nrows, offset=off)
    off += nrows * dt.itemsize
    hi = None
    if has_hi:
        hi = np.frombuffer(body, dtype=np.int64, count=nrows, offset=off)
        off += nrows * 8
    dictionary = None
    if typ.is_varchar:
        (dlen,) = struct.unpack_from("<I", body, off)
        off += 4
        vocab = []
        for _ in range(dlen):
            (slen,) = struct.unpack_from("<I", body, off)
            off += 4
            vocab.append(body[off : off + slen].decode())
            off += slen
        dictionary = Dictionary(vocab)
    children = None
    if typ.is_nested:
        children = []
        for _ in T.type_children(typ):
            (crows,) = struct.unpack_from("<I", body, off)
            off += 4
            child, off = _deserialize_column(body, off, crows)
            children.append(child)
    return (
        Column(
            typ, jnp.asarray(vals), nulls, dictionary, children=children,
            hi=jnp.asarray(hi) if hi is not None else None,
        ),
        off,
    )
