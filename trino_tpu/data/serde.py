"""Columnar wire format: Page <-> bytes, with compression.

Reference: ``core/trino-main/.../execution/buffer/PageSerializer.java:59`` /
``PageDeserializer`` and ``PagesSerdeFactory.java:53-59`` (per-block encodings
+ LZ4/ZSTD frame + optional AES). Here: a compact header + per-column blocks
(dtype tag, null bitmap, raw values, dictionary vocabulary for varchar),
compressed with zlib (the image has no lz4 module; the codec byte leaves room
to add one). Used by the DCN streaming shuffle tier and the spooled exchange
(SURVEY.md §2.6) — intra-slice repartition never serializes (it rides ICI
inside the compiled program).

Format (little-endian):
  magic u32 | version u8 | codec u8 | num_columns u16 | num_rows u32
  then per column (inside the compressed body):
    type_name: u16 len + utf8
    has_nulls: u8; if 1: packed bitmap ceil(n/8) bytes
    dtype_code: u8 (PHYSICAL dtype — may be narrower than the logical type)
    values: n * itemsize bytes
    if varchar: dict_len u32, then dict_len strings (u32 len + utf8)
"""
from __future__ import annotations

import struct
import zlib
from typing import List

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.data.dictionary import Dictionary
from trino_tpu.data.page import Column, Page

MAGIC = 0x7E51_00D5
CODEC_NONE = 0
CODEC_ZLIB = 1

# Physical dtype tags: a column may ride a narrower dtype than its logical
# type's (data/page.py Column), so the wire format carries the actual one.
_DTYPE_CODES = {
    np.dtype(np.bool_): 0, np.dtype(np.int8): 1, np.dtype(np.int16): 2,
    np.dtype(np.int32): 3, np.dtype(np.int64): 4,
    np.dtype(np.float32): 5, np.dtype(np.float64): 6,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


def _serialize_column(col: Column, n: int, parts: List[bytes]) -> None:
    name = str(col.type).encode()
    parts.append(struct.pack("<H", len(name)))
    parts.append(name)
    if col.nulls is not None:
        parts.append(b"\x01")
        parts.append(np.packbits(np.asarray(col.nulls)).tobytes())
    else:
        parts.append(b"\x00")
    vals_np = np.ascontiguousarray(np.asarray(col.values))
    dtype_code = _DTYPE_CODES[vals_np.dtype]
    if col.hi is not None:
        # long-decimal two-limb column: flag bit 7 on the dtype code, hi
        # limb block follows the low words (reference: Int128 flat storage)
        parts.append(struct.pack("<B", dtype_code | 0x80))
        parts.append(vals_np.tobytes())
        parts.append(np.ascontiguousarray(np.asarray(col.hi)).tobytes())
    else:
        parts.append(struct.pack("<B", dtype_code))
        parts.append(vals_np.tobytes())
    if col.type.is_varchar:
        assert col.dictionary is not None
        vocab = col.dictionary.values
        parts.append(struct.pack("<I", len(vocab)))
        for s in vocab:
            b = s.encode()
            parts.append(struct.pack("<I", len(b)))
            parts.append(b)
    if col.type.is_nested:
        # children: u32 flat row count, then the child column recursively
        # (reference: ArrayBlockEncoding/MapBlockEncoding nest the element
        # block encodings the same way)
        for child in col.children:
            parts.append(struct.pack("<I", len(child)))
            _serialize_column(child, len(child), parts)


def serialize_page(page: Page, codec: int = CODEC_ZLIB) -> bytes:
    parts: List[bytes] = []
    n = page.num_rows
    for col in page.columns:
        _serialize_column(col, n, parts)
    body = b"".join(parts)
    if codec == CODEC_ZLIB:
        body = zlib.compress(body, level=1)
    header = struct.pack("<IBBHI", MAGIC, 2, codec, page.channel_count, n)
    return header + body


def deserialize_page(data: bytes) -> Page:
    magic, version, codec, ncols, nrows = struct.unpack_from("<IBBHI", data, 0)
    if magic != MAGIC:
        raise ValueError("bad page magic")
    if version != 2:
        raise ValueError(f"unsupported page format version {version} (expected 2)")
    body = data[12:]
    if codec == CODEC_ZLIB:
        body = zlib.decompress(body)
    off = 0
    columns: List[Column] = []
    for _ in range(ncols):
        col, off = _deserialize_column(body, off, nrows)
        columns.append(col)
    return Page(columns)


def _deserialize_column(body: bytes, off: int, nrows: int):
    (name_len,) = struct.unpack_from("<H", body, off)
    off += 2
    typ = T.parse_type(body[off : off + name_len].decode())
    off += name_len
    has_nulls = body[off]
    off += 1
    nulls = None
    if has_nulls:
        nbytes = (nrows + 7) // 8
        bits = np.unpackbits(
            np.frombuffer(body, dtype=np.uint8, count=nbytes, offset=off)
        )[:nrows].astype(np.bool_)
        nulls = jnp.asarray(bits)
        off += nbytes
    code = body[off]
    has_hi = bool(code & 0x80)
    dt = _CODE_DTYPES[code & 0x7F]
    off += 1
    vals = np.frombuffer(body, dtype=dt, count=nrows, offset=off)
    off += nrows * dt.itemsize
    hi = None
    if has_hi:
        hi = np.frombuffer(body, dtype=np.int64, count=nrows, offset=off)
        off += nrows * 8
    dictionary = None
    if typ.is_varchar:
        (dlen,) = struct.unpack_from("<I", body, off)
        off += 4
        vocab = []
        for _ in range(dlen):
            (slen,) = struct.unpack_from("<I", body, off)
            off += 4
            vocab.append(body[off : off + slen].decode())
            off += slen
        dictionary = Dictionary(vocab)
    children = None
    if typ.is_nested:
        children = []
        for _ in T.type_children(typ):
            (crows,) = struct.unpack_from("<I", body, off)
            off += 4
            child, off = _deserialize_column(body, off, crows)
            children.append(child)
    return (
        Column(
            typ, jnp.asarray(vals), nulls, dictionary, children=children,
            hi=jnp.asarray(hi) if hi is not None else None,
        ),
        off,
    )
