"""Columnar data plane: device-resident struct-of-arrays batches.

Reference: ``core/trino-spi/.../spi/Page.java`` (Page = Block[] + positionCount)
and the Block hierarchy (``spi/block/``). Here a Page is a list of Columns;
each Column is one ``jax.Array`` of values plus an optional null mask array;
varchar columns carry a host-side Dictionary.
"""
from trino_tpu.data.dictionary import Dictionary
from trino_tpu.data.page import Column, Page

__all__ = ["Dictionary", "Column", "Page"]
