"""Host-side string dictionaries backing varchar columns.

Reference: Trino's ``DictionaryBlock`` (``core/trino-spi/.../spi/block/
DictionaryBlock.java``) — there, an optimization; here, the *primary*
representation of strings: the device holds int32 codes, the host holds the
code -> UTF-8 mapping. Device-side string work (grouping, equality, ordering)
happens on codes; code order is made to match string order by sorting the
vocabulary at build time, so ORDER BY / min / max on varchar reduce to integer
ops on codes (SURVEY.md §7.1 "dictionary-first").
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

NULL_CODE = -1  # code used in the values array where the row is NULL


class Dictionary:
    """An ordered vocabulary: code i is the i-th smallest string.

    Invariant: ``values`` is sorted ascending (bytewise UTF-8, which matches
    Trino's collation-free varchar ordering), so ``code_a < code_b`` iff
    ``str_a < str_b``. This keeps ORDER BY and range predicates on varchar as
    pure integer comparisons on device.
    """

    __slots__ = ("values", "_index")

    def __init__(self, sorted_values: Sequence[str]):
        self.values: List[str] = list(sorted_values)
        self._index = {v: i for i, v in enumerate(self.values)}

    @classmethod
    def build(cls, strings: Iterable[Optional[str]]) -> "Dictionary":
        uniq = sorted({s for s in strings if s is not None})
        return cls(uniq)

    def __len__(self) -> int:
        return len(self.values)

    def encode(self, strings: Sequence[Optional[str]]) -> np.ndarray:
        out = np.empty(len(strings), dtype=np.int32)
        idx = self._index
        for i, s in enumerate(strings):
            out[i] = NULL_CODE if s is None else idx[s]
        return out

    def code_of(self, s: str) -> int:
        """Code for a literal, or -1 if absent (comparison will be all-false)."""
        return self._index.get(s, NULL_CODE)

    def lower_bound(self, s: str) -> int:
        """First code whose string >= s (for range predicates on varchar)."""
        import bisect

        return bisect.bisect_left(self.values, s)

    def decode(self, codes: np.ndarray) -> List[Optional[str]]:
        vals = self.values
        return [None if c == NULL_CODE else vals[int(c)] for c in codes]

    def decode_one(self, code: int) -> Optional[str]:
        return None if code == NULL_CODE else self.values[code]

    def merge(self, other: "Dictionary") -> "Dictionary":
        return Dictionary(sorted(set(self.values) | set(other.values)))

    def recode_table(self, target: "Dictionary") -> np.ndarray:
        """int32 mapping old code -> code in ``target`` (for cross-table ops)."""
        return np.array([target.code_of(v) for v in self.values], dtype=np.int32)
