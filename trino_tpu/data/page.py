"""Page/Column: the device-resident columnar batch.

Reference: ``core/trino-spi/.../spi/Page.java:31`` (Page = Block[] +
positionCount) and the Block hierarchy ``spi/block/`` (LongArrayBlock,
IntArrayBlock, VariableWidthBlock, DictionaryBlock, null masks per block).

TPU-first differences (SURVEY.md §7.1):
- A Column is a struct-of-arrays: ``values: jax.Array`` (+ optional
  ``nulls: jax.Array`` of bool, True = NULL) instead of a class hierarchy.
- Varchar values are int32 dictionary codes; the Dictionary lives host-side.
- A Page may carry a *selection mask* (``sel``) instead of being compacted:
  filters AND into ``sel`` so shapes stay static for XLA (no data-dependent
  compaction inside jit).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.data.dictionary import NULL_CODE, Dictionary


@dataclasses.dataclass
class Column:
    """``values.dtype`` is the column's PHYSICAL dtype and may be narrower
    than ``type.np_dtype`` (the logical width) for integer-kind, date, and
    decimal columns whose value range provably fits — the TPU analog of the
    reference's type-specialized codegen (``FlatHashStrategyCompiler``):
    int64 is emulated 2x int32 on TPU, so keys/dates that fit int32 sort,
    join, and group ~2x faster and cost half the HBM traffic. Arithmetic
    re-widens explicitly (ops/expr_lower casts operands to the result
    type's compute dtype), so narrowing never changes results.

    ``vrange`` is an optional static (min, max) bound on the stored values
    (storage repr — scaled ints for decimals, epoch days for dates), from
    connector stats. It licenses narrowing and lets the expression lowering
    skip int128 paths when interval arithmetic proves an int64 fit."""

    type: T.Type
    values: jnp.ndarray  # device array; int32 codes when type.is_varchar
    nulls: Optional[jnp.ndarray] = None  # bool[n], True where NULL; None = no nulls
    dictionary: Optional[Dictionary] = None  # required when type.is_varchar
    vrange: Optional[tuple] = None  # static (min, max) of values, Python ints
    # values are non-decreasing in row order (connector sort order, kept by
    # order-preserving ops: filter masks, stable compaction, probe-major
    # join expansion). Licenses the sort-free group/join fast paths —
    # lax.sort is the engine's dominant cost at scale, and TPC-H fact
    # tables arrive sorted by their join key (reference: LocalProperties
    # driving e.g. streaming aggregations).
    ascending: bool = False
    # Nested (array/map/row) columns: ``values`` holds per-row int32 element
    # counts (rows for RowType ignore it) and ``children`` the flattened
    # child columns — array: [elements], map: [keys, values], row: fields.
    # Reference: spi/block/ArrayBlock.java / MapBlock.java (offsets + child
    # blocks); lengths instead of offsets keep every row-parallel kernel
    # (sel/null masks) shape-compatible with scalar columns.
    children: Optional[List["Column"]] = None
    # Long-decimal (p > 18) high limb (reference: spi/type/Int128.java —
    # two-longs-per-position flat storage). Present when the column holds
    # (or, for unproven arithmetic results, MAY hold) values beyond int64:
    # ``values`` is then the low 64-bit pattern and ``hi`` the signed high
    # limb. Absent (None) = every value provably fits int64 and the column
    # rides the narrow single-array layout — the adaptive analog of the
    # reference's short/long decimal split, chosen from data/stats instead
    # of per type. Consumers without limb kernels degrade via
    # Executor._narrowed_or_flag (low word + deferred overflow check).
    hi: Optional[jnp.ndarray] = None

    def __post_init__(self):
        if self.type.is_varchar and self.dictionary is None:
            raise ValueError("varchar column requires a dictionary")
        if self.type.is_nested and self.children is None:
            raise ValueError(f"nested column {self.type} requires children")

    def __len__(self) -> int:
        return int(self.values.shape[0])

    def offsets(self) -> np.ndarray:
        """Host-side int64 offsets[n+1] derived from the stored lengths.

        Invariant: lengths always describe the flat child layout — a NULL
        row may still own flat elements (produced by device kernels whose
        null masks arrive after the fact); they are simply never read."""
        lens = np.asarray(self.values, dtype=np.int64)
        return np.concatenate([np.zeros(1, np.int64), np.cumsum(lens)])

    @classmethod
    def from_python(cls, typ: T.Type, data: Sequence) -> "Column":
        """Build a column from Python values (None = NULL). Host -> device."""
        n = len(data)
        has_null = any(v is None for v in data)
        nulls = (
            jnp.asarray(np.array([v is None for v in data], dtype=np.bool_))
            if has_null
            else None
        )
        if typ.is_varchar:
            if typ.is_varbinary:
                # bytes ride the dictionary as hex strings (hex order ==
                # unsigned-byte order, so comparisons/sorts agree)
                data = [v.hex() if isinstance(v, (bytes, bytearray)) else v
                        for v in data]
            d = Dictionary.build(data)
            codes = d.encode(list(data))
            return cls(typ, jnp.asarray(codes), nulls, d)
        if typ.is_nested:
            return cls._nested_from_python(typ, data, nulls)
        np_dtype = typ.np_dtype
        assert np_dtype is not None, f"unsupported type {typ}"
        fill = 0
        reprs = [fill if v is None else _to_repr(typ, v) for v in data]
        if typ.is_decimal and any(
            isinstance(r, int) and not -(2**63) <= r < 2**63 for r in reprs
        ):
            # long decimal beyond int64: two-limb storage (Int128.java)
            lo = np.array([r & (2**64 - 1) for r in reprs], dtype=np.uint64)
            hi = np.array([r >> 64 for r in reprs], dtype=np.int64)
            return cls(
                typ, jnp.asarray(lo.view(np.int64)), nulls, None, hi=jnp.asarray(hi)
            )
        arr = np.array(reprs, dtype=np_dtype)
        if n == 0:
            arr = np.empty(0, dtype=np_dtype)
        return cls(typ, jnp.asarray(arr), nulls, None)

    @classmethod
    def _nested_from_python(cls, typ: T.Type, data: Sequence, nulls) -> "Column":
        n = len(data)
        if isinstance(typ, T.RowType):
            kids = []
            for i, ft in enumerate(typ.field_types):
                kids.append(cls.from_python(ft, [None if r is None else r[i] for r in data]))
            return cls(typ, jnp.zeros((n,), jnp.int8), nulls, None, children=kids)
        if isinstance(typ, T.MapType):
            rows = [[] if m is None else sorted(m.items(), key=lambda kv: str(kv[0])) for m in data]
            lens = np.array([len(r) for r in rows], dtype=np.int32)
            keys = [k for r in rows for k, _ in r]
            vals = [v for r in rows for _, v in r]
            kids = [cls.from_python(typ.key, keys), cls.from_python(typ.value, vals)]
            return cls(typ, jnp.asarray(lens), nulls, None, children=kids)
        assert isinstance(typ, T.ArrayType)
        rows = [[] if a is None else list(a) for a in data]
        lens = np.array([len(r) for r in rows], dtype=np.int32)
        flat = [v for r in rows for v in r]
        return cls(
            typ, jnp.asarray(lens), nulls, None,
            children=[cls.from_python(typ.element, flat)],
        )

    def to_python(self) -> List:
        """Device -> host, decoding reprs back to Python values."""
        if self.type.is_nested:
            return self._nested_to_python()
        if self.hi is not None:
            his = np.asarray(self.hi).tolist()
            los = np.asarray(self.values).view(np.uint64).tolist()
            nulls = np.asarray(self.nulls).tolist() if self.nulls is not None else None
            out = [
                _from_repr(self.type, (h << 64) | l) for h, l in zip(his, los)
            ]
            if nulls is not None:
                out = [None if isnull else v for v, isnull in zip(out, nulls)]
            return out
        vals = np.asarray(self.values)
        nulls = np.asarray(self.nulls) if self.nulls is not None else None
        if self.type.is_varchar:
            assert self.dictionary is not None
            out = self.dictionary.decode(vals)
            if self.type.is_varbinary:
                out = [bytes.fromhex(v) if v is not None else v for v in out]
            if nulls is not None:
                out = [None if isnull else v for v, isnull in zip(out, nulls)]
            return out
        out = [_from_repr(self.type, v) for v in vals.tolist()]
        if nulls is not None:
            out = [None if isnull else v for v, isnull in zip(out, nulls)]
        return out

    def _nested_to_python(self) -> List:
        nulls = np.asarray(self.nulls) if self.nulls is not None else None
        if isinstance(self.type, T.RowType):
            fields = [c.to_python() for c in self.children]
            out = [tuple(f[i] for f in fields) for i in range(len(self))]
        else:
            off = self.offsets()
            kids = [c.to_python() for c in self.children]
            if isinstance(self.type, T.MapType):
                keys, vals = kids
                out = [
                    dict(zip(keys[off[i] : off[i + 1]], vals[off[i] : off[i + 1]]))
                    for i in range(len(self))
                ]
            else:
                (flat,) = kids
                out = [flat[off[i] : off[i + 1]] for i in range(len(self))]
        if nulls is not None:
            out = [None if isnull else v for v, isnull in zip(out, nulls)]
        return out


def fits_int32(vrange) -> bool:
    """True when a (min, max) range can ride int32 physically. The bounds
    are strict: the dtype max stays free for join sentinels and the min
    stays negation-safe for descending sort keys."""
    if vrange is None:
        return False
    lo, hi = vrange
    return -(2**31) < lo and hi < 2**31 - 1


def merge_vrange(a, b):
    """Union of two optional (min, max) ranges; None dominates (unknown)."""
    if a is None or b is None:
        return None
    return (min(a[0], b[0]), max(a[1], b[1]))


def _to_repr(typ: T.Type, v):
    """Python value -> device representation (int days, scaled int, ...)."""
    if isinstance(typ, T.TimestampType):
        import datetime

        unit = 10 ** typ.precision
        if isinstance(v, str):
            v = datetime.datetime.fromisoformat(v)
        if isinstance(v, datetime.datetime):
            if v.tzinfo is not None:
                v = v.astimezone(datetime.timezone.utc).replace(tzinfo=None)
            epoch = datetime.datetime(1970, 1, 1)
            delta = v - epoch
            micros = (delta.days * 86_400_000_000
                      + delta.seconds * 1_000_000 + delta.microseconds)
            return micros * unit // 1_000_000
        if isinstance(v, datetime.date):
            return (v - datetime.date(1970, 1, 1)).days * 86_400 * unit
        return int(v)
    if typ == T.DATE:
        if isinstance(v, str):
            import datetime

            d = datetime.date.fromisoformat(v)
            return (d - datetime.date(1970, 1, 1)).days
        import datetime

        if isinstance(v, datetime.date):
            return (v - datetime.date(1970, 1, 1)).days
        return int(v)
    if typ.is_decimal:
        assert isinstance(typ, T.DecimalType)
        import decimal
        from decimal import Decimal

        with decimal.localcontext() as ctx:
            ctx.prec = 60  # p=38 plus headroom: scaleb must not round
            return int(Decimal(str(v)).scaleb(typ.scale).to_integral_value())
    if typ == T.BOOLEAN:
        return bool(v)
    if typ.is_floating:
        return float(v)
    return int(v)


def _from_repr(typ: T.Type, r):
    if isinstance(typ, T.TimestampType):
        import datetime

        unit = 10 ** typ.precision
        micros = int(r) * 1_000_000 // unit
        base = datetime.datetime(
            1970, 1, 1,
            tzinfo=datetime.timezone.utc if typ.with_tz else None)
        return base + datetime.timedelta(microseconds=micros)
    if typ == T.DATE:
        import datetime

        return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(r))
    if typ.is_decimal:
        assert isinstance(typ, T.DecimalType)
        import decimal
        from decimal import Decimal

        with decimal.localcontext() as ctx:
            ctx.prec = 60
            return Decimal(r).scaleb(-typ.scale)
    if typ == T.BOOLEAN:
        return bool(r)
    if typ.is_floating:
        return float(r)
    return int(r)


def _concat_col(ca: Column, cb: Column) -> Column:
    va, vb = ca.values, cb.values
    if ca.type.is_nested:
        # lengths concatenate; children are flat, so their rows concatenate
        # too (offsets re-derive from the combined lengths, which by the
        # offsets() invariant describe the flat layout even for null rows).
        kids = [_concat_col(ka, kb) for ka, kb in zip(ca.children, cb.children)]
        vals = jnp.concatenate([va, vb])
        nulls = None
        if ca.nulls is not None or cb.nulls is not None:
            na = ca.nulls if ca.nulls is not None else jnp.zeros((len(ca),), bool)
            nb = cb.nulls if cb.nulls is not None else jnp.zeros((len(cb),), bool)
            nulls = jnp.concatenate([na, nb])
        return Column(ca.type, vals, nulls, None, children=kids)
    if va.dtype != vb.dtype:  # mixed physical widths: promote
        dt = jnp.promote_types(va.dtype, vb.dtype)
        va, vb = va.astype(dt), vb.astype(dt)
    d = ca.dictionary
    if ca.dictionary is not None and cb.dictionary is not None:
        if ca.dictionary is not cb.dictionary and ca.dictionary.values != cb.dictionary.values:
            d = ca.dictionary.merge(cb.dictionary)

            def recode(src_dict):
                t = np.asarray(src_dict.recode_table(d))
                # an all-NULL side has an empty vocab: pad so the gather
                # below stays in range (its codes are all NULL_CODE anyway)
                return jnp.asarray(t if len(t) else np.array([NULL_CODE], np.int32))

            va = jnp.where(va >= 0, recode(ca.dictionary)[jnp.clip(va, 0)], NULL_CODE)
            vb = jnp.where(vb >= 0, recode(cb.dictionary)[jnp.clip(vb, 0)], NULL_CODE)
    vals = jnp.concatenate([va, vb])
    if ca.nulls is None and cb.nulls is None:
        nulls = None
    else:
        na = ca.nulls if ca.nulls is not None else jnp.zeros((len(ca),), bool)
        nb = cb.nulls if cb.nulls is not None else jnp.zeros((len(cb),), bool)
        nulls = jnp.concatenate([na, nb])
    hi = None
    if ca.hi is not None or cb.hi is not None:
        # a missing hi limb is the sign extension of the low word
        ha = ca.hi if ca.hi is not None else (va.astype(jnp.int64) >> 63)
        hb = cb.hi if cb.hi is not None else (vb.astype(jnp.int64) >> 63)
        hi = jnp.concatenate([ha, hb])
    vr = None if hi is not None else merge_vrange(ca.vrange, cb.vrange)
    return Column(ca.type, vals, nulls, d, vr, hi=hi)


def host_take(c: Column, idx: np.ndarray, device: bool = True) -> Column:
    """Row gather on the HOST (numpy). The one gather path that supports
    nested columns: child segments are re-flattened by explicit offsets —
    a data-dependent-shape operation jit'd device code cannot express.

    ``device=False`` keeps the gathered arrays as numpy (no device_put):
    the host-consumption paths (``to_pylist`` — result rows headed
    straight to Python) would otherwise pay one device round trip per
    column just to read them back."""
    up = jnp.asarray if device else np.asarray
    if c.type.is_nested:
        nulls = np.asarray(c.nulls)[idx] if c.nulls is not None else None
        if isinstance(c.type, T.RowType):
            kids = [host_take(k, idx, device=device) for k in c.children]
            vals = np.asarray(c.values)[idx]
        else:
            off = c.offsets()
            lens = np.asarray(c.values, dtype=np.int64)
            vals = lens[idx].astype(np.int32)
            if len(idx):
                child_idx = np.concatenate(
                    [np.arange(off[i], off[i + 1], dtype=np.int64) for i in idx]
                )
            else:
                child_idx = np.zeros(0, np.int64)
            kids = [host_take(k, child_idx, device=device) for k in c.children]
        return Column(
            c.type, up(vals),
            up(nulls) if nulls is not None else None,
            None, None, children=kids,
        )
    # the sorted flag survives only order-preserving gathers (compact /
    # slice pass monotone indices; arbitrary permutations must drop it)
    monotone = bool(c.ascending) and (len(idx) < 2 or bool(np.all(np.diff(idx) >= 0)))
    return Column(
        c.type,
        up(np.asarray(c.values)[idx]),
        up(np.asarray(c.nulls)[idx]) if c.nulls is not None else None,
        c.dictionary,
        c.vrange,
        ascending=monotone,
        hi=up(np.asarray(c.hi)[idx]) if c.hi is not None else None,
    )


@dataclasses.dataclass
class Page:
    """A batch of rows: one Column per channel + optional selection mask.

    ``sel`` (bool[n], True = row is live) realizes filtering without
    compaction — XLA-friendly static shapes (SURVEY.md §7.3 item 1). ``None``
    means all rows live.

    ``replicated``: under SPMD execution (parallel/spmd.py), True means every
    device holds the same rows (post-broadcast/gather); False means this is a
    per-device shard. Purely host-side bookkeeping (not traced).
    """

    columns: List[Column]
    sel: Optional[jnp.ndarray] = None
    replicated: bool = False
    # sel (when present) is a LIVE PREFIX: rows [0, k) live, [k, n) dead —
    # the shape compact_to produces. Lets sorted-input fast paths treat
    # ascending columns as dead-tail-sorted without inspecting the mask.
    live_prefix: bool = False

    @property
    def num_rows(self) -> int:
        return 0 if not self.columns else len(self.columns[0])

    @property
    def channel_count(self) -> int:
        return len(self.columns)

    @classmethod
    def from_pydict(cls, schema: Dict[str, T.Type], data: Dict[str, Sequence]) -> "Page":
        return cls([Column.from_python(t, data[name]) for name, t in schema.items()])

    @staticmethod
    def concat_pages(a: "Page", b: "Page") -> "Page":
        """Row-wise concatenation (static shapes: n_a + n_b). Dictionaries are
        merged host-side with device recode gathers when they differ."""
        cols = [_concat_col(ca, cb) for ca, cb in zip(a.columns, b.columns)]
        sa = a.sel if a.sel is not None else jnp.ones((a.num_rows,), bool)
        sb = b.sel if b.sel is not None else jnp.ones((b.num_rows,), bool)
        return Page(cols, jnp.concatenate([sa, sb]), a.replicated and b.replicated)

    @staticmethod
    def all_dead(types: Sequence[T.Type]) -> "Page":
        """One all-dead row of the given types — the canonical empty page
        (zero-length arrays break downstream gathers: joins index
        counts[p], build.rows, etc., so 'empty' is 1 row with sel=False)."""
        def col_of(t: T.Type, nrows: int) -> Column:
            kids = (
                [col_of(ct, nrows if t.is_row else 0) for ct in T.type_children(t)]
                if t.is_nested
                else None
            )
            return Column(
                t,
                jnp.zeros((nrows,), t.np_dtype or np.dtype(np.int64)),
                None,
                Dictionary([""]) if t.is_varchar else None,
                children=kids,
            )

        return Page([col_of(t, 1) for t in types], jnp.zeros((1,), bool))

    def compact(self) -> "Page":
        """Drop dead rows (host-side gather). Used at wire boundaries: the
        serde (data/serde.py) carries no selection mask, so pages compact
        once before serialization — the DCN tier's analog of the reference
        compacting pages into the PartitionedOutputBuffer."""
        if self.sel is None:
            return self
        live = np.asarray(self.sel)
        idx = np.nonzero(live)[0]
        return Page([host_take(c, idx) for c in self.columns], None, self.replicated)

    def slice_rows(self, lo: int, hi: int) -> "Page":
        """Row-range view [lo, hi) of a compacted page (sel must be None) —
        the producer-side page chunker of the streaming output path."""
        assert self.sel is None, "slice_rows requires a compacted page"
        cols = [
            host_take(c, np.arange(lo, min(hi, len(c)), dtype=np.int64))
            if c.type.is_nested
            else Column(
                c.type,
                c.values[lo:hi],
                c.nulls[lo:hi] if c.nulls is not None else None,
                c.dictionary,
                c.vrange,
                ascending=c.ascending,
                hi=c.hi[lo:hi] if c.hi is not None else None,
            )
            for c in self.columns
        ]
        return Page(cols, None, self.replicated)

    def row_byte_estimate(self) -> int:
        """Rough serialized bytes per row (dtype widths; dictionaries are
        amortized) — sizes output chunks."""
        total = 0
        for c in self.columns:
            total += np.asarray(c.values).dtype.itemsize
            if c.nulls is not None:
                total += 1
            if c.children is not None and self.num_rows:
                # amortize flattened children over the parent row count
                for k in c.children:
                    total += max(
                        1, (len(k) * np.asarray(k.values).dtype.itemsize) // self.num_rows
                    )
        return max(total, 1)

    def live_count(self) -> int:
        if self.sel is None:
            return self.num_rows
        # host count: the mask is a bool vector headed for one scalar —
        # a jnp.sum here pays a device dispatch per call, and this is
        # called several times per query on the serving path
        return int(np.count_nonzero(np.asarray(self.sel)))

    def to_pylist(self) -> List[tuple]:
        """Materialize live rows as Python tuples (host side, test/CLI path).
        Compacts FIRST so per-row Python decode touches only live rows — a
        TopN page carries its full input capacity with a tiny live prefix,
        and decoding millions of dead slots would dwarf the query itself.
        The compacted intermediates stay on the HOST: the very next step
        is Python decode, so the device upload ``compact()`` pays at wire
        boundaries would be a per-column round trip bought for nothing
        (measured ~0.7ms per point query on the serving path)."""
        if self.sel is not None:
            idx = np.nonzero(np.asarray(self.sel))[0]
            page = Page([host_take(c, idx, device=False)
                         for c in self.columns], None, self.replicated)
        else:
            page = self
        cols = [c.to_python() for c in page.columns]
        return [tuple(col[i] for col in cols) for i in range(page.num_rows)]
