"""trino_tpu: a TPU-native distributed SQL query engine.

A ground-up re-design of the capabilities of Trino (reference:
losipiuk/trino, studied in SURVEY.md) for TPU hardware:

- The columnar data plane (reference ``core/trino-spi/.../spi/Page.java``,
  ``spi/block/*``) becomes device-resident struct-of-arrays batches of
  ``jax.Array`` columns with validity masks (``trino_tpu.data``).
- Query-time bytecode generation (reference ``sql/gen/ExpressionCompiler.java``)
  becomes tracing + ``jax.jit``: expression IR lowers to jax ops and XLA fuses
  the filter/project pipeline (``trino_tpu.ops.expr_lower``).
- Hash aggregation / hash join (reference ``operator/HashAggregationOperator``,
  ``operator/join/``) become vectorized sort/segment and lookup kernels that
  map onto the MXU/VPU (``trino_tpu.ops``).
- The repartition shuffle (reference ``operator/output/PartitionedOutputOperator``)
  becomes XLA ``all_to_all`` over ICI inside ``shard_map`` programs
  (``trino_tpu.parallel``).
- Everything sits behind a connector SPI (reference ``core/trino-spi``):
  ``trino_tpu.connector``.
"""

import jax

# SQL semantics require 64-bit integers (BIGINT) and doubles. JAX defaults to
# 32-bit; enable x64 before any arrays are created.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from trino_tpu.client.session import Session, execute  # noqa: E402,F401
