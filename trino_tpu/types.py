"""SQL type system.

Reference: Trino's SPI types (``core/trino-spi/src/main/java/io/trino/spi/type/``,
~90 files: BigintType, IntegerType, DoubleType, BooleanType, VarcharType,
DateType, DecimalType via Int128, TimestampType, ...). Here each SQL type maps
to a fixed-width device representation (TPUs want fixed-width):

- BOOLEAN            -> bool_
- TINYINT/SMALLINT/INTEGER/BIGINT -> int8/int16/int32/int64
- REAL/DOUBLE        -> float32/float64
- DATE               -> int32 (days since 1970-01-01)
- TIMESTAMP(6)       -> int64 (microseconds since epoch)
- DECIMAL(p, s)      -> int64 scaled by 10**s, plus an adaptive high limb
                        for p > 18 columns whose data exceeds int64
                        (ops/int128.py, reference Int128Math.java); results
                        past 10^38 raise DECIMAL_OVERFLOW (see decimal())
- VARCHAR/CHAR       -> int32 dictionary codes; the dictionary (the actual
                        UTF-8 strings) lives host-side (data/dictionary.py).
                        TPUs excel at fixed width; strings are dictionary-first
                        (SURVEY.md §7.1).

Nulls are carried out-of-band as boolean masks on columns, three-valued logic
is implemented in the expression lowering (ops/expr_lower.py).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Type:
    """A SQL type. Instances are interned/compared by value."""

    name: str  # canonical SQL name, e.g. "bigint", "varchar", "decimal(15,2)"
    np_dtype: Optional[np.dtype]  # device representation; None => not yet supported
    comparable: bool = True
    orderable: bool = True

    def __str__(self) -> str:
        return self.name

    @property
    def is_varchar(self) -> bool:
        # "varchar-kind" = dictionary-coded on device (int32 codes, values
        # host-side). VARBINARY deliberately rides the same machinery —
        # its dictionary stores hex encodings, decoded to bytes at the
        # boundary (to_python/literals) — so joins/grouping/serde work
        # unchanged (reference: VarbinaryType is its own Block type there;
        # here the fixed-width dictionary layout is the TPU-first choice
        # for ALL variable-width values).
        return (self.name.startswith("varchar") or self.name.startswith("char")
                or self.name == "varbinary")

    @property
    def is_varbinary(self) -> bool:
        return self.name == "varbinary"

    @property
    def is_timestamp(self) -> bool:
        return self.name.startswith("timestamp")

    @property
    def is_decimal(self) -> bool:
        return self.name.startswith("decimal")

    @property
    def is_integer_kind(self) -> bool:
        return self.name in ("tinyint", "smallint", "integer", "bigint")

    @property
    def is_floating(self) -> bool:
        return self.name in ("real", "double")

    @property
    def is_numeric(self) -> bool:
        return self.is_integer_kind or self.is_floating or self.is_decimal

    @property
    def is_array(self) -> bool:
        return self.name.startswith("array(")

    @property
    def is_map(self) -> bool:
        return self.name.startswith("map(")

    @property
    def is_row(self) -> bool:
        return self.name.startswith("row(")

    @property
    def is_nested(self) -> bool:
        """Container types: device layout is per-row lengths (int32) plus
        flattened child columns (data/page.py Column.children)."""
        return self.is_array or self.is_map or self.is_row


BOOLEAN = Type("boolean", np.dtype(np.bool_))
TINYINT = Type("tinyint", np.dtype(np.int8))
SMALLINT = Type("smallint", np.dtype(np.int16))
INTEGER = Type("integer", np.dtype(np.int32))
BIGINT = Type("bigint", np.dtype(np.int64))
REAL = Type("real", np.dtype(np.float32))
DOUBLE = Type("double", np.dtype(np.float64))
DATE = Type("date", np.dtype(np.int32))
UNKNOWN = Type("unknown", None)  # type of NULL literal before coercion
# VARBINARY: dictionary-coded like varchar; dictionary entries are HEX
# strings of the bytes (lexicographic hex order == bytes order, so sorts
# and range comparisons agree with the reference's unsigned-byte order).
VARBINARY = Type("varbinary", np.dtype(np.int32), orderable=True)


@dataclasses.dataclass(frozen=True)
class TimestampType(Type):
    """timestamp(p) [with time zone]. Reference: ``spi/type/TimestampType``
    / ``TimestampWithTimeZoneType`` (p in 0..12 there; 0..9 here — the
    picosecond tail would not fit the int64 epoch span). Storage: int64
    count of 10^-p second units since the epoch, UTC. The tz variant
    stores the UTC instant; zone is rendering metadata (the reference
    packs a zone id per value — a fixed-offset subset is supported via
    AT TIME ZONE)."""

    precision: int = 6
    with_tz: bool = False

    def __str__(self) -> str:
        return self.name


def timestamp(precision: int = 6, with_tz: bool = False) -> TimestampType:
    if not 0 <= precision <= 9:
        raise ValueError(f"timestamp precision out of range: {precision}")
    name = f"timestamp({precision})" + (" with time zone" if with_tz else "")
    return TimestampType(name=name, np_dtype=np.dtype(np.int64),
                         precision=precision, with_tz=with_tz)


# TIMESTAMP(6) — microsecond precision, the engine default.
TIMESTAMP = timestamp(6)


@dataclasses.dataclass(frozen=True)
class DecimalType(Type):
    precision: int = 38
    scale: int = 0

    def __str__(self) -> str:
        return self.name


def decimal(precision: int, scale: int) -> DecimalType:
    if not 1 <= precision <= 38:
        raise ValueError(f"decimal precision out of range: {precision}")
    # Storage is a scaled int64, plus an ADAPTIVE second limb for p > 18
    # columns whose data exceeds int64 (data/page.py Column.hi — the
    # reference's short/long decimal split, spi/type/Int128.java, decided
    # per column from the data). Arithmetic routes through the int128 limb
    # kernels (ops/int128.py, reference Int128Math.java); results past the
    # 10^38 cap raise the deferred DECIMAL_OVERFLOW error.
    return DecimalType(
        name=f"decimal({precision},{scale})",
        np_dtype=np.dtype(np.int64),
        precision=precision,
        scale=scale,
    )


@dataclasses.dataclass(frozen=True)
class VarcharType(Type):
    length: Optional[int] = None  # None = unbounded

    def __str__(self) -> str:
        return self.name


def varchar(length: Optional[int] = None) -> VarcharType:
    name = "varchar" if length is None else f"varchar({length})"
    return VarcharType(name=name, np_dtype=np.dtype(np.int32), length=length)


def char(length: int) -> VarcharType:
    # CHAR semantics (pad/compare) are normalized to varchar at load time.
    return VarcharType(name=f"char({length})", np_dtype=np.dtype(np.int32), length=length)


VARCHAR = varchar()


@dataclasses.dataclass(frozen=True)
class ArrayType(Type):
    """array(E). Reference: ``spi/type/ArrayType.java`` + ``spi/block/
    ArrayBlock.java`` (offsets + element block). Device layout here is
    struct-of-arrays: per-row int32 *lengths* ride ``Column.values`` (offsets
    are their prefix sum) and the flattened elements ride ``Column.children
    [0]`` — lengths rather than offsets so a length-n column keeps n slots
    and every row-parallel kernel (sel masks, null masks) applies unchanged."""

    element: Optional["Type"] = None


def array_of(element: Type) -> ArrayType:
    return ArrayType(
        name=f"array({element.name})",
        np_dtype=np.dtype(np.int32),  # physical: per-row element count
        orderable=False,
        element=element,
    )


@dataclasses.dataclass(frozen=True)
class MapType(Type):
    """map(K, V). Reference: ``spi/type/MapType.java`` / ``MapBlock.java``.
    Layout: per-row entry counts + two flattened children (keys, values)."""

    key: Optional["Type"] = None
    value: Optional["Type"] = None


def map_of(key: Type, value: Type) -> MapType:
    return MapType(
        name=f"map({key.name}, {value.name})",
        np_dtype=np.dtype(np.int32),
        comparable=False,
        orderable=False,
        key=key,
        value=value,
    )


@dataclasses.dataclass(frozen=True)
class RowType(Type):
    """row(f1 T1, ...). Reference: ``spi/type/RowType.java`` / ``RowBlock``.
    Layout: one child column per field (no lengths; ``Column.values`` is a
    placeholder zeros array so row-count machinery keeps working)."""

    field_names: Tuple[str, ...] = ()
    field_types: Tuple["Type", ...] = ()


def row_of(fields) -> RowType:
    """fields: sequence of (name|None, Type)."""
    names = tuple(n if n is not None else f"field{i}" for i, (n, _) in enumerate(fields))
    ftypes = tuple(t for _, t in fields)
    inner = ", ".join(
        f"{n} {t.name}" if n is not None else t.name for (n, _), t in zip(fields, ftypes)
    )
    return RowType(
        name=f"row({inner})",
        np_dtype=np.dtype(np.int8),
        orderable=False,
        field_names=names,
        field_types=ftypes,
    )


def type_children(t: Type):
    """The flattened child types a nested column carries, in child order."""
    if isinstance(t, ArrayType):
        return [t.element]
    if isinstance(t, MapType):
        return [t.key, t.value]
    if isinstance(t, RowType):
        return list(t.field_types)
    return []


import re as _re

_TS_RE = _re.compile(r"timestamp(?:\((\d+)\))?( with time zone)?")


def parse_type(s: str) -> Type:
    """Parse a SQL type string, e.g. ``decimal(15,2)``, ``varchar(25)``."""
    s = s.strip().lower()
    simple = {
        "boolean": BOOLEAN,
        "tinyint": TINYINT,
        "smallint": SMALLINT,
        "int": INTEGER,
        "integer": INTEGER,
        "bigint": BIGINT,
        "real": REAL,
        "double": DOUBLE,
        "double precision": DOUBLE,
        "date": DATE,
        "timestamp": TIMESTAMP,
        "varchar": VARCHAR,
        "varbinary": VARBINARY,
        "unknown": UNKNOWN,
    }
    if s in simple:
        return simple[s]
    m = _TS_RE.fullmatch(s)
    if m:
        p = int(m.group(1)) if m.group(1) is not None else 6
        return timestamp(p, with_tz=m.group(2) is not None)
    if s.startswith("decimal(") and s.endswith(")"):
        p, sc = s[len("decimal(") : -1].split(",")
        return decimal(int(p), int(sc))
    if s.startswith("varchar(") and s.endswith(")"):
        return varchar(int(s[len("varchar(") : -1]))
    if s.startswith("char(") and s.endswith(")"):
        return char(int(s[len("char(") : -1]))
    if s.startswith("array(") and s.endswith(")"):
        return array_of(parse_type(s[len("array(") : -1]))
    if s.startswith("map(") and s.endswith(")"):
        k, v = _split_top_level(s[len("map(") : -1])
        return map_of(parse_type(k), parse_type(v))
    if s.startswith("row(") and s.endswith(")"):
        fields = []
        for part in _split_all_top_level(s[len("row(") : -1]):
            part = part.strip()
            # "name type" or bare "type"
            sp = part.find(" ")
            if sp > 0 and not part[:sp].endswith("("):
                try:
                    fields.append((part[:sp], parse_type(part[sp + 1 :])))
                    continue
                except ValueError:
                    pass
            fields.append((None, parse_type(part)))
        return row_of(fields)
    raise ValueError(f"unknown type: {s}")


def _split_all_top_level(s: str):
    """Split on commas not nested inside parentheses."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(s[start:i])
            start = i + 1
    parts.append(s[start:])
    return parts


def _split_top_level(s: str):
    parts = _split_all_top_level(s)
    if len(parts) != 2:
        raise ValueError(f"expected two type arguments in {s!r}")
    return parts[0].strip(), parts[1].strip()


# ---------------------------------------------------------------------------
# Type coercion (reference: io.trino.type.TypeCoercion / function resolution in
# core/trino-main/.../metadata — simplified numeric promotion lattice).
# ---------------------------------------------------------------------------

_INT_ORDER = ["tinyint", "smallint", "integer", "bigint"]


def common_super_type(a: Type, b: Type) -> Optional[Type]:
    """Least common type two operands coerce to, or None if incompatible."""
    if a == b:
        return a
    if a == UNKNOWN:
        return b
    if b == UNKNOWN:
        return a
    if a.is_integer_kind and b.is_integer_kind:
        ia, ib = _INT_ORDER.index(a.name), _INT_ORDER.index(b.name)
        return parse_type(_INT_ORDER[max(ia, ib)])
    if a.is_floating and b.is_floating:
        return DOUBLE
    if (a.is_floating and b.is_numeric) or (b.is_floating and a.is_numeric):
        return DOUBLE if DOUBLE in (a, b) or a.is_decimal or b.is_decimal else REAL
    if a.is_decimal and b.is_integer_kind:
        return _decimal_int_super(a, b)
    if b.is_decimal and a.is_integer_kind:
        return _decimal_int_super(b, a)
    if a.is_decimal and b.is_decimal:
        assert isinstance(a, DecimalType) and isinstance(b, DecimalType)
        scale = max(a.scale, b.scale)
        ip = max(a.precision - a.scale, b.precision - b.scale)
        return decimal(min(38, ip + scale), scale)
    if a.is_varchar and b.is_varchar:
        return VARCHAR
    if isinstance(a, ArrayType) and isinstance(b, ArrayType):
        e = common_super_type(a.element, b.element)
        return array_of(e) if e is not None else None
    if isinstance(a, MapType) and isinstance(b, MapType):
        k = common_super_type(a.key, b.key)
        v = common_super_type(a.value, b.value)
        return map_of(k, v) if k is not None and v is not None else None
    if isinstance(a, TimestampType) and isinstance(b, TimestampType):
        if a.with_tz != b.with_tz:
            return None
        return timestamp(max(a.precision, b.precision), a.with_tz)
    if a == DATE and isinstance(b, TimestampType) and not b.with_tz:
        return b
    if b == DATE and isinstance(a, TimestampType) and not a.with_tz:
        return a
    return None


def _decimal_int_super(d: Type, i: Type) -> Type:
    assert isinstance(d, DecimalType)
    int_digits = {"tinyint": 3, "smallint": 5, "integer": 10, "bigint": 19}[i.name]
    ip = max(d.precision - d.scale, int_digits)
    return decimal(min(38, ip + d.scale), d.scale)
