"""Materialized-view registry: the coordinator-owned metadata store.

Reference: the reference engine's materialized views live in connector
metadata (``ConnectorMetadata.getMaterializedView`` returning a
``ConnectorMaterializedViewDefinition`` + ``getMaterializedViewFreshness``
deciding staleness); here the engine owns one registry per server —
shared by every query the coordinator runs (like the prepared-statement
registry) and replicated across the PR 12 executor-process plane via the
``system.runtime.sync_materialized_view`` procedure.

Each entry records everything the transparent-substitution pass
(``matview/substitute.py``) needs to decide *match* and *freshness*
without re-planning the definition:

- the **canonical plan fingerprint** of the optimized defining query
  (``cache/plan_key.canonicalize_plan``), recomputed at every REFRESH so
  it reflects the catalog state the stored rows were computed from —
  plus canonicals for each select-item *prefix* of the definition (the
  projection-subsumption stretch match);
- the **base-table data versions** captured when the REFRESH planned
  (before it executed — a mid-refresh mutation makes the view stale,
  never wrong);
- the **storage version** of the backing table after the atomic swap, so
  an out-of-band mutation (or DROP) of the storage suppresses
  substitution too.

The registry is pure metadata — no jax imports — so the docs gates and
the system-catalog schema module can load it standalone.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class MaterializedView:
    """One registered materialized view (metadata only; rows live in the
    storage table behind the connector write SPI)."""

    catalog: str
    schema: str
    name: str
    definition_sql: str            # the defining query's SQL text
    definition: object             # parsed ast.Query
    owner: str                     # creating principal
    # name resolution defaults captured at CREATE: unqualified tables in
    # the definition must keep resolving against the CREATOR's defaults,
    # whatever session later expands or refreshes the view
    default_catalog: str = "tpch"
    default_schema: str = "tiny"
    storage_catalog: str = ""
    storage_schema: str = ""
    storage_table: str = ""
    column_names: Tuple[str, ...] = ()
    column_types: tuple = ()       # engine Type objects, definition order
    base_tables: Tuple[tuple, ...] = ()   # ((catalog, schema, table), ...)
    # canonical plan string of the optimized definition (match key) and
    # the prefix-projection variants: canonical -> column prefix width
    canonical: Optional[str] = None
    prefix_canonicals: Dict[str, int] = dataclasses.field(default_factory=dict)
    # freshness state, written atomically at REFRESH
    base_versions: Optional[tuple] = None   # (((c, s, t), version), ...)
    storage_version: Optional[str] = None
    last_refresh: Optional[float] = None
    created_at: float = dataclasses.field(default_factory=time.time)
    hits: int = 0
    refreshes: int = 0

    @property
    def qualified(self) -> str:
        return f"{self.catalog}.{self.schema}.{self.name}"

    @property
    def storage_qualified(self) -> str:
        return (f"{self.storage_catalog}.{self.storage_schema}"
                f".{self.storage_table}")


class MaterializedViewRegistry:
    """Thread-safe (catalog, schema, name) -> MaterializedView map.

    Server-wide like the prepared-statement registry: CREATE on one query
    is substitutable by the next, whatever lane/thread runs it. Embedded
    sessions get a private instance (client/session.py)."""

    def __init__(self):
        self._entries: Dict[tuple, MaterializedView] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(catalog: str, schema: str, name: str) -> tuple:
        return (catalog.lower(), schema.lower(), name.lower())

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def empty(self) -> bool:
        with self._lock:
            return not self._entries

    def put(self, mv: MaterializedView) -> None:
        with self._lock:
            self._entries[self._key(mv.catalog, mv.schema, mv.name)] = mv

    def get(self, catalog: str, schema: str, name: str
            ) -> Optional[MaterializedView]:
        with self._lock:
            return self._entries.get(self._key(catalog, schema, name))

    def remove(self, catalog: str, schema: str, name: str
               ) -> Optional[MaterializedView]:
        with self._lock:
            return self._entries.pop(self._key(catalog, schema, name), None)

    def snapshot(self) -> List[MaterializedView]:
        """Entry list sorted by qualified name (system-table row order)."""
        with self._lock:
            return [self._entries[k] for k in sorted(self._entries)]

    def record_hit(self, catalog: str, schema: str, name: str) -> None:
        with self._lock:
            mv = self._entries.get(self._key(catalog, schema, name))
            if mv is not None:
                mv.hits += 1

    def publish_refresh(self, mv: MaterializedView, base_versions,
                        storage_version: str, canonical: str,
                        prefix_canonicals: Dict[str, int]) -> None:
        """The REFRESH commit point: one locked write flips the match
        keys and the freshness evidence together, so a concurrent
        substitution sees either the pre-refresh state (stale -> falls
        back) or the complete post-refresh state — never a torn mix."""
        with self._lock:
            mv.base_versions = tuple(base_versions)
            mv.base_tables = tuple(tuple(k) for k, _v in base_versions)
            mv.storage_version = str(storage_version)
            mv.canonical = canonical
            mv.prefix_canonicals = dict(prefix_canonicals)
            mv.last_refresh = time.time()
            mv.refreshes += 1


# ------------------------------------------------- cross-process payload
def to_payload(mv: MaterializedView) -> dict:
    """JSON-shaped registry entry for the executor-process sync procedure
    (``CALL system.runtime.sync_materialized_view('<json>')``). Column
    types serialize as their SQL spellings; the definition ships as SQL
    and re-parses on the receiving side."""
    return {
        "op": "put",
        "catalog": mv.catalog, "schema": mv.schema, "name": mv.name,
        "definitionSql": mv.definition_sql,
        "owner": mv.owner,
        "defaultCatalog": mv.default_catalog,
        "defaultSchema": mv.default_schema,
        "storageCatalog": mv.storage_catalog,
        "storageSchema": mv.storage_schema,
        "storageTable": mv.storage_table,
        "columnNames": list(mv.column_names),
        "columnTypes": [str(t) for t in mv.column_types],
        "baseTables": [list(t) for t in mv.base_tables],
        "canonical": mv.canonical,
        "prefixCanonicals": dict(mv.prefix_canonicals),
        "baseVersions": ([[list(k), v] for k, v in mv.base_versions]
                         if mv.base_versions is not None else None),
        "storageVersion": mv.storage_version,
        "lastRefresh": mv.last_refresh,
        "createdAt": mv.created_at,
    }


def drop_payload(catalog: str, schema: str, name: str) -> dict:
    return {"op": "drop", "catalog": catalog, "schema": schema,
            "name": name}


def from_payload(payload: dict) -> MaterializedView:
    from trino_tpu import types as T
    from trino_tpu.sql.parser import ast
    from trino_tpu.sql.parser.parser import parse_statement

    definition = parse_statement(payload["definitionSql"])
    if isinstance(definition, ast.CreateMaterializedView):
        # definition_sql kept the FULL statement text (a shape the
        # prefix-stripping regex could not take apart): unwrap the query
        definition = definition.query
    return MaterializedView(
        catalog=payload["catalog"], schema=payload["schema"],
        name=payload["name"],
        definition_sql=payload["definitionSql"],
        definition=definition,
        owner=payload.get("owner", "anonymous"),
        default_catalog=payload.get("defaultCatalog", "tpch"),
        default_schema=payload.get("defaultSchema", "tiny"),
        storage_catalog=payload["storageCatalog"],
        storage_schema=payload["storageSchema"],
        storage_table=payload["storageTable"],
        column_names=tuple(payload.get("columnNames") or ()),
        column_types=tuple(
            T.parse_type(t) for t in payload.get("columnTypes") or ()),
        base_tables=tuple(
            tuple(t) for t in payload.get("baseTables") or ()),
        canonical=payload.get("canonical"),
        prefix_canonicals={
            str(k): int(v)
            for k, v in (payload.get("prefixCanonicals") or {}).items()},
        base_versions=(
            tuple((tuple(k), v) for k, v in payload["baseVersions"])
            if payload.get("baseVersions") is not None else None),
        storage_version=payload.get("storageVersion"),
        last_refresh=payload.get("lastRefresh"),
        created_at=payload.get("createdAt") or time.time(),
    )
