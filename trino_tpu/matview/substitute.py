"""Transparent materialized-view substitution: the optimizer's MV pass.

Reference: the reference planner's ``getMaterializedView`` flow — when a
query references a fresh materialized view, the plan reads the storage
table instead of the view query. This engine goes further (there is no
view *name* required): the pass matches a query's **optimized plan
subtree** against every registered MV definition by canonical plan
fingerprint (``cache/plan_key.canonicalize_plan`` — the exact machinery
the result cache keys on), so a repeated q3-shaped join+aggregate turns
into a table scan of the precomputed storage table whether or not the
user ever mentions the view. The scan then lands on the device-cache
tiers (PR 7/14): a fresh hit is a warm-HBM scan instead of a sort-merge
join.

Correctness contract:

- substitution happens ONLY when the view is **fresh**: every base-table
  ``data_version`` captured when the REFRESH planned still matches the
  connector's current token, the storage table still exists, and its own
  version still matches the one recorded at the swap. Anything else —
  including a never-refreshed view, a mid-refresh mutation, or an
  out-of-band storage edit — falls back to the base plan. Stale never
  means wrong rows; it means the join runs.
- per-user access control re-fires: the substituting principal must be
  allowed to SELECT every base table of the definition (a storage scan
  must not launder table grants through the view).
- sessions inside an explicit transaction never substitute (their reads
  go through copy-on-write overlay connectors whose versions are not the
  registry's vocabulary).
- the rewritten tree is COPY-ON-WRITE: plans can be shared with the
  logical-plan cache, so ancestors of a substituted subtree are shallow-
  copied and the cached tree is never mutated.

The caller threads the returned substitutions into the result-cache key:
the captured versions of a substituted plan are the STORAGE version plus
the view's recorded BASE versions, so both a REFRESH and a base-table
DML invalidate cached results naturally.
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from trino_tpu.matview.registry import MaterializedView
from trino_tpu.sql.planner import plan as P


def substitution_enabled(session) -> bool:
    v = (getattr(session, "properties", None) or {}).get(
        "materialized_view_substitution", True)
    return str(v).lower() not in ("false", "0", "no")


def staleness_reason(catalogs, mv: MaterializedView) -> Optional[str]:
    """None when the view is fresh (substitutable); else a human-readable
    reason. Shared by the substitution pass, EXPLAIN annotations, and
    ``system.metadata.materialized_views``."""
    if mv.base_versions is None:
        return "never refreshed"
    for (c, s, t), v in mv.base_versions:
        conn = catalogs.get(c)
        try:
            cur = conn.data_version(s, t) if conn is not None else None
        except Exception:  # noqa: BLE001 — unreadable == stale
            cur = None
        if cur is None or str(cur) != v:
            return f"base table {c}.{s}.{t} moved ({v} -> {cur})"
    sconn = catalogs.get(mv.storage_catalog)
    try:
        meta = (sconn.get_table(mv.storage_schema, mv.storage_table)
                if sconn is not None else None)
    except Exception:  # noqa: BLE001 — unreadable == stale, never fail
        meta = None
    if meta is None:
        return f"storage table {mv.storage_qualified} missing"
    try:
        cur = sconn.data_version(mv.storage_schema, mv.storage_table)
    except Exception:  # noqa: BLE001 — unreadable == stale, never fail
        cur = None
    if cur is None or str(cur) != mv.storage_version:
        return (f"storage version moved "
                f"({mv.storage_version} -> {cur})")
    return None


def _access_denied_reason(session, mv: MaterializedView) -> Optional[str]:
    """Re-fire plan-time access control on the defining query's base
    tables for the CURRENT principal (the reference's view-security
    check): a denied base table suppresses substitution."""
    ac = getattr(session, "access_control", None)
    if ac is None:
        return None
    identity = getattr(session, "identity", None)
    for c, s, t in mv.base_tables:
        try:
            ac.check_can_select(identity, c, s, t)
        except PermissionError:
            return f"access denied on base table {c}.{s}.{t}"
    return None


def _scan_table_sets(root: P.PlanNode) -> Dict[int, frozenset]:
    """node id -> frozenset of (catalog, schema, table) the subtree
    scans — the cheap prefilter before canonicalizing a subtree."""
    out: Dict[int, frozenset] = {}

    def walk(node: P.PlanNode) -> frozenset:
        if isinstance(node, P.TableScanNode):
            s = frozenset({(node.catalog, node.schema, node.table)})
        else:
            s = frozenset()
            for child in node.sources:
                s = s | walk(child)
        out[node.id] = s
        return s

    walk(root)
    return out


def _set_sources(node: P.PlanNode, sources: List[P.PlanNode]) -> None:
    if isinstance(node, (P.JoinNode, P.SetOpNode)):
        node.left, node.right = sources
    elif isinstance(node, P.UnionNode):
        node.sources_ = list(sources)
    elif sources:
        node.source = sources[0]


def _storage_scan(mv: MaterializedView, subtree: P.PlanNode,
                  width: Optional[int]) -> P.TableScanNode:
    """The replacement scan over the MV storage table: full width for an
    exact match, the leading ``width`` columns for a prefix match. Types
    come from the MATCHED subtree so the channel contract (and the plan
    sanity checker) hold exactly."""
    k = width if width is not None else len(mv.column_names)
    return P.TableScanNode(
        catalog=mv.storage_catalog, schema=mv.storage_schema,
        table=mv.storage_table,
        column_names=list(mv.column_names[:k]),
        column_types=list(subtree.output_types),
        mv_name=mv.qualified,
    )


def substitute_plan(session, root: P.PlanNode
                    ) -> Tuple[P.PlanNode, List[dict]]:
    """Match ``root``'s subtrees against the session's registered MVs and
    rewrite fresh matches into storage-table scans. Returns
    ``(new_root, substitution notes)`` — ``new_root`` is ``root`` itself
    when nothing substituted (the input tree is never mutated). Notes:
    ``{"view", "result": "substituted"|"stale"|"access-denied",
    "reason", "prefix"}`` — one per decided match, for EXPLAIN headers,
    queryStats.mvHits, and the substitution metric."""
    registry = getattr(session, "matviews", None)
    if registry is None or registry.empty():
        return root, []
    if not substitution_enabled(session):
        return root, []
    if getattr(session, "transaction", None) is not None:
        return root, []
    # candidate table: canonical -> (mv, prefix width or None). Views
    # without a completed REFRESH have nothing to substitute.
    candidates: Dict[str, tuple] = {}
    base_sets: List[frozenset] = []
    for mv in registry.snapshot():
        if mv.base_versions is None or not mv.canonical:
            continue
        candidates[mv.canonical] = (mv, None)
        for canon, k in mv.prefix_canonicals.items():
            candidates.setdefault(canon, (mv, k))
        base_sets.append(frozenset(tuple(t) for t in mv.base_tables))
    if not candidates:
        return root, []

    from trino_tpu.cache.plan_key import canonicalize_plan
    from trino_tpu.obs import metrics as M
    from trino_tpu.obs import trace as tracing

    tables_of = _scan_table_sets(root)
    notes: List[dict] = []
    mv_by_name: Dict[str, MaterializedView] = {}
    decided: set = set()  # view names already decided stale/denied
    # the freshness verdict is memoized per view for the duration of the
    # pass: a plan with N subtrees matching one view pays the live
    # data_version probes once, and the verdict stays consistent across
    # all N decisions even if a REFRESH lands mid-pass
    freshness: Dict[str, Optional[str]] = {}

    def _reason(mv: MaterializedView) -> Optional[str]:
        if mv.qualified not in freshness:
            freshness[mv.qualified] = (
                staleness_reason(session.catalogs, mv)
                or _access_denied_reason(session, mv))
        return freshness[mv.qualified]

    def try_match(node: P.PlanNode) -> Optional[P.TableScanNode]:
        if isinstance(node, (P.OutputNode, P.ValuesNode)):
            return None
        if not any(tables_of[node.id] == s for s in base_sets):
            return None
        hit = candidates.get(canonicalize_plan(node))
        if hit is None:
            return None
        mv, width = hit
        mv_by_name[mv.qualified] = mv
        reason = _reason(mv)
        if reason is not None:
            if mv.qualified not in decided:
                decided.add(mv.qualified)
                result = ("access-denied" if reason.startswith("access")
                          else "stale")
                notes.append({"view": mv.qualified, "result": result,
                              "reason": reason, "prefix": width})
            return None
        notes.append({"view": mv.qualified, "result": "substituted",
                      "reason": None, "prefix": width})
        return _storage_scan(mv, node, width)

    def rewrite(node: P.PlanNode) -> P.PlanNode:
        scan = try_match(node)
        if scan is not None:
            return scan
        srcs = list(node.sources)
        new_srcs = [rewrite(s) for s in srcs]
        if all(n is s for n, s in zip(new_srcs, srcs)):
            return node
        # copy-on-write: the input tree may be shared with the plan
        # cache — ancestors of a substitution are shallow-copied,
        # untouched sibling subtrees are shared into the new tree
        clone = copy.copy(node)
        _set_sources(clone, new_srcs)
        return clone

    with tracing.span("plan/mv-substitute") as sp:
        new_root = rewrite(root)
        if new_root is not root:
            # the rewrite must uphold every plan invariant (arity/
            # channel/type): a bad substitution falls back to the base
            # plan, never fails the query or corrupts rows
            try:
                from trino_tpu.sql.planner.sanity import validate_plan

                validate_plan(new_root, phase="mv-substitute")
            except Exception:  # noqa: BLE001 — containment: base plan
                for n in notes:
                    if n["result"] == "substituted":
                        n["result"] = "invalid"
                        n["reason"] = "substituted plan failed validation"
                new_root = root
        # metrics + hit counters AFTER the validation verdict, so a
        # contained invalid rewrite never counts as 'substituted'
        for n in notes:
            M.MV_SUBSTITUTIONS.inc(1, n["result"])
            if n["result"] == "substituted":
                mv = mv_by_name[n["view"]]
                registry.record_hit(mv.catalog, mv.schema, mv.name)
        substituted = [n for n in notes if n["result"] == "substituted"]
        sp.set("candidates", len(candidates))
        sp.set("substituted", len(substituted))
        if notes:
            sp.set("views", ",".join(sorted({n["view"] for n in notes})))
            sp.set("results", ",".join(n["result"] for n in notes))
    return new_root, notes


def substitution_versions(session, root: P.PlanNode,
                          notes: List[dict]) -> Optional[list]:
    """The captured data versions of a substituted plan for result-cache
    keying: the plan's own scanned versions (storage + any unsubstituted
    scans) UNION every substituted view's recorded base versions — so a
    REFRESH (storage version moves) and a base-table DML (base version
    moves) both invalidate cached results. None when any component is
    unversioned (the cache then bypasses)."""
    from trino_tpu.cache.plan_key import capture_versions

    versions = capture_versions(session, root)
    if versions is None:
        return None
    merged = dict(versions)
    registry = getattr(session, "matviews", None)
    if registry is None:
        return list(versions)
    seen = {n["view"] for n in notes if n["result"] == "substituted"}
    for mv in registry.snapshot():
        if mv.qualified in seen and mv.base_versions is not None:
            for key, v in mv.base_versions:
                merged.setdefault(tuple(key), v)
    return sorted(merged.items())
