"""Materialized views: version-fresh precomputation served as table scans.

Three pieces (ROADMAP item 5b; reference: the connector-SPI materialized
view flow — ``getMaterializedView`` / ``MaterializedViewFreshness``):

- ``registry.py`` — the coordinator-owned metadata store (definitions,
  storage location, canonical match keys, per-refresh base/storage data
  versions), replicated across the executor-process plane;
- ``substitute.py`` — the transparent planner pass: a query subtree whose
  canonical plan fingerprint equals a FRESH view's definition rewrites
  into a scan of the precomputed storage table (which the device cache
  then serves from warm HBM);
- ``lifecycle.py`` — CREATE / REFRESH / DROP execution over the plain
  connector write SPI, with the atomic version swap that makes staleness
  a provable, never-wrong-rows property.
"""
from trino_tpu.matview.registry import (
    MaterializedView, MaterializedViewRegistry, drop_payload, from_payload,
    to_payload)
from trino_tpu.matview.substitute import (
    staleness_reason, substitute_plan, substitution_enabled,
    substitution_versions)

__all__ = [
    "MaterializedView", "MaterializedViewRegistry", "drop_payload",
    "from_payload", "to_payload", "staleness_reason", "substitute_plan",
    "substitution_enabled", "substitution_versions",
]
