"""Materialized-view lifecycle: CREATE / REFRESH / DROP execution.

Reference: ``execution/CreateMaterializedViewTask`` +
``RefreshMaterializedViewTask`` — there REFRESH plans an INSERT-overwrite
of the storage table through the connector's ``beginRefreshMaterializedView``
handshake; here the defining query executes through the engine's normal
path (the coordinator passes its distributed ``_execute_query`` as
``execute_fn``; embedded sessions run the local executor) and the result
swaps into the storage table via the plain connector write SPI
(``create_table``/``overwrite_rows``/``drop_table`` — any writable
catalog can host MV storage).

Freshness bookkeeping is the whole point of the swap protocol:

1. plan the definition (plan-time access control re-fires for the
   refreshing principal) and capture every base table's ``data_version``
   BEFORE executing — a base mutation DURING the refresh then leaves the
   recorded versions behind the connector's current token, so the view
   lands stale, never wrong;
2. execute, overwrite the storage table (recreating it when the
   definition's column shape drifted), and read the storage version the
   write produced;
3. publish versions + the recomputed canonical match keys in ONE locked
   registry write (``MaterializedViewRegistry.publish_refresh``) — a
   concurrent substitution sees pre- or post-refresh state, never a mix;
4. optionally pre-stage the new storage into the warm-HBM device cache
   (``device_cache_enabled`` sessions) so the first post-refresh
   substituted query reports ``fresh_staged_rows=0``.
"""
from __future__ import annotations

import contextlib
import dataclasses
import re
import time
from typing import List, Optional, Tuple

from trino_tpu.matview.registry import (
    MaterializedView, MaterializedViewRegistry)
from trino_tpu.sql.parser import ast

# select-item prefixes beyond this width are not precomputed as match
# keys (the stretch projection-subsumption match); the full-width exact
# match always works
MAX_PREFIX_WIDTH = 12

_DEFINITION_RE = re.compile(
    r"(?is)^\s*create\s+(?:or\s+replace\s+)?materialized\s+view\s+"
    r"(?:if\s+not\s+exists\s+)?\S+\s+as\s+(.*?);?\s*$")


def definition_sql_of(sql: Optional[str]) -> Optional[str]:
    """The defining query's text, stripped from the rigid CREATE prefix
    when the statement matches it; statements the regex cannot take
    apart (leading comments, exotic quoting) keep their FULL text —
    ``from_payload`` unwraps the CREATE statement's query at parse time,
    so replication never silently skips a legal statement."""
    if not sql:
        return None
    m = _DEFINITION_RE.match(sql)
    return m.group(1).strip() if m else sql.strip()


def registry_of(session) -> MaterializedViewRegistry:
    reg = getattr(session, "matviews", None)
    if reg is None:
        raise ValueError(
            "materialized views are not available in this session")
    return reg


def resolve_mv_name(session, parts) -> Tuple[str, str, str]:
    """Qualified (catalog, schema, name) with session defaults applied —
    same resolution as table names (exec/query._resolve_table_name)."""
    parts = [p.lower() for p in parts]
    catalog = str(session.properties.get("catalog", "tpch"))
    schema = str(session.properties.get("schema", "tiny"))
    if len(parts) == 3:
        catalog, schema, name = parts
    elif len(parts) == 2:
        schema, name = parts
    else:
        (name,) = parts
    return catalog, schema, name


def _writable(conn) -> bool:
    """Does this connector implement the write SPI (CREATE TABLE)?"""
    from trino_tpu.connector import spi

    return type(conn).create_table is not spi.Connector.create_table


def _storage_location(session, catalog: str, schema: str) -> Tuple[str, str]:
    """Where the MV's storage table lives: the view's own catalog when
    writable, else the ``materialized_view_storage_catalog`` session
    property (default: the in-memory connector)."""
    conn = session.catalogs.get(catalog)
    if conn is not None and _writable(conn):
        return catalog, schema
    fallback = str(session.properties.get(
        "materialized_view_storage_catalog", "memory"))
    fconn = session.catalogs.get(fallback)
    if fconn is None or not _writable(fconn):
        raise ValueError(
            f"no writable catalog for materialized-view storage: "
            f"{catalog} does not support CREATE TABLE and the "
            f"materialized_view_storage_catalog fallback "
            f"'{fallback}' is "
            + ("not registered" if fconn is None else "not writable"))
    return fallback, schema


@contextlib.contextmanager
def _definition_defaults(session, mv: MaterializedView):
    """Plan the definition under the CREATOR's name-resolution defaults:
    an unqualified table in the definition must keep meaning what it
    meant at CREATE time, whichever session later refreshes/expands."""
    saved = (session.properties.get("catalog"),
             session.properties.get("schema"))
    session.properties["catalog"] = mv.default_catalog
    session.properties["schema"] = mv.default_schema
    try:
        yield
    finally:
        session.properties["catalog"], session.properties["schema"] = saved


def plan_definition(session, mv: MaterializedView):
    """Optimized plan of the defining query as the CURRENT principal
    (plan-time access control on every base table re-fires here)."""
    from trino_tpu.sql.planner.optimizer import optimize
    from trino_tpu.sql.planner.planner import Planner

    stmt = mv.definition
    udfs = getattr(session, "udfs", None)
    if udfs:
        from trino_tpu.sql.routines import expand_udfs

        stmt = expand_udfs(stmt, udfs)
    with _definition_defaults(session, mv):
        root = Planner(session).plan(stmt)
        return optimize(root, session)


def _match_keys(session, mv: MaterializedView, root):
    """The canonical match key of the optimized definition plus the
    prefix-projection variants: for each leading select-item prefix of a
    plain QuerySpec definition, plan+optimize the prefix query through
    the very pipeline a user query takes, so its canonical equals what a
    ``SELECT <first k items> ...`` query optimizes to. Prefixes that
    fail to plan (or collapse to the full canonical) are skipped — the
    stretch match is purely additive."""
    from trino_tpu.cache.plan_key import canonicalize_plan
    from trino_tpu.sql.planner import plan as P

    src = root.source if isinstance(root, P.OutputNode) else root
    canonical = canonicalize_plan(src)
    prefixes = {}
    q = mv.definition
    body = q.body if isinstance(q, ast.Query) else None
    width = len(mv.column_names)
    eligible = (
        isinstance(body, ast.QuerySpec)
        and not q.order_by and q.limit is None
        and 1 < width <= MAX_PREFIX_WIDTH
        and not any(isinstance(it.expr, ast.Star)
                    for it in body.select_items)
    )
    if eligible:
        from trino_tpu.sql.planner.optimizer import optimize
        from trino_tpu.sql.planner.planner import Planner

        for k in range(1, width):
            pq = ast.Query(
                body=dataclasses.replace(
                    body, select_items=body.select_items[:k]),
                with_queries=q.with_queries)
            try:
                with _definition_defaults(session, mv):
                    proot = optimize(Planner(session).plan(pq), session)
            except Exception:  # noqa: BLE001 — prefix match is optional
                continue
            psrc = proot.source
            if list(psrc.output_types) != list(mv.column_types[:k]):
                continue
            c = canonicalize_plan(psrc)
            if c != canonical:
                prefixes[c] = k
    return canonical, prefixes


def _check_definition(session, stmt_query, root) -> None:
    """CREATE-time validation: the definition must be deterministic (a
    cached result would freeze random()/now()), must scan only versioned
    tables (an unversioned base can never prove freshness), and must
    produce uniquely named columns (they become storage columns)."""
    from trino_tpu.cache.determinism import uncachable_reason
    from trino_tpu.cache.plan_key import capture_versions

    reason = uncachable_reason(stmt_query, root)
    if reason is not None:
        raise ValueError(
            f"materialized view definition is not materializable: "
            f"{reason}")
    if capture_versions(session, root) is None:
        raise ValueError(
            "materialized view definition scans an unversioned table — "
            "freshness could never be decided")
    names = [n.lower() for n in root.column_names]
    if len(set(names)) != len(names) or any(not n for n in names):
        raise ValueError(
            "materialized view definition must produce uniquely named "
            f"columns, got {names} — alias the select items")


def create_materialized_view(session, stmt, sql: Optional[str] = None,
                             execute_fn=None,
                             warm: bool = True) -> Tuple[List[str], list]:
    """CREATE [OR REPLACE] MATERIALIZED VIEW: validate + register the
    definition, then (by default) run the initial REFRESH so the view is
    born fresh. Returns ``(columns, rows)`` for the statement result."""
    registry = registry_of(session)
    if stmt.or_replace and stmt.not_exists:
        raise ValueError(
            "CREATE MATERIALIZED VIEW cannot combine OR REPLACE and "
            "IF NOT EXISTS")
    catalog, schema, name = resolve_mv_name(session, stmt.name)
    existing = registry.get(catalog, schema, name)
    if existing is not None:
        if stmt.not_exists:
            return ["result"], [("CREATE MATERIALIZED VIEW",)]
        if not stmt.or_replace:
            raise ValueError(
                f"materialized view already exists: "
                f"{catalog}.{schema}.{name}")
    mv = MaterializedView(
        catalog=catalog, schema=schema, name=name,
        definition_sql=definition_sql_of(sql),
        definition=stmt.query,
        owner=getattr(getattr(session, "identity", None), "user",
                      "anonymous"),
        default_catalog=str(session.properties.get("catalog", "tpch")),
        default_schema=str(session.properties.get("schema", "tiny")),
    )
    root = plan_definition(session, mv)
    _check_definition(session, stmt.query, root)
    mv.column_names = tuple(n.lower() for n in root.column_names)
    mv.column_types = tuple(root.source.output_types)
    scat, sschema = _storage_location(session, catalog, schema)
    mv.storage_catalog, mv.storage_schema = scat, sschema
    # fallback-catalog storage qualifies the VIEW's catalog into the
    # table name: same-named views of two unwritable catalogs must never
    # fight over one storage table
    mv.storage_table = (f"{name}$storage" if scat == catalog
                        else f"{name}${catalog}$storage")
    ac = getattr(session, "access_control", None)
    if ac is not None:
        ac.check_can_write(session.identity, scat, sschema,
                           mv.storage_table)
    refresh = str(session.properties.get(
        "materialized_view_refresh_on_create", True)).lower() not in (
        "false", "0", "no")
    same_storage = existing is not None and (
        existing.storage_catalog, existing.storage_schema,
        existing.storage_table) == (
        mv.storage_catalog, mv.storage_schema, mv.storage_table)
    if refresh:
        # the initial refresh runs BEFORE the registry swap: a failed
        # CREATE [OR REPLACE] leaves the previous view registered (its
        # version check marks it stale if the shared storage was partly
        # overwritten — stale, never wrong) instead of destroying it
        try:
            refresh_materialized_view(session, mv, execute_fn=execute_fn,
                                      planned_root=root, warm=warm)
        except BaseException:
            if not same_storage:  # never drop a replaced view's storage
                _drop_storage(session, mv)
            raise
    if existing is not None:
        if not same_storage:  # OR REPLACE into a new location: retire
            _drop_storage(session, existing)
        registry.remove(catalog, schema, name)
    registry.put(mv)
    return ["result"], [("CREATE MATERIALIZED VIEW",)]


def refresh_materialized_view(session, mv_or_parts, execute_fn=None,
                              planned_root=None,
                              warm: bool = True) -> Tuple[List[str], list]:
    """REFRESH MATERIALIZED VIEW: execute the definition through
    ``execute_fn`` (default: the local executor) and atomically swap the
    storage table + freshness record. Returns the statement result with
    the refreshed row count."""
    from trino_tpu.cache.plan_key import capture_versions
    from trino_tpu.obs import metrics as M
    from trino_tpu.obs import trace as tracing

    registry = registry_of(session)
    if isinstance(mv_or_parts, MaterializedView):
        mv = mv_or_parts
    else:
        catalog, schema, name = resolve_mv_name(session, mv_or_parts)
        mv = registry.get(catalog, schema, name)
        if mv is None:
            raise ValueError(
                f"materialized view not found: {catalog}.{schema}.{name}")
    t0 = time.perf_counter()
    with tracing.span("mv/refresh") as sp:
        sp.set("view", mv.qualified)
        root = (planned_root if planned_root is not None
                else plan_definition(session, mv))
        # versions captured BEFORE execution: a base mutation during the
        # refresh leaves these behind the current token => stale, not
        # wrong
        versions = capture_versions(session, root)
        if versions is None:
            raise ValueError(
                f"materialized view {mv.qualified} scans an unversioned "
                "table — cannot refresh")
        if execute_fn is not None:
            rows = execute_fn(root)
        else:
            from trino_tpu.exec.executor import Executor

            rows = Executor(session).execute_checked(root).to_pylist()
        mv.column_names = tuple(n.lower() for n in root.column_names)
        mv.column_types = tuple(root.source.output_types)
        storage_version = _swap_storage(session, mv, rows)
        canonical, prefixes = _match_keys(session, mv, root)
        registry.publish_refresh(mv, versions, storage_version,
                                 canonical, prefixes)
        elapsed = time.perf_counter() - t0
        M.MV_REFRESH_SECONDS.observe(elapsed)
        sp.set("rows", len(rows))
        sp.set("storage", mv.storage_qualified)
        # the caller opts out of the warm scan when substituted SELECTs
        # will not execute in THIS process (the coordinator under the
        # executor-process plane): warming the dispatch process's device
        # cache there is pure wasted wall time and HBM
        warmed = _warm_storage(session, mv) if warm else 0
        if warmed:
            sp.set("warmed_rows", warmed)
    return ["rows"], [(len(rows),)]


def _swap_storage(session, mv: MaterializedView, rows) -> str:
    """Overwrite (or [re]create, when the column shape drifted) the
    storage table and return its post-write data version."""
    sconn = session.catalogs.get(mv.storage_catalog)
    if sconn is None:
        raise ValueError(
            f"storage catalog not found: {mv.storage_catalog}")
    ac = getattr(session, "access_control", None)
    if ac is not None:
        ac.check_can_write(session.identity, mv.storage_catalog,
                           mv.storage_schema, mv.storage_table)
    schema_def = list(zip(mv.column_names, mv.column_types))
    meta = sconn.get_table(mv.storage_schema, mv.storage_table)
    if meta is not None and [
            (c.name, c.type) for c in meta.columns] != schema_def:
        sconn.drop_table(mv.storage_schema, mv.storage_table)
        meta = None
    if meta is None:
        sconn.create_table(mv.storage_schema, mv.storage_table,
                           schema_def, rows)
    else:
        sconn.overwrite_rows(mv.storage_schema, mv.storage_table, rows)
    version = sconn.data_version(mv.storage_schema, mv.storage_table)
    if version is None:
        raise ValueError(
            f"storage catalog {mv.storage_catalog} is unversioned — "
            "cannot host materialized-view storage")
    return str(version)


def _warm_storage(session, mv: MaterializedView) -> int:
    """Device-cache warm-on-refresh: stage the new storage table into
    the warm-HBM tier through the normal executor scan path (same cache
    key the first substituted query computes), so that query reports
    ``fresh_staged_rows=0``. Best-effort and gated on the session's
    ``device_cache_enabled`` — a refresh never fails because a prefetch
    did."""
    from trino_tpu import devcache

    try:
        if not devcache.cache_enabled(session):
            return 0
    except Exception:  # noqa: BLE001 — prefetch is best-effort
        return 0
    try:
        from trino_tpu.exec.executor import Executor
        from trino_tpu.sql.planner import plan as P

        scan = P.TableScanNode(
            catalog=mv.storage_catalog, schema=mv.storage_schema,
            table=mv.storage_table,
            column_names=list(mv.column_names),
            column_types=list(mv.column_types),
            mv_name=mv.qualified,
        )
        page = Executor(session).execute(scan)
        for col in page.columns:
            col.values.block_until_ready()
        return int(page.num_rows)
    except Exception:  # noqa: BLE001 — prefetch is best-effort
        return 0


def _drop_storage(session, mv: MaterializedView) -> None:
    sconn = session.catalogs.get(mv.storage_catalog)
    if sconn is None:
        return
    try:
        if sconn.get_table(mv.storage_schema, mv.storage_table) is not None:
            sconn.drop_table(mv.storage_schema, mv.storage_table)
    except Exception:  # noqa: BLE001 — registry removal is authoritative
        pass


def drop_materialized_view(session, stmt) -> Tuple[List[str], list]:
    registry = registry_of(session)
    catalog, schema, name = resolve_mv_name(session, stmt.name)
    mv = registry.get(catalog, schema, name)
    if mv is None:
        if stmt.if_exists:
            return ["result"], [("DROP MATERIALIZED VIEW",)]
        raise ValueError(
            f"materialized view not found: {catalog}.{schema}.{name}")
    ac = getattr(session, "access_control", None)
    if ac is not None:
        ac.check_can_write(session.identity, mv.storage_catalog,
                           mv.storage_schema, mv.storage_table)
    _drop_storage(session, mv)
    registry.remove(catalog, schema, name)
    return ["result"], [("DROP MATERIALIZED VIEW",)]


def dispatch_mv_statement(session, stmt, sql: Optional[str] = None,
                          execute_fn=None,
                          warm: bool = True) -> Tuple[List[str], list]:
    """The one entry point statement dispatchers call (exec/query.py
    embedded path; the coordinator passes its distributed execute_fn)."""
    if isinstance(stmt, ast.CreateMaterializedView):
        return create_materialized_view(session, stmt, sql=sql,
                                        execute_fn=execute_fn, warm=warm)
    if isinstance(stmt, ast.RefreshMaterializedView):
        return refresh_materialized_view(session, stmt.name,
                                         execute_fn=execute_fn, warm=warm)
    if isinstance(stmt, ast.DropMaterializedView):
        return drop_materialized_view(session, stmt)
    raise ValueError(f"not a materialized-view statement: {stmt}")


def sync_from_payload(registry: MaterializedViewRegistry,
                      payload: dict) -> str:
    """Apply one replication payload (the executor-process plane's
    ``system.runtime.sync_materialized_view`` procedure body)."""
    from trino_tpu.matview.registry import from_payload

    op = payload.get("op")
    if op == "drop":
        registry.remove(payload["catalog"], payload["schema"],
                        payload["name"])
        return f"dropped {payload['catalog']}.{payload['schema']}.{payload['name']}"
    mv = from_payload(payload)
    registry.put(mv)
    return f"synced {mv.qualified}"
