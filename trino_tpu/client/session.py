"""User-facing entry point: Session.execute(sql) -> rows.

Reference: the client protocol stack (client/trino-client
``StatementClientV1.java:70``) collapsed to an in-process call for the local
engine; the HTTP coordinator/worker protocol is the distributed tier
(trino_tpu.server, later rounds).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class Session:
    """A query session: catalogs, session properties, and an executor."""

    def __init__(self, properties: Optional[Dict[str, Any]] = None, num_partitions: int = 1,
                 identity=None, access_control=None, catalogs=None, udfs=None,
                 matviews=None):
        from trino_tpu.client.properties import defaulted
        from trino_tpu.connector.registry import default_catalogs
        from trino_tpu.server.security import AccessControl, Identity

        # ``catalogs``: share one connector-instance map across sessions
        # (server mode) so DDL/DML against in-memory connectors persists
        # between statements; default = fresh per-session catalogs.
        self.catalogs = catalogs if catalogs is not None else default_catalogs()
        self.properties: Dict[str, Any] = defaulted(dict(properties or {}))
        self.num_partitions = num_partitions
        self.identity = identity or Identity()
        self.access_control = access_control or AccessControl()
        # active explicit transaction (exec/transaction.py), or None
        self.transaction = None
        # SQL routines (sql/routines.py): name -> UdfDef. Server mode
        # shares one dict across sessions (like ``catalogs``) so CREATE
        # FUNCTION persists between statements.
        self.udfs = udfs if udfs is not None else {}
        # materialized-view registry (trino_tpu/matview/): server mode
        # shares one instance across sessions (like ``catalogs``) so
        # CREATE MATERIALIZED VIEW persists between statements; embedded
        # sessions get a private one
        if matviews is None:
            from trino_tpu.matview.registry import MaterializedViewRegistry

            matviews = MaterializedViewRegistry()
        self.matviews = matviews

    def set_property(self, name: str, value: Any) -> None:
        """SET SESSION analog: typed/validated (client/properties.py;
        reference: SystemSessionProperties + SessionPropertyManager)."""
        from trino_tpu.client.properties import validate_property

        self.properties[name] = validate_property(name, value)

    def execute(self, sql: str):
        """Run a query; returns a QueryResult (column names + Python rows)."""
        from trino_tpu.exec.query import run_query

        return run_query(self, sql)

    def explain(self, sql: str, mode: str = "logical") -> str:
        from trino_tpu.exec.query import explain_query

        return explain_query(self, sql, mode)


def execute(sql: str, **kwargs) -> List[Tuple]:
    """One-shot convenience: execute sql in a fresh session, return rows."""
    return Session(**kwargs).execute(sql).rows
