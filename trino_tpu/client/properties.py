"""Typed session properties.

Reference: ``SystemSessionProperties.java`` (1,985 lines, ~200 typed
properties) + ``SessionPropertyManager`` — every knob is declared with a
type, default, and description; setting an unknown property or a
badly-typed value is an error at set time, not a silent no-op at use time.

The registry here covers the knobs the engine actually reads; add an entry
when a new subsystem grows a switch.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass(frozen=True)
class PropertyMetadata:
    name: str
    description: str
    py_type: type
    default: Any
    validate: Optional[Callable[[Any], Optional[str]]] = None  # -> error | None


def _positive(v) -> Optional[str]:
    return None if v > 0 else "must be positive"


SYSTEM_SESSION_PROPERTIES: Dict[str, PropertyMetadata] = {
    p.name: p
    for p in [
        PropertyMetadata("catalog", "default catalog", str, "tpch"),
        PropertyMetadata("schema", "default schema", str, "tiny"),
        PropertyMetadata(
            "query_max_device_memory",
            "per-query device working-set budget in bytes; exceeding it "
            "spills joins/aggregations to host-partitioned passes "
            "(reference: query.max-memory-per-node)",
            int, None, lambda v: _positive(v) if v is not None else None,
        ),
        PropertyMetadata(
            "dynamic_filtering_enabled",
            "collect build-side join key domains at runtime to narrow probe "
            "scans (reference: enable_dynamic_filtering)",
            bool, True,
        ),
        PropertyMetadata(
            "spill_enabled",
            "allow over-budget joins/aggregations to run as host-partitioned "
            "passes instead of failing (reference: spill_enabled)",
            bool, True,
        ),
        PropertyMetadata(
            "device_profiling",
            "bracket every device dispatch with block_until_ready so the "
            "kernel ledger measures device seconds (off: zero-sync "
            "counting only — device seconds estimated from wall)",
            bool, False,
        ),
        PropertyMetadata(
            "slow_injection",
            "straggler injection for speculative-execution tests: "
            "'<task-id-substring>:<seconds>' sleeps matching tasks "
            "(reference: FailureInjector)",
            str, "",
        ),
        PropertyMetadata(
            "phased_execution",
            "delay probe-side fragments until their leaf join-build "
            "fragments finish executing (reference: "
            "execution-policy=phased / PhasedExecutionSchedule)",
            bool, True,
        ),
        PropertyMetadata(
            "target_result_page_rows",
            "rows per result page on the client protocol",
            int, 10_000, _positive,
        ),
        PropertyMetadata(
            "join_max_broadcast_rows",
            "estimated build-side rows above which a distributed join "
            "co-partitions both sides by key hash instead of broadcasting "
            "the build (reference: join_max_broadcast_table_size)",
            int, 1 << 17, _positive,
        ),
        PropertyMetadata(
            "sink_max_buffer_bytes",
            "producer-blocking watermark of a task's output buffer "
            "(reference: sink.max-buffer-size) — the streaming flow-control "
            "bound between producer serialization and consumer pulls",
            int, 32 << 20, _positive,
        ),
        PropertyMetadata(
            "task_output_chunk_bytes",
            "target serialized bytes per task output page: task results "
            "stream to consumers in chunks of this size (reference role: "
            "PagesSerde / output-buffer page size targets)",
            int, 4 << 20, _positive,
        ),
        PropertyMetadata(
            "retry_policy",
            "NONE = pipelined all-at-once scheduling; TASK = fault-tolerant "
            "stage-by-stage execution with per-task retries over spooled "
            "outputs (reference: retry-policy / RetryPolicy.java)",
            str, "NONE",
            lambda v: None if v.upper() in ("NONE", "TASK") else "must be NONE or TASK",
        ),
        PropertyMetadata(
            "gather_max_rows_per_device",
            "estimated rows per device above which distributed windows/"
            "set-ops/sorts repartition (hash or range exchange) instead of "
            "gathering the whole input to every device (reference role: "
            "the AddExchanges distribution thresholds)",
            int, 1 << 16, _positive,
        ),
        PropertyMetadata(
            "slow_query_log_threshold_ms",
            "queries whose wall time reaches this many milliseconds are "
            "logged by SlowQueryLogListener with their slowest trace spans "
            "(obs/listeners.py); overrides the listener/server default",
            int, None, lambda v: _positive(v) if v is not None else None,
        ),
        PropertyMetadata(
            "result_cache_enabled",
            "serve repeated deterministic SELECTs from the coordinator "
            "result cache (trino_tpu/cache/): keyed on the canonical "
            "optimized plan + connector data versions, single-flighted, "
            "disposition surfaced via the X-Trino-Tpu-Cache header",
            bool, False,
        ),
        PropertyMetadata(
            "result_cache_ttl_ms",
            "lifetime of a result-cache entry in milliseconds; version-"
            "based invalidation usually fires first, the TTL bounds "
            "staleness for unversioned edge cases and reclaims dead keys",
            int, 60_000, _positive,
        ),
        PropertyMetadata(
            "result_cache_max_bytes",
            "per-query admission budget against the coordinator result "
            "cache: results above a quarter of min(this, the server "
            "budget) are not cached (the server-wide LRU budget itself is "
            "fixed at server scope — one session cannot resize it)",
            int, 64 << 20, _positive,
        ),
        PropertyMetadata(
            "logical_plan_cache_enabled",
            "reuse cached optimized logical plans on canonical-SQL repeat "
            "(skipping parse/analyze/plan/optimize), revalidated against "
            "connector data versions at lookup",
            bool, True,
        ),
        PropertyMetadata(
            "device_cache_enabled",
            "serve repeated table stagings from the device-resident table "
            "cache (trino_tpu/devcache/): staged scan pages stay warm in "
            "device memory keyed by connector data_version, so an "
            "unchanged table's second query pays zero host->device scan "
            "transfer; unversioned connectors always bypass",
            bool, False,
        ),
        PropertyMetadata(
            "device_cache_max_bytes",
            "per-staging admission cap against the device table cache: "
            "entries above min(this, the server-wide budget) are staged "
            "but not retained (the shared budget itself is fixed at "
            "process scope — one session cannot resize it)",
            int, 1 << 30, _positive,
        ),
        PropertyMetadata(
            "staging_parallelism",
            "fan-out width of the pipelined staging engine "
            "(exec/staging.py): split scan+decode run with this many in "
            "flight on the shared staging pool, overlapping the "
            "host->device transfer; 1 = the serial path (the microbench "
            "baseline), 0 = auto (min(8, cpu count))",
            int, 0, lambda v: None if v >= 0 else "must be >= 0",
        ),
        PropertyMetadata(
            "staging_split_bytes",
            "target estimated bytes per scan split: staging derives its "
            "get_splits target from estimated table bytes / this, so "
            "tiny tables stay single-split (no fan-out overhead) and "
            "huge tables parallelize (adaptive split sizing, "
            "exec/staging.py)",
            int, 64 << 20, _positive,
        ),
        PropertyMetadata(
            "host_cache_max_bytes",
            "per-split admission cap against the host-RAM columnar page "
            "cache (trino_tpu/devcache/hostcache.py): decoded split "
            "column sets above min(this, the server-wide budget) are "
            "staged but not retained (the shared budget itself is fixed "
            "at process scope — one session cannot resize it)",
            int, 256 << 20, _positive,
        ),
        PropertyMetadata(
            "fused_join_enabled",
            "run N:1 lookup joins and semi/anti membership through the "
            "fused sort-merge tier (ops/fused_join.py): build and probe "
            "keys sort TOGETHER in one compiled region — no SortedBuild "
            "intermediate, no separate build sort; dense integer-keyed "
            "builds keep the direct-address fast path either way (the "
            "cost gate, see README 'Join kernels')",
            bool, True,
        ),
        PropertyMetadata(
            "fused_join_pallas",
            "run the merge step of sorted-build joins as the Pallas tiled "
            "two-pointer merge kernel (ops/merge_pallas.py) when its "
            "contract holds (single int32 key, sentinel provably "
            "unreachable); OPT-IN: unset/false keeps the XLA rank merge "
            "(the kernel graduates to a default after a hardware bench "
            "round validates it); true engages it — compiled on TPU, "
            "interpret mode elsewhere (test meshes)",
            bool, None,
        ),
        PropertyMetadata(
            "exchange_overlap_blocks",
            "split the probe side of SPMD partitioned joins into this many "
            "double-buffered send blocks so the ICI all-to-all of block "
            "k+1 overlaps join compute on block k "
            "(parallel/exchange.repartition_page_overlapped); results are "
            "bit-identical to the unoverlapped exchange; 0 or 1 disables "
            "the pipeline (one exchange-then-compute barrier)",
            int, 0, lambda v: None if v >= 0 else "must be >= 0",
        ),
        PropertyMetadata(
            "short_query_fast_path",
            "run SELECTs whose optimized plan would fragment into at most "
            "one distributed stage (point lookups, small scans, single-"
            "step aggregations) on the coordinator's own engine — same "
            "admission, caches, stats, and spans, zero task HTTP round-"
            "trips (server/fastpath.py; reference role: the dispatch/"
            "execution split of QueuedStatementResource); the decision is "
            "visible in query info (fastPath) and EXPLAIN ANALYZE",
            bool, False,
        ),
        PropertyMetadata(
            "fast_path_max_scan_rows",
            "estimated total scan rows above which a single-stage plan "
            "still executes distributed (the coordinator must not absorb "
            "big scans serially just because they fragment simply)",
            int, 4_000_000, _positive,
        ),
        PropertyMetadata(
            "adaptive_execution_enabled",
            "re-plan not-yet-scheduled downstream fragments between stage "
            "completions using the runtime operator-stats rollups (master "
            "switch for trino_tpu/adaptive/; reference: AdaptivePlanner + "
            "FTE adaptive partitioning)",
            bool, True,
        ),
        PropertyMetadata(
            "adaptive_join_distribution",
            "flip broadcast<->partitioned join distribution at the stage "
            "boundary when a build side's ACTUAL rows contradict the "
            "estimate across join_max_broadcast_rows (reference: "
            "DetermineJoinDistributionType re-fired on runtime stats)",
            bool, True,
        ),
        PropertyMetadata(
            "adaptive_capacity_reseed",
            "replace static capacity-hint guesses with runtime truth: "
            "staged-scan histograms size expansion joins and hash exchanges "
            "at build time (compiled/SPMD tiers), and completed upstream "
            "stage rows stamp exchange sources on the coordinator — "
            "eliminating the double-and-recompile loop",
            bool, False,
        ),
        PropertyMetadata(
            "adaptive_skew_threshold",
            "hot-partition ROW ratio — a partition is hot when its output "
            "rows exceed this many times the mean of the OTHER partitions "
            "(serialized bytes lie under compression) and a 4096-row "
            "floor; the adaptive re-planner then salts the repartition "
            "join: the probe producer re-runs spreading hot partitions "
            "across all tasks while the build producer replicates them "
            "everywhere; 0 disables skew mitigation",
            int, 8, lambda v: None if v >= 0 else "must be >= 0",
        ),
        PropertyMetadata(
            "plan_validation",
            "run the plan-IR sanity checker (sql/planner/sanity.py) after "
            "initial planning, after each optimizer pass, after "
            "fragmentation, and after every adaptive re-plan — a bad "
            "rewrite fails loudly at plan time instead of corrupting "
            "results (reference: PlanSanityChecker between optimizer "
            "stages); default (unset) = AUTO: on under pytest, off "
            "otherwise",
            bool, None,
        ),
        PropertyMetadata(
            "query_max_history",
            "completed-query records the coordinator history ring retains "
            "for system.runtime.queries and the /ui recent-queries table "
            "(reference: query.max-history); applied when THIS query "
            "completes, and only ever GROWS retention — values below the "
            "server default are clamped up (the ring is shared state; one "
            "session must not shrink other users' history)",
            int, 100, _positive,
        ),
        PropertyMetadata(
            "query_min_expire_age_ms",
            "minimum age in milliseconds before a completed-query record "
            "may be evicted from the history ring even when over "
            "query_max_history (reference: query.min-expire-age); values "
            "below the server default are clamped up, and a hard "
            "server-side cap still bounds the ring",
            int, 15_000, lambda v: None if v >= 0 else "must be >= 0",
        ),
        PropertyMetadata(
            "spooled_results_enabled",
            "serve large SELECT results as a spooled segment manifest "
            "instead of inline rows: the producers write serde-encoded "
            "result segments (workers directly for export-shaped plans, "
            "the coordinator's own segment store otherwise), the "
            "statement response carries segment URIs, and clients fetch "
            "them in parallel — the coordinator leaves the data path "
            "(reference: Trino 455's spooled client protocol)",
            bool, False,
        ),
        PropertyMetadata(
            "spooled_results_threshold_bytes",
            "estimated result bytes at/above which an enabled spooled-"
            "results query answers with a segment manifest; smaller "
            "results stay inline (the protocol decision, not a cap)",
            int, 8 << 20, _positive,
        ),
        PropertyMetadata(
            "spooled_results_segment_bytes",
            "target serialized bytes per spooled result segment — the "
            "unit of client-side parallel fetch (reference role: the "
            "spooled protocol's segment sizing)",
            int, 8 << 20, _positive,
        ),
        PropertyMetadata(
            "result_segment_ttl_ms",
            "lifetime of an un-acked spooled result segment in "
            "milliseconds; client acks (DELETE /v1/segment/{id}) delete "
            "sooner, the TTL bounds the leak when a client vanishes "
            "mid-fetch",
            int, 300_000, _positive,
        ),
        PropertyMetadata(
            "inline_result_max_bytes",
            "hard cap on result bytes the coordinator will materialize "
            "in process memory for the inline protocol: over it, the "
            "query auto-spools when spooled_results_enabled, else FAILS "
            "loudly (one export query must not OOM the dispatch plane)",
            int, 256 << 20, _positive,
        ),
        PropertyMetadata(
            "materialized_view_substitution",
            "transparently rewrite query plan subtrees that match a "
            "FRESH registered materialized view's definition (canonical "
            "plan fingerprint, exact or select-item-prefix) into a scan "
            "of the precomputed storage table (trino_tpu/matview/); a "
            "stale view always falls back to the base plan — never "
            "wrong rows",
            bool, True,
        ),
        PropertyMetadata(
            "materialized_view_refresh_on_create",
            "run the initial REFRESH as part of CREATE MATERIALIZED "
            "VIEW so the view is born fresh; false registers the "
            "definition only (the first REFRESH populates it)",
            bool, True,
        ),
        PropertyMetadata(
            "materialized_view_storage_catalog",
            "catalog hosting materialized-view storage tables when the "
            "view's own catalog is not writable (e.g. a view over the "
            "immutable tpch generator); must support CREATE TABLE",
            str, "memory",
        ),
        PropertyMetadata(
            "resource_group",
            "admission routing hint matched by resource-group selectors' "
            "session_property field (server/resource_groups.py): a "
            "selector configured on this property routes the query into "
            "its named group subtree before user/source matching is "
            "consulted; empty means only user/source selectors apply",
            str, "",
        ),
        PropertyMetadata(
            "failure_injection",
            "inject a task failure when this substring matches a task id, "
            "e.g. '.<fragment>.<worker>.a<attempt>' (reference: "
            "FailureInjector.java:41-69; test-only)",
            str, "",
        ),
        PropertyMetadata(
            "straggler_multiple",
            "flow-ledger straggler detector sensitivity: a task is "
            "flagged when its elapsed exceeds this multiple of its "
            "stage's median task elapsed (obs/flowledger.py; read "
            "surfaces: system.runtime.stragglers, "
            "GET /v1/query/{id}/flows, EXPLAIN ANALYZE)",
            float, 3.0,
        ),
    ]
}


def validate_property(name: str, value: Any) -> Any:
    """Coerce + validate one property; raises ValueError with the known-name
    list on unknown properties (the reference's 'Session property X does not
    exist' error)."""
    meta = SYSTEM_SESSION_PROPERTIES.get(name)
    if meta is None:
        known = ", ".join(sorted(SYSTEM_SESSION_PROPERTIES))
        raise ValueError(f"session property '{name}' does not exist (known: {known})")
    if value is None:
        if meta.default is None:
            return None
        raise ValueError(f"session property '{name}' cannot be null")
    if meta.py_type is bool and isinstance(value, str):
        if value.lower() in ("true", "1"):
            value = True
        elif value.lower() in ("false", "0"):
            value = False
        else:
            raise ValueError(f"session property '{name}': expected boolean, got {value!r}")
    elif meta.py_type is int and isinstance(value, str):
        try:
            value = int(value)
        except ValueError:
            raise ValueError(f"session property '{name}': expected integer, got {value!r}")
    elif meta.py_type is float and isinstance(value, (str, int)):
        try:
            value = float(value)
        except ValueError:
            raise ValueError(f"session property '{name}': expected number, got {value!r}")
    if not isinstance(value, meta.py_type):
        raise ValueError(
            f"session property '{name}': expected {meta.py_type.__name__},"
            f" got {type(value).__name__}"
        )
    if meta.validate is not None:
        err = meta.validate(value)
        if err:
            raise ValueError(f"session property '{name}': {err}")
    return value


def defaulted(properties: Dict[str, Any]) -> Dict[str, Any]:
    """Validated property map with registry defaults filled in."""
    out = {
        name: meta.default
        for name, meta in SYSTEM_SESSION_PROPERTIES.items()
        if meta.default is not None
    }
    for k, v in properties.items():
        out[k] = validate_property(k, v)
    return out
