"""Remote statement client: the REST protocol consumer.

Reference: ``client/trino-client/.../StatementClientV1.java:70`` — submit
with ``POST /v1/statement``, then ``advance()`` (:350-362) follows
``nextUri`` until the query reaches a terminal state, accumulating result
pages.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from trino_tpu.server import wire


class RemoteQueryError(RuntimeError):
    pass


class QueueFullError(RemoteQueryError):
    """The coordinator's dispatch queue rejected the statement (429 +
    Retry-After) and client-side retries ran out of budget."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class StatementClient:
    """Submit one statement and iterate its results."""

    def __init__(self, coordinator_url: str, session_properties: Optional[Dict[str, str]] = None):
        self.coordinator_url = coordinator_url.rstrip("/")
        self.session_properties = dict(session_properties or {})
        # result-cache disposition of the LAST statement (HIT|MISS|BYPASS),
        # from the X-Trino-Tpu-Cache response header; None before the
        # coordinator has decided (or against a pre-cache server)
        self.cache_status: Optional[str] = None
        # the LAST statement's ``stats`` block (the StatementStats analog:
        # state, elapsedMs, completedSplits/totalSplits, totalRows/Bytes,
        # peakBytes, stages) — updated on every protocol response, so it is
        # live progress while polling and the final rollup once terminal
        self.stats: Optional[Dict] = None
        # query id assigned by the coordinator for the last statement
        self.query_id: Optional[str] = None
        # prepared statements this client knows are live on the server
        # (name -> statement text), maintained from the
        # addedPreparedStatements / deallocatedPreparedStatements payload
        # blocks — the X-Trino-Added-Prepare round-trip analog
        self.prepared_statements: Dict[str, str] = {}
        # 429 (dispatch queue full) resubmissions of the LAST statement
        self.submit_retries = 0

    @staticmethod
    def _retry_after(body: bytes, resp_headers: Dict[str, str]) -> float:
        """Server retry guidance from a 429: the structured payload
        field, else the Retry-After header, else one second — clamped to
        a sane band so a confused server cannot park the client."""
        import json

        retry_after = None
        try:
            retry_after = json.loads(body)["error"]["retryAfterSeconds"]
        except (ValueError, KeyError, TypeError):
            for k, v in (resp_headers or {}).items():
                if k.lower() == "retry-after":
                    try:
                        retry_after = float(v)
                    except ValueError:
                        pass
        return min(30.0, max(0.05, float(retry_after or 1.0)))

    def execute(self, sql: str, timeout: float = 600.0,
                on_stats=None) -> Tuple[List[str], List[list]]:
        """Returns (column_names, rows). ``on_stats`` (callable taking the
        stats dict) fires after every protocol response — the hook the CLI
        uses to render a live progress line."""
        headers = {
            f"X-Trino-Session-{k}": str(v) for k, v in self.session_properties.items()
        }
        self.cache_status = None
        self.stats = None
        self.query_id = None
        self.submit_retries = 0
        import json

        deadline = time.monotonic() + timeout
        while True:
            status, body, resp_headers = wire.http_request(
                "POST", f"{self.coordinator_url}/v1/statement",
                sql.encode(), "text/plain", headers=headers)
            if status != 429:
                break
            # typed overload (DISPATCH_QUEUE_FULL): honor Retry-After and
            # resubmit until the client deadline — overload is backpressure,
            # not failure, and no query is ever silently lost
            retry_after = self._retry_after(body, resp_headers)
            if time.monotonic() + retry_after > deadline:
                raise QueueFullError(
                    f"submit rejected (queue full) and retry budget "
                    f"exhausted: {body[:300].decode(errors='replace')}",
                    retry_after_s=retry_after)
            self.submit_retries += 1
            time.sleep(retry_after)
        self._note_cache_header(resp_headers)
        if status >= 400:
            raise RemoteQueryError(f"submit failed: {body[:500].decode(errors='replace')}")
        payload = json.loads(body)
        columns: List[str] = []
        rows: List[list] = []
        while True:
            self.query_id = payload.get("id", self.query_id)
            if "stats" in payload:
                self.stats = payload["stats"]
                if on_stats is not None:
                    on_stats(self.stats)
            if "error" in payload:
                raise RemoteQueryError(payload["error"]["message"])
            # SET/RESET SESSION round-trip: apply to subsequent statements
            # (reference: StatementClientV1 processes X-Trino-Set-Session)
            for k, v in payload.get("setSessionProperties", {}).items():
                self.session_properties[k] = v
            for k in payload.get("resetSessionProperties", []):
                self.session_properties.pop(k, None)
            for k, v in payload.get("addedPreparedStatements", {}).items():
                self.prepared_statements[k] = v
            for k in payload.get("deallocatedPreparedStatements", []):
                self.prepared_statements.pop(k, None)
            if "columns" in payload:
                columns = [c["name"] for c in payload["columns"]]
            rows.extend(payload.get("data", []))
            next_uri = payload.get("nextUri")
            if next_uri is None:
                return columns, rows
            if time.monotonic() > deadline:
                raise RemoteQueryError("client timeout")
            status, body, resp_headers = wire.http_request(
                "GET", next_uri, timeout=60.0)
            self._note_cache_header(resp_headers)
            if status >= 400:
                raise RemoteQueryError(f"poll failed: {body[:500].decode(errors='replace')}")
            payload = json.loads(body)

    def _note_cache_header(self, resp_headers: Dict[str, str]) -> None:
        for k, v in (resp_headers or {}).items():
            if k.lower() == "x-trino-tpu-cache":
                self.cache_status = v
