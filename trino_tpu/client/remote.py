"""Remote statement client: the REST protocol consumer.

Reference: ``client/trino-client/.../StatementClientV1.java:70`` — submit
with ``POST /v1/statement``, then ``advance()`` (:350-362) follows
``nextUri`` until the query reaches a terminal state, accumulating result
pages.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from trino_tpu.server import wire


class RemoteQueryError(RuntimeError):
    pass


class QueueFullError(RemoteQueryError):
    """The coordinator's dispatch queue rejected the statement (429 +
    Retry-After) and client-side retries ran out of budget.
    ``resource_group``/``queued_ahead`` carry the structured 429 payload
    fields when the server runs group-aware admission: WHICH group said
    no and how deep its queue was."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 resource_group: Optional[str] = None,
                 queued_ahead: Optional[int] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.resource_group = resource_group
        self.queued_ahead = queued_ahead


class SegmentFetchError(RemoteQueryError):
    """A spooled result segment could not be fetched/decoded (missing,
    truncated, or unreachable) after the one transparent retry."""

    def __init__(self, message: str, segment_id: Optional[str] = None):
        super().__init__(message)
        self.segment_id = segment_id


class StatementClient:
    """Submit one statement and iterate its results.

    ``fetch_streams`` sizes the parallel fetch of spooled result
    segments (the client half of the spooled protocol): segment bodies
    download + decode on a small thread pool over the keep-alive
    connection pool, off the statement-polling path, and reassemble in
    manifest order."""

    def __init__(self, coordinator_url: str,
                 session_properties: Optional[Dict[str, str]] = None,
                 fetch_streams: int = 4, user: Optional[str] = None,
                 source: Optional[str] = None):
        self.coordinator_url = coordinator_url.rstrip("/")
        self.session_properties = dict(session_properties or {})
        self.fetch_streams = max(1, int(fetch_streams))
        # claimed identity + client source (X-Trino-User/X-Trino-Source):
        # both are resource-group selector routing dimensions; an
        # authenticator-enforced server overrides the claimed user
        self.user = user
        self.source = source
        # spooled-protocol telemetry of the LAST statement: segments
        # fetched, their serialized bytes, and the fetch+decode wall
        self.spooled_segments = 0
        self.spooled_bytes = 0
        self.segment_fetch_s = 0.0
        # statement-protocol payload bytes of the LAST statement (every
        # submit/poll response body) — the bytes an inline client drains
        self.response_bytes = 0
        # result-cache disposition of the LAST statement (HIT|MISS|BYPASS),
        # from the X-Trino-Tpu-Cache response header; None before the
        # coordinator has decided (or against a pre-cache server)
        self.cache_status: Optional[str] = None
        # the LAST statement's ``stats`` block (the StatementStats analog:
        # state, elapsedMs, completedSplits/totalSplits, totalRows/Bytes,
        # peakBytes, stages) — updated on every protocol response, so it is
        # live progress while polling and the final rollup once terminal
        self.stats: Optional[Dict] = None
        # query id assigned by the coordinator for the last statement
        self.query_id: Optional[str] = None
        # prepared statements this client knows are live on the server
        # (name -> statement text), maintained from the
        # addedPreparedStatements / deallocatedPreparedStatements payload
        # blocks — the X-Trino-Added-Prepare round-trip analog
        self.prepared_statements: Dict[str, str] = {}
        # 429 (dispatch queue full) resubmissions of the LAST statement
        self.submit_retries = 0

    @staticmethod
    def _retry_after(body: bytes, resp_headers: Dict[str, str]) -> float:
        """Server retry guidance from a 429: the structured payload
        field, else the Retry-After header, else one second — clamped to
        a sane band so a confused server cannot park the client."""
        import json

        retry_after = None
        try:
            retry_after = json.loads(body)["error"]["retryAfterSeconds"]
        except (ValueError, KeyError, TypeError):
            for k, v in (resp_headers or {}).items():
                if k.lower() == "retry-after":
                    try:
                        retry_after = float(v)
                    except ValueError:
                        pass
        return min(30.0, max(0.05, float(retry_after or 1.0)))

    def execute(self, sql: str, timeout: float = 600.0,
                on_stats=None) -> Tuple[List[str], List[list]]:
        """Returns (column_names, rows). ``on_stats`` (callable taking the
        stats dict) fires after every protocol response — the hook the CLI
        uses to render a live progress line."""
        headers = {
            f"X-Trino-Session-{k}": str(v) for k, v in self.session_properties.items()
        }
        if self.user:
            headers["X-Trino-User"] = self.user
        if self.source:
            headers["X-Trino-Source"] = self.source
        self.cache_status = None
        self.stats = None
        self.query_id = None
        self.submit_retries = 0
        self.spooled_segments = 0
        self.spooled_bytes = 0
        self.segment_fetch_s = 0.0
        self.response_bytes = 0
        import json

        deadline = time.monotonic() + timeout
        while True:
            status, body, resp_headers = wire.http_request(
                "POST", f"{self.coordinator_url}/v1/statement",
                sql.encode(), "text/plain", headers=headers)
            if status != 429:
                break
            # typed overload (DISPATCH_QUEUE_FULL): honor Retry-After and
            # resubmit until the client deadline — overload is backpressure,
            # not failure, and no query is ever silently lost
            retry_after = self._retry_after(body, resp_headers)
            if time.monotonic() + retry_after > deadline:
                err: Dict = {}
                try:
                    err = json.loads(body).get("error") or {}
                except ValueError:
                    pass
                raise QueueFullError(
                    f"submit rejected (queue full) and retry budget "
                    f"exhausted: {body[:300].decode(errors='replace')}",
                    retry_after_s=retry_after,
                    resource_group=err.get("resourceGroup"),
                    queued_ahead=err.get("queuedAhead"))
            self.submit_retries += 1
            time.sleep(retry_after)
        self._note_cache_header(resp_headers)
        if status >= 400:
            raise RemoteQueryError(f"submit failed: {body[:500].decode(errors='replace')}")
        self.response_bytes += len(body)
        payload = json.loads(body)
        columns: List[str] = []
        rows: List[list] = []
        while True:
            self.query_id = payload.get("id", self.query_id)
            if "stats" in payload:
                self.stats = payload["stats"]
                if on_stats is not None:
                    on_stats(self.stats)
            if "error" in payload:
                raise RemoteQueryError(payload["error"]["message"])
            # SET/RESET SESSION round-trip: apply to subsequent statements
            # (reference: StatementClientV1 processes X-Trino-Set-Session)
            for k, v in payload.get("setSessionProperties", {}).items():
                self.session_properties[k] = v
            for k in payload.get("resetSessionProperties", []):
                self.session_properties.pop(k, None)
            for k, v in payload.get("addedPreparedStatements", {}).items():
                self.prepared_statements[k] = v
            for k in payload.get("deallocatedPreparedStatements", []):
                self.prepared_statements.pop(k, None)
            if "columns" in payload:
                columns = [c["name"] for c in payload["columns"]]
            rows.extend(payload.get("data", []))
            segments = payload.get("segments")
            if segments:
                # spooled result protocol: the payload carries a segment
                # manifest instead of inline data — fetch the segments
                # in parallel from the producers, decode off the
                # statement path, reassemble in manifest order
                rows.extend(self._fetch_segments(segments))
            next_uri = payload.get("nextUri")
            if next_uri is None:
                return columns, rows
            if time.monotonic() > deadline:
                raise RemoteQueryError("client timeout")
            status, body, resp_headers = wire.http_request(
                "GET", next_uri, timeout=60.0)
            self._note_cache_header(resp_headers)
            if status >= 400:
                raise RemoteQueryError(f"poll failed: {body[:500].decode(errors='replace')}")
            self.response_bytes += len(body)
            payload = json.loads(body)

    def _note_cache_header(self, resp_headers: Dict[str, str]) -> None:
        for k, v in (resp_headers or {}).items():
            if k.lower() == "x-trino-tpu-cache":
                self.cache_status = v

    # ------------------------------------------------- spooled segments
    def _fetch_segments(self, segments: List[dict]) -> List[list]:
        """Fetch + decode every manifest segment, ``fetch_streams`` at a
        time, preserving manifest order in the returned rows. Each
        segment gets one transparent retry (the producer may have
        dropped a keep-alive socket); a segment that stays missing or
        truncated raises a typed ``SegmentFetchError``."""
        from concurrent.futures import ThreadPoolExecutor

        t0 = time.monotonic()
        self.spooled_segments = len(segments)
        self.spooled_bytes = sum(int(s.get("bytes", 0)) for s in segments)
        parts: List[Optional[list]] = [None] * len(segments)
        if len(segments) == 1 or self.fetch_streams == 1:
            for i, seg in enumerate(segments):
                parts[i] = self._fetch_one_segment(seg)
        else:
            with ThreadPoolExecutor(
                    max_workers=min(self.fetch_streams, len(segments)),
                    thread_name_prefix="segment-fetch") as pool:
                futures = [pool.submit(self._fetch_one_segment, seg)
                           for seg in segments]
                for i, fut in enumerate(futures):
                    parts[i] = fut.result()  # first failure propagates
        self.segment_fetch_s = time.monotonic() - t0
        rows: List[list] = []
        for part in parts:
            rows.extend(part or ())
        return rows

    def _fetch_one_segment(self, seg: dict) -> list:
        """One segment: GET the framed pages, decode, normalize values
        to the inline protocol's JSON vocabulary, ack. Retries ONCE on
        any transport/decode failure before raising typed."""
        last_err: Optional[str] = None
        for attempt in range(2):
            try:
                status, body, _ = wire.http_request(
                    "GET", seg["uri"], timeout=120.0)
            except Exception as e:  # noqa: BLE001 — transport failure
                last_err = f"fetch failed: {e}"
                continue
            if status >= 400:
                last_err = (f"status {status}: "
                            f"{body[:200].decode(errors='replace')}")
                continue
            try:
                rows = _decode_segment(body, int(seg.get("rows", -1)))
            except Exception as e:  # noqa: BLE001 — truncated/corrupt
                last_err = f"decode failed: {e}"
                continue
            self._ack_segment(seg)
            return rows
        raise SegmentFetchError(
            f"segment {seg.get('id')} unavailable after retry "
            f"({last_err})", segment_id=seg.get("id"))

    @staticmethod
    def _ack_segment(seg: dict) -> None:
        """Best-effort ack (DELETE) so the producer reclaims the segment
        now instead of at TTL; a lost ack only delays the reclaim."""
        try:
            wire.http_request(
                "DELETE", seg.get("ackUri") or seg["uri"], timeout=10.0)
        except Exception:  # noqa: BLE001 — the TTL is the backstop
            pass


def _decode_segment(body: bytes, expected_rows: int = -1) -> list:
    """Framed serde pages -> inline-protocol-compatible Python rows.

    Values normalize to the same vocabulary the inline JSON path yields
    (dates/timestamps -> ISO strings, decimals -> decimal strings), so
    spooled and inline results are bit-identical row for row — but the
    decode is COLUMNAR: plain numeric columns materialize with one
    C-level ``tolist`` and dates/decimals convert vectorized, instead of
    the per-value ``to_pylist`` loop (which is the decode bottleneck at
    export scale — ~1.8us/value of isinstance dispatch and Decimal
    context churn)."""
    from trino_tpu.data.serde import deserialize_page
    from trino_tpu.server.wire import unframe_pages

    rows: list = []
    for pb in unframe_pages(body):
        page = deserialize_page(pb)
        cols = [_column_client_values(c) for c in page.columns]
        # rows are LISTS, like the inline JSON data arrays, so both
        # protocols hand identical structures to callers
        rows.extend(list(t) for t in zip(*cols))
    if expected_rows >= 0 and len(rows) != expected_rows:
        raise ValueError(
            f"segment decoded {len(rows)} rows, manifest says "
            f"{expected_rows} (truncated?)")
    return rows


def _column_client_values(col) -> list:
    """One decoded column -> Python values in the inline protocol's
    JSON vocabulary. Fast vectorized paths for the flat dtypes; varchar
    dictionaries, nested types, two-limb decimals, and timestamps fall
    back to ``to_python`` + a normalization pass."""
    import numpy as np

    from trino_tpu import types as T

    t = col.type
    if (col.children is not None or col.hi is not None or t.is_varchar
            or isinstance(t, T.TimestampType)):
        return _normalized_slow_values(col)
    vals = np.asarray(col.values)
    if t == T.DATE:
        # epoch days -> ISO strings, entirely in C
        out = np.asarray(vals, "datetime64[D]").astype(str).tolist()
    elif t.is_decimal:
        out = _decimal_strings(vals.tolist(), t.scale)
    elif t == T.BOOLEAN:
        out = np.asarray(vals, bool).tolist()
    else:
        out = vals.tolist()  # ints/floats: exact JSON round-trip values
    if col.nulls is not None:
        out = [None if isnull else v
               for v, isnull in zip(out, np.asarray(col.nulls).tolist())]
    return out


def _decimal_strings(ints: list, scale: int) -> list:
    """Scaled-int64 decimals -> the exact strings ``str(Decimal)`` (the
    inline ``_jsonable``) yields, without building Decimal objects."""
    if scale == 0:
        return [str(v) for v in ints]
    p = 10 ** scale
    return [(f"{v // p}.{v % p:0{scale}d}" if v >= 0
             else f"-{-v // p}.{-v % p:0{scale}d}")
            for v in ints]


def _normalized_slow_values(col) -> list:
    """``to_python`` plus the inline-vocabulary normalization (dates and
    datetimes -> ISO strings, Decimals -> strings), decided from the
    first live value."""
    import datetime
    import decimal

    out = col.to_python()
    conv = None
    for v in out:
        if v is None:
            continue
        if isinstance(v, (datetime.date, datetime.datetime)):
            conv = lambda x: x.isoformat()  # noqa: E731
        elif isinstance(v, decimal.Decimal):
            conv = str
        break
    if conv is None:
        return out
    return [None if v is None else conv(v) for v in out]
