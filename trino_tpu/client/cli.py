"""Interactive SQL console.

Reference: ``client/trino-cli`` (``Trino.java:41``, ``Console.java:86``) —
a readline console with aligned output, running either against an embedded
in-process session (default) or a remote coordinator over the REST protocol
(``--server URL``).

Usage:
    python -m trino_tpu.client.cli [--server URL] [--catalog C] [--schema S]
    python -m trino_tpu.client.cli --execute "select 1"
"""
from __future__ import annotations

import argparse
import sys
import time
from typing import List, Sequence


def format_table(columns: Sequence[str], rows: Sequence[Sequence]) -> str:
    """ALIGNED output format (the CLI default in the reference)."""
    cells = [[_render(v) for v in row] for row in rows]
    widths = [len(c) for c in columns]
    for row in cells:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    sep = "-+-".join("-" * w for w in widths)
    out: List[str] = []
    out.append(" | ".join(c.ljust(w) for c, w in zip(columns, widths)))
    out.append(sep)
    for row in cells:
        out.append(" | ".join(cell.ljust(w) for cell, w in zip(row, widths)))
    return "\n".join(out)


def _render(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


class Console:
    def __init__(self, args):
        self.args = args
        if args.server:
            from trino_tpu.client.remote import StatementClient

            props = {"catalog": args.catalog, "schema": args.schema}
            self._client = StatementClient(args.server, props)
            self._session = None
        else:
            from trino_tpu.client.session import Session

            self._client = None
            self._session = Session({"catalog": args.catalog, "schema": args.schema})

    def run_statement(self, sql: str) -> int:
        t0 = time.monotonic()
        try:
            if self._client is not None:
                columns, rows = self._client.execute(sql)
            else:
                result = self._session.execute(sql)
                columns, rows = result.column_names, result.rows
        except Exception as e:  # noqa: BLE001 — console surface
            print(f"Query failed: {e}", file=sys.stderr)
            return 1
        print(format_table(columns, rows))
        dt = time.monotonic() - t0
        summary = f"({len(rows)} row{'s' if len(rows) != 1 else ''} in {dt:.2f}s)"
        cache = getattr(self._client, "cache_status", None)
        if cache:
            # result-cache disposition from the X-Trino-Tpu-Cache header
            # (remote runs only; embedded sessions have no cache in front)
            summary += f" [cache: {cache}]"
        print(summary)
        return 0

    def repl(self) -> int:
        try:
            import readline  # noqa: F401 — line editing side effect
        except ImportError:
            pass
        print("trino-tpu console — end statements with ';', quit/exit to leave")
        buf: List[str] = []
        while True:
            try:
                prompt = "trino> " if not buf else "    -> "
                line = input(prompt)
            except (EOFError, KeyboardInterrupt):
                print()
                return 0
            if not buf and line.strip().lower() in ("quit", "exit"):
                return 0
            buf.append(line)
            text = "\n".join(buf)
            if text.rstrip().endswith(";"):
                buf = []
                sql = text.rstrip().rstrip(";").strip()
                if sql:
                    self.run_statement(sql)


def main() -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu")
    ap.add_argument("--server", default=None, help="coordinator URL (default: embedded)")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("--execute", "-e", default=None, help="run one statement and exit")
    args = ap.parse_args()
    console = Console(args)
    if args.execute:
        return console.run_statement(args.execute)
    return console.repl()


if __name__ == "__main__":
    sys.exit(main())
