"""Interactive SQL console.

Reference: ``client/trino-cli`` (``Trino.java:41``, ``Console.java:86``) —
a readline console with aligned output, running either against an embedded
in-process session (default) or a remote coordinator over the REST protocol
(``--server URL``).

Usage:
    python -m trino_tpu.client.cli [--server URL] [--catalog C] [--schema S]
    python -m trino_tpu.client.cli --execute "select 1"
"""
from __future__ import annotations

import argparse
import sys
import time
from typing import List, Sequence


def format_table(columns: Sequence[str], rows: Sequence[Sequence]) -> str:
    """ALIGNED output format (the CLI default in the reference)."""
    cells = [[_render(v) for v in row] for row in rows]
    widths = [len(c) for c in columns]
    for row in cells:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    sep = "-+-".join("-" * w for w in widths)
    out: List[str] = []
    out.append(" | ".join(c.ljust(w) for c, w in zip(columns, widths)))
    out.append(sep)
    for row in cells:
        out.append(" | ".join(cell.ljust(w) for cell, w in zip(row, widths)))
    return "\n".join(out)


def _render(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _si(n) -> str:
    """Human row counts the way the reference CLI prints them: 6.0M, 1.2K."""
    n = int(n or 0)
    for div, suffix in ((1_000_000_000, "B"), (1_000_000, "M"), (1_000, "K")):
        # the 0.9995 factor rolls values the one-decimal rounding would
        # push to the next unit (999_950 -> "1.0M", never "1000.0K")
        if n >= div * 0.9995:
            return f"{n / div:.1f}{suffix}"
    return str(n)


def render_progress(stats) -> str:
    """One live progress line from a statement-protocol ``stats`` block:
    ``[RUNNING 2/3 stages, 6.0M rows, 1.2s]`` (reference: the CLI's
    StatusPrinter progress bar, reduced to a line)."""
    state = stats.get("state", "?")
    parts = []
    stages = stats.get("stages") or 0
    if stages:
        parts.append(f"{stats.get('completedStages', 0)}/{stages} stages")
    total_splits = stats.get("totalSplits") or 0
    if total_splits:
        parts.append(f"{stats.get('completedSplits', 0)}/{total_splits} splits")
    if stats.get("totalRows"):
        parts.append(f"{_si(stats['totalRows'])} rows")
    if stats.get("elapsedMs") is not None:
        parts.append(f"{stats['elapsedMs'] / 1e3:.1f}s")
    return f"[{state} {', '.join(parts)}]" if parts else f"[{state}]"


def render_summary(stats) -> str:
    """Final one-line stats summary appended to the row-count line."""
    if not stats:
        return ""
    parts = []
    if stats.get("totalRows"):
        parts.append(f"{_si(stats['totalRows'])} rows processed")
    if stats.get("totalSplits"):
        parts.append(
            f"{stats.get('completedSplits', 0)}/{stats['totalSplits']} splits")
    if stats.get("peakBytes"):
        parts.append(f"peak: {stats['peakBytes'] // 1024}KiB")
    mem = stats.get("memory") or {}
    if mem.get("shedBytes"):
        # revocable cache bytes the cluster shed on this query's behalf
        # (memory ledger: queryStats.memory)
        parts.append(f"shed: {mem['shedBytes'] // 1024}KiB")
    if stats.get("adaptations"):
        # the runtime re-planner rewrote fragments mid-query (details:
        # planVersions on GET /v1/query/{id})
        parts.append(f"adapted: {stats['adaptations']} plan change(s)")
    if stats.get("fastPath") == "fast-path":
        # the short-query fast path served this statement coordinator-
        # local (zero task round-trips)
        parts.append("fast-path")
    if stats.get("resourceGroup"):
        # the admission group that gated this query (server/
        # resource_groups.py; live occupancy: system.runtime.resource_groups)
        parts.append(f"group: {stats['resourceGroup']}")
    if stats.get("deviceCacheHits"):
        # scans served warm from the device table cache (zero transfer)
        parts.append(f"warm scans: {stats['deviceCacheHits']}")
    if stats.get("mvHits"):
        # fresh materialized views substituted into this query's plan
        # (the join/aggregate ran at REFRESH time, not now)
        names = stats.get("mvNames") or ()
        parts.append(("mv: " + ", ".join(names)) if names
                     else f"mv hits: {stats['mvHits']}")
    if stats.get("spooled"):
        # the spooled result protocol served a segment manifest instead
        # of inline rows (worker-direct = the coordinator never touched
        # the result data)
        parts.append(
            f"spooled: {stats.get('resultSegments', 0)} segments "
            f"({stats['spooled']})")
    flows = stats.get("flows") or {}
    if flows.get("drainMbPerS") is not None:
        # client-drain throughput from the flow ledger (result bytes
        # serialized to this client over the drain wall)
        parts.append(f"drain: {flows['drainMbPerS']:g} MB/s")
    if flows.get("stragglers"):
        # straggler verdicts (flow ledger): details on
        # GET /v1/query/{id}/flows or system.runtime.stragglers
        parts.append(f"stragglers: {flows['stragglers']}")
    out = f" [{', '.join(parts)}]" if parts else ""
    tl = stats.get("timeline")
    if tl:
        # the completion-time phase ledger: where the wall went
        from trino_tpu.obs.timeline import summarize

        ledger = summarize(tl, max_phases=4)
        if ledger:
            out += f" [phases: {ledger}]"
    return out


class Console:
    def __init__(self, args):
        self.args = args
        if args.server:
            from trino_tpu.client.remote import StatementClient

            props = {"catalog": args.catalog, "schema": args.schema}
            self._client = StatementClient(
                args.server, props,
                fetch_streams=getattr(args, "fetch_streams", 4))
            self._session = None
        else:
            from trino_tpu.client.session import Session

            self._client = None
            self._session = Session({"catalog": args.catalog, "schema": args.schema})

    def run_statement(self, sql: str) -> int:
        t0 = time.monotonic()
        # live progress while the coordinator reports a non-terminal state
        # (remote runs on a tty only: a progress line inside piped output
        # would corrupt it)
        live = self._client is not None and sys.stderr.isatty()
        progress_len = [0]

        def on_stats(stats):
            if not live or stats.get("state") in ("FINISHED", "FAILED",
                                                  "CANCELED"):
                return
            line = render_progress(stats)
            pad = max(0, progress_len[0] - len(line))
            sys.stderr.write("\r" + line + " " * pad)
            sys.stderr.flush()
            progress_len[0] = len(line)

        try:
            if self._client is not None:
                # pass the progress hook only when rendering it (keeps the
                # call compatible with minimal client stand-ins)
                kwargs = {"on_stats": on_stats} if live else {}
                columns, rows = self._client.execute(sql, **kwargs)
            else:
                result = self._session.execute(sql)
                columns, rows = result.column_names, result.rows
        except Exception as e:  # noqa: BLE001 — console surface
            if live and progress_len[0]:
                sys.stderr.write("\r" + " " * progress_len[0] + "\r")
            print(f"Query failed: {e}", file=sys.stderr)
            return 1
        if live and progress_len[0]:
            sys.stderr.write("\r" + " " * progress_len[0] + "\r")
            sys.stderr.flush()
        print(format_table(columns, rows))
        dt = time.monotonic() - t0
        summary = f"({len(rows)} row{'s' if len(rows) != 1 else ''} in {dt:.2f}s)"
        summary += render_summary(getattr(self._client, "stats", None))
        nseg = getattr(self._client, "spooled_segments", 0)
        if nseg:
            # spooled-protocol client telemetry: segment bytes fetched in
            # parallel and the realized drain rate
            mb = getattr(self._client, "spooled_bytes", 0) / 1e6
            fetch_s = getattr(self._client, "segment_fetch_s", 0.0)
            rate = f" @ {mb / fetch_s:.0f}MB/s" if fetch_s > 0 else ""
            summary += f" [fetched {nseg} segments, {mb:.1f}MB{rate}]"
        cache = getattr(self._client, "cache_status", None)
        if cache:
            # result-cache disposition from the X-Trino-Tpu-Cache header
            # (remote runs only; embedded sessions have no cache in front)
            summary += f" [cache: {cache}]"
        print(summary)
        return 0

    def repl(self) -> int:
        try:
            import readline  # noqa: F401 — line editing side effect
        except ImportError:
            pass
        print("trino-tpu console — end statements with ';', quit/exit to leave")
        buf: List[str] = []
        while True:
            try:
                prompt = "trino> " if not buf else "    -> "
                line = input(prompt)
            except (EOFError, KeyboardInterrupt):
                print()
                return 0
            if not buf and line.strip().lower() in ("quit", "exit"):
                return 0
            buf.append(line)
            text = "\n".join(buf)
            if text.rstrip().endswith(";"):
                buf = []
                sql = text.rstrip().rstrip(";").strip()
                if sql:
                    self.run_statement(sql)


def main() -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu")
    ap.add_argument("--server", default=None, help="coordinator URL (default: embedded)")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("--execute", "-e", default=None, help="run one statement and exit")
    ap.add_argument("--fetch-streams", type=int, default=4,
                    help="parallel spooled-segment fetch streams "
                         "(remote servers with spooled_results_enabled)")
    args = ap.parse_args()
    console = Console(args)
    if args.execute:
        return console.run_statement(args.execute)
    return console.repl()


if __name__ == "__main__":
    sys.exit(main())
