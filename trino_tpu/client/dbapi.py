"""PEP 249 (DB-API 2.0) client — the role of the reference's JDBC driver.

Reference: ``client/trino-jdbc`` (TrinoDriver/TrinoConnection/
TrinoStatement over the REST statement protocol) and the companion
``trino-python-client``'s dbapi module. Standard shape: ``connect()`` ->
Connection -> ``cursor()`` -> ``execute(sql, params)`` / ``fetchall()``,
with qmark-style parameters bound through the engine's PREPARE/EXECUTE
path when talking to a coordinator, or substituted locally for embedded
sessions.

Two transports:
- ``connect(coordinator_url=...)`` — remote over the REST protocol
  (client/remote.py StatementClient), the JDBC-over-HTTP analog;
- ``connect(session=...)`` / ``connect()`` — embedded in-process engine
  (the reference's testing QueryRunner role).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class Connection:
    def __init__(self, coordinator_url: Optional[str] = None, session=None,
                 catalog: str = "tpch", schema: str = "tiny",
                 fetch_streams: int = 4, user: Optional[str] = None,
                 source: Optional[str] = None, **properties):
        # ``fetch_streams`` is a CLIENT knob (parallel spooled-segment
        # fetch width), not a server session property — it never rides
        # the X-Trino-Session-* headers; ``user``/``source`` ride the
        # X-Trino-User / X-Trino-Source headers (resource-group selector
        # inputs, server/resource_groups.py)
        if coordinator_url is not None:
            from trino_tpu.client.remote import StatementClient

            props = {"catalog": catalog, "schema": schema, **properties}
            self._client = StatementClient(coordinator_url, props,
                                           fetch_streams=fetch_streams,
                                           user=user, source=source)
            self._session = None
        else:
            if session is None:
                from trino_tpu.client.session import Session

                session = Session({"catalog": catalog, "schema": schema, **properties})
            self._session = session
            self._client = None
        self._closed = False

    def cursor(self) -> "Cursor":
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def close(self) -> None:
        self._closed = True

    # transactions (embedded sessions only; the remote protocol is
    # autocommit, like the reference driver's default)
    def commit(self) -> None:
        if self._session is not None and self._session.transaction is not None:
            self._session.transaction.commit()

    def rollback(self) -> None:
        if self._session is not None and self._session.transaction is not None:
            self._session.transaction.rollback()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Cursor:
    arraysize = 1

    def __init__(self, conn: Connection):
        self._conn = conn
        self.description = None  # 7-tuples per PEP 249
        self.rowcount = -1
        # result-cache disposition of the last execute() against a remote
        # coordinator: "HIT" | "MISS" | "BYPASS" (None for embedded
        # sessions, which have no coordinator cache in front of them)
        self.cache_status: Optional[str] = None
        # final query stats of the last execute() against a remote
        # coordinator (the StatementStats analog: elapsedMs, splits, rows,
        # bytes, peakBytes); None for embedded sessions
        self.stats: Optional[dict] = None
        self._rows: List[tuple] = []
        self._pos = 0

    def execute(self, operation: str, parameters: Optional[Sequence] = None):
        if self._conn._closed:
            raise InterfaceError("connection is closed")
        if (parameters is not None and self._conn._client is not None
                and _qmark_count(operation) > 0):
            # remote qmark binding goes through server-side
            # PREPARE/EXECUTE: the parameterized plan caches ONCE on the
            # coordinator and every binding reuses it (the reference
            # driver's prepared-statement path; executemany loops EXECUTE
            # over the same prepared plan)
            return self._execute_prepared_remote(operation, parameters)
        sql = operation
        if parameters:
            # embedded sessions bind by literal substitution (one
            # in-process call; no coordinator plan cache to warm)
            sql = _substitute_qmarks(operation, parameters)
        return self._run(sql)

    def _run(self, sql: str):
        self.cache_status = None
        self.stats = None
        try:
            if self._conn._client is not None:
                columns, rows = self._conn._client.execute(sql)
                self.cache_status = self._conn._client.cache_status
                self.stats = self._conn._client.stats
            else:
                res = self._conn._session.execute(sql)
                columns, rows = res.column_names, res.rows
        except Exception as e:  # noqa: BLE001 — PEP 249 error taxonomy
            raise DatabaseError(str(e)) from e
        self.description = [(c, None, None, None, None, None, None) for c in columns]
        self._rows = [tuple(r) for r in rows]
        self._pos = 0
        self.rowcount = len(self._rows)
        return self

    def _execute_prepared_remote(self, operation: str, parameters: Sequence):
        client = self._conn._client
        name = "dbapi_" + _statement_digest(operation)
        if name not in client.prepared_statements:
            self._run(f"PREPARE {name} FROM {operation}")
        args = ", ".join(_literal(v) for v in parameters)
        sql = f"EXECUTE {name}" + (f" USING {args}" if args else "")
        try:
            return self._run(sql)
        except DatabaseError as e:
            if "prepared statement not found" not in str(e):
                raise
            # the server lost the statement (restart / registry eviction):
            # re-PREPARE once and retry
            client.prepared_statements.pop(name, None)
            self._run(f"PREPARE {name} FROM {operation}")
            return self._run(sql)

    def executemany(self, operation: str, seq_of_parameters):
        # each binding runs through execute(): against a coordinator the
        # first call PREPAREs and every later one is a bare EXECUTE over
        # the one cached parameterized plan
        for params in seq_of_parameters:
            self.execute(operation, params)
        return self

    def fetchone(self):
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None):
        size = size or self.arraysize
        out = self._rows[self._pos : self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self):
        out = self._rows[self._pos :]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    def close(self):
        self._rows = []

    def setinputsizes(self, sizes):  # noqa: D102 — PEP 249 no-ops
        pass

    def setoutputsize(self, size, column=None):
        pass


def connect(coordinator_url: Optional[str] = None, **kwargs) -> Connection:
    return Connection(coordinator_url, **kwargs)


def _statement_digest(sql: str) -> str:
    """Stable per-statement name suffix for driver-generated PREPAREs (two
    cursors binding the same SQL share one server-side plan)."""
    import hashlib

    return hashlib.sha1(sql.strip().encode()).hexdigest()[:12]


def _sql_segments(sql: str):
    """Tokenize into ``("text", chunk)`` / ``("qmark", None)`` segments,
    ``'...'``-literal aware (with ``''`` escapes) — the ONE scanner both
    the qmark counter and the literal substitution consume, so the
    remote-routing decision can never disagree with the substitution."""
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch == "'":
            j = i + 1
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    j += 2
                    continue
                if sql[j] == "'":
                    break
                j += 1
            yield "text", sql[i : j + 1]
            i = j + 1
            continue
        if ch == "?":
            yield "qmark", None
            i += 1
            continue
        yield "text", ch
        i += 1


def _qmark_count(sql: str) -> int:
    """``?`` parameter markers outside string literals."""
    return sum(1 for kind, _ in _sql_segments(sql) if kind == "qmark")


def _substitute_qmarks(sql: str, params: Sequence) -> str:
    """Bind qmark parameters as SQL literals, string-literal-aware
    (embedded sessions only; the remote path sends PREPARE/EXECUTE)."""
    out = []
    it = iter(params)
    for kind, chunk in _sql_segments(sql):
        if kind == "qmark":
            try:
                out.append(_literal(next(it)))
            except StopIteration:
                raise InterfaceError("not enough parameters for statement") from None
        else:
            out.append(chunk)
    return "".join(out)


def _literal(v) -> str:
    import datetime
    import decimal

    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float, decimal.Decimal)):
        return str(v)
    if isinstance(v, datetime.date):
        return f"date '{v.isoformat()}'"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    raise InterfaceError(f"cannot bind parameter of type {type(v).__name__}")
