"""PEP 249 (DB-API 2.0) client — the role of the reference's JDBC driver.

Reference: ``client/trino-jdbc`` (TrinoDriver/TrinoConnection/
TrinoStatement over the REST statement protocol) and the companion
``trino-python-client``'s dbapi module. Standard shape: ``connect()`` ->
Connection -> ``cursor()`` -> ``execute(sql, params)`` / ``fetchall()``,
with qmark-style parameters bound through the engine's PREPARE/EXECUTE
path when talking to a coordinator, or substituted locally for embedded
sessions.

Two transports:
- ``connect(coordinator_url=...)`` — remote over the REST protocol
  (client/remote.py StatementClient), the JDBC-over-HTTP analog;
- ``connect(session=...)`` / ``connect()`` — embedded in-process engine
  (the reference's testing QueryRunner role).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class Connection:
    def __init__(self, coordinator_url: Optional[str] = None, session=None,
                 catalog: str = "tpch", schema: str = "tiny", **properties):
        if coordinator_url is not None:
            from trino_tpu.client.remote import StatementClient

            props = {"catalog": catalog, "schema": schema, **properties}
            self._client = StatementClient(coordinator_url, props)
            self._session = None
        else:
            if session is None:
                from trino_tpu.client.session import Session

                session = Session({"catalog": catalog, "schema": schema, **properties})
            self._session = session
            self._client = None
        self._closed = False

    def cursor(self) -> "Cursor":
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def close(self) -> None:
        self._closed = True

    # transactions (embedded sessions only; the remote protocol is
    # autocommit, like the reference driver's default)
    def commit(self) -> None:
        if self._session is not None and self._session.transaction is not None:
            self._session.transaction.commit()

    def rollback(self) -> None:
        if self._session is not None and self._session.transaction is not None:
            self._session.transaction.rollback()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Cursor:
    arraysize = 1

    def __init__(self, conn: Connection):
        self._conn = conn
        self.description = None  # 7-tuples per PEP 249
        self.rowcount = -1
        # result-cache disposition of the last execute() against a remote
        # coordinator: "HIT" | "MISS" | "BYPASS" (None for embedded
        # sessions, which have no coordinator cache in front of them)
        self.cache_status: Optional[str] = None
        # final query stats of the last execute() against a remote
        # coordinator (the StatementStats analog: elapsedMs, splits, rows,
        # bytes, peakBytes); None for embedded sessions
        self.stats: Optional[dict] = None
        self._rows: List[tuple] = []
        self._pos = 0

    def execute(self, operation: str, parameters: Optional[Sequence] = None):
        if self._conn._closed:
            raise InterfaceError("connection is closed")
        sql = operation
        if parameters:
            sql = _substitute_qmarks(operation, parameters)
        self.cache_status = None
        self.stats = None
        try:
            if self._conn._client is not None:
                columns, rows = self._conn._client.execute(sql)
                self.cache_status = self._conn._client.cache_status
                self.stats = self._conn._client.stats
            else:
                res = self._conn._session.execute(sql)
                columns, rows = res.column_names, res.rows
        except Exception as e:  # noqa: BLE001 — PEP 249 error taxonomy
            raise DatabaseError(str(e)) from e
        self.description = [(c, None, None, None, None, None, None) for c in columns]
        self._rows = [tuple(r) for r in rows]
        self._pos = 0
        self.rowcount = len(self._rows)
        return self

    def executemany(self, operation: str, seq_of_parameters):
        for params in seq_of_parameters:
            self.execute(operation, params)
        return self

    def fetchone(self):
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None):
        size = size or self.arraysize
        out = self._rows[self._pos : self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self):
        out = self._rows[self._pos :]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    def close(self):
        self._rows = []

    def setinputsizes(self, sizes):  # noqa: D102 — PEP 249 no-ops
        pass

    def setoutputsize(self, size, column=None):
        pass


def connect(coordinator_url: Optional[str] = None, **kwargs) -> Connection:
    return Connection(coordinator_url, **kwargs)


def _substitute_qmarks(sql: str, params: Sequence) -> str:
    """Bind qmark parameters as SQL literals, string-literal-aware (the
    reference driver sends PREPARE/EXECUTE; literal substitution keeps the
    remote path one round trip)."""
    out = []
    it = iter(params)
    i = 0
    n = len(sql)
    while i < n:
        ch = sql[i]
        if ch == "'":
            j = i + 1
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    j += 2
                    continue
                if sql[j] == "'":
                    break
                j += 1
            out.append(sql[i : j + 1])
            i = j + 1
            continue
        if ch == "?":
            try:
                out.append(_literal(next(it)))
            except StopIteration:
                raise InterfaceError("not enough parameters for statement") from None
            i += 1
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _literal(v) -> str:
    import datetime
    import decimal

    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float, decimal.Decimal)):
        return str(v)
    if isinstance(v, datetime.date):
        return f"date '{v.isoformat()}'"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    raise InterfaceError(f"cannot bind parameter of type {type(v).__name__}")
