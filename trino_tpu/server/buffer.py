"""Task output buffers: token-addressed page streams with at-least-once pull.

Reference: the producer side of the pipelined shuffle —
``execution/buffer/PartitionedOutputBuffer.java`` /
``BroadcastOutputBuffer.java`` + the token protocol of
``server/TaskResource.java:333-336`` (SURVEY.md §A.4): a consumer GETs
``/results/{buffer}/{token}``, the response carries pages starting at that
sequence id, and requesting token T+k implicitly acknowledges [T, T+k).
At-least-once delivery with client-side de-dup by sequence id makes retries
safe (the FTE determinism contract).

Like the reference's OutputBuffers, the consumer set is declared up front
(``consumer_count``): broadcast exchanges give every downstream task its own
buffer id, and a page is garbage-collected only once EVERY consumer has
acknowledged past it.

Pages are stored serialized (data/serde.py) — the buffer is a wire-format
queue, not a device-array holder; workers compact+serialize once, every
consumer pull is a byte copy.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from trino_tpu.obs.flowledger import FLOW_LEDGER


# default producer-blocking watermark (reference: sink.max-buffer-size /
# OutputBufferMemoryManager's 32MB default)
DEFAULT_MAX_BUFFER_BYTES = 32 * 1024 * 1024


class OutputBuffer:
    """An ordered page stream read by ``consumer_count`` independent
    consumers, each addressing its own buffer id ∈ [0, consumer_count).

    BOUNDED: ``enqueue`` blocks the producing driver once un-GC'd bytes
    exceed ``max_buffer_bytes`` until consumers acknowledge pages away —
    the reference's OutputBufferMemoryManager backpressure invariant
    ("return a blocked future"; here the producer thread parks, which is
    the same flow control on a thread-per-fragment worker)."""

    def __init__(self, consumer_count: int = 1,
                 max_buffer_bytes: int = DEFAULT_MAX_BUFFER_BYTES,
                 stall_key=None):
        assert consumer_count >= 1
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pages: List[bytes] = []
        self._base = 0  # sequence id of _pages[0]
        self._acked = [0] * consumer_count  # per-consumer ack watermark
        self._complete = False
        self._aborted: Optional[str] = None
        self._max_bytes = max_buffer_bytes
        self._bytes = 0  # un-GC'd page bytes
        self.peak_buffered_bytes = 0
        # flow-ledger label for full-wait stall samples: (stage, partition)
        self._stall_key = stall_key if stall_key is not None else (None, None)
        self.stalled_seconds = 0.0  # cumulative producer full-wait

    def enqueue(self, page_bytes: bytes, timeout: float = 300.0) -> None:
        waited_s = 0.0
        depth = 0
        timed_out = False
        with self._cond:
            if self._aborted is not None:
                return  # writes to a destroyed buffer are discarded
            assert not self._complete, "enqueue after set_complete"
            # about to block? sample this full-wait into the backpressure
            # timeline (the ledger append happens OUTSIDE the lock below)
            t0 = (time.perf_counter()
                  if self._bytes >= self._max_bytes else None)
            if t0 is not None:
                depth = self._bytes
            # block while over the watermark (unless aborted — a dead
            # consumer must not wedge the producer forever)
            # lint: allow(blocking-under-lock) Condition.wait_for RELEASES the lock while blocked; this IS the backpressure
            ok = self._cond.wait_for(
                lambda: self._aborted is not None
                or self._bytes < self._max_bytes,
                timeout,
            )
            if t0 is not None:
                waited_s = time.perf_counter() - t0
            if not ok:
                timed_out = True
            elif self._aborted is None:
                self._pages.append(page_bytes)
                self._bytes += len(page_bytes)
                self.peak_buffered_bytes = max(self.peak_buffered_bytes, self._bytes)
                self._cond.notify_all()
        if waited_s > 0.0:
            self.stalled_seconds += waited_s
            stage, partition = self._stall_key
            FLOW_LEDGER.record_stall(
                "buffer-enqueue", stage, partition, waited_s,
                depth_bytes=depth, limit_bytes=self._max_bytes)
        if timed_out:
            raise TimeoutError(
                f"output buffer full for {timeout}s "
                f"({depth} buffered bytes, no consumer progress)")

    def set_complete(self) -> None:
        with self._cond:
            self._complete = True
            self._cond.notify_all()

    def abort(self, reason: str) -> None:
        with self._cond:
            self._aborted = reason
            self._complete = True
            self._cond.notify_all()

    def _gc_locked(self) -> None:
        """Drop the prefix acknowledged by EVERY consumer (and wake any
        producer blocked on the byte watermark)."""
        drop = min(min(self._acked) - self._base, len(self._pages))
        if drop > 0:
            self._bytes -= sum(len(p) for p in self._pages[:drop])
            del self._pages[:drop]
            self._base += drop
            self._cond.notify_all()

    def poll(
        self, token: int, buffer_id: int = 0, max_pages: int = 16, timeout: float = 1.0
    ) -> Tuple[List[bytes], int, bool, Optional[str]]:
        """Return (pages, next_token, complete, failure) for one consumer
        from sequence id ``token``; long-polls up to ``timeout`` when no data
        is ready. Requesting token T acknowledges this consumer's [0, T)."""
        with self._cond:
            if not 0 <= buffer_id < len(self._acked):
                raise ValueError(f"buffer id {buffer_id} out of range")
            self._acked[buffer_id] = max(self._acked[buffer_id], token)
            self._gc_locked()
            # lint: allow(blocking-under-lock) Condition.wait_for RELEASES the lock; long-poll until a page lands
            self._cond.wait_for(
                lambda: self._aborted or self._complete or self._base + len(self._pages) > token,
                timeout,
            )
            if self._aborted:
                return [], token, True, self._aborted
            start = token - self._base
            if start < 0:
                raise ValueError(f"token {token} already garbage-collected (base {self._base})")
            pages = self._pages[start : start + max_pages]
            next_token = token + len(pages)
            complete = self._complete and next_token == self._base + len(self._pages)
            return list(pages), next_token, complete, None

    def destroy_consumer(self, buffer_id: int) -> None:
        """Final ack: this consumer is done with the whole stream."""
        with self._cond:
            if 0 <= buffer_id < len(self._acked):
                self._acked[buffer_id] = self._base + len(self._pages)
                self._gc_locked()
                self._cond.notify_all()

    @property
    def buffered_bytes(self) -> int:
        with self._lock:
            return sum(len(p) for p in self._pages)


class PartitionedOutputBuffer:
    """Per-partition DISTINCT page streams: buffer id p serves partition p
    (reference: PartitionedOutputBuffer.java — one client per partition),
    unlike OutputBuffer where every consumer reads the same stream. Each
    partition is its own bounded OutputBuffer, so backpressure applies per
    consumer."""

    def __init__(self, partitions: int,
                 max_buffer_bytes: int = DEFAULT_MAX_BUFFER_BYTES,
                 stall_stage=None):
        assert partitions >= 1
        self._parts = [
            OutputBuffer(1, max_buffer_bytes=max(max_buffer_bytes // partitions, 1 << 16),
                         stall_key=(stall_stage, pid))
            for pid in range(partitions)
        ]
        # cumulative serialized bytes enqueued per partition (never
        # decremented by GC), reported in task stats. NOT the skew
        # detection signal — serde compression inverts bytes under a
        # constant hot key, so detection runs on partitionRows; the
        # re-planner uses this series only to cap replication cost
        self._enqueued_bytes = [0] * partitions

    def enqueue_partition(self, pid: int, page_bytes: bytes, timeout: float = 300.0) -> None:
        self._parts[pid].enqueue(page_bytes, timeout=timeout)
        self._enqueued_bytes[pid] += len(page_bytes)

    @property
    def partition_enqueued_bytes(self) -> List[int]:
        return list(self._enqueued_bytes)

    def set_complete(self) -> None:
        for p in self._parts:
            p.set_complete()

    def abort(self, reason: str) -> None:
        for p in self._parts:
            p.abort(reason)

    def poll(self, token: int, buffer_id: int = 0, max_pages: int = 16,
             timeout: float = 1.0):
        if not 0 <= buffer_id < len(self._parts):
            raise ValueError(f"buffer id {buffer_id} out of range")
        return self._parts[buffer_id].poll(token, 0, max_pages, timeout)

    def destroy_consumer(self, buffer_id: int) -> None:
        if 0 <= buffer_id < len(self._parts):
            self._parts[buffer_id].destroy_consumer(0)

    @property
    def buffered_bytes(self) -> int:
        return sum(p.buffered_bytes for p in self._parts)

    @property
    def stalled_seconds(self) -> float:
        return sum(p.stalled_seconds for p in self._parts)
