"""Wire helpers for the DCN tier: framed page bodies + internal auth.

Reference: ``application/X-trino-pages`` bodies (concatenated serialized
pages) with sequence-id headers (``server/InternalHeaders.java:21-25``,
SURVEY.md §A.4), and HMAC-style internal authentication
(``server/InternalAuthenticationManager.java`` — JWT there, keyed digest
here; same role: workers only accept control-plane calls from the cluster).
"""
from __future__ import annotations

import hashlib
import hmac
import json
import os
import struct
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

MEDIA_PAGES = "application/x-trino-tpu-pages"

H_PAGE_TOKEN = "X-Page-Token"
H_NEXT_TOKEN = "X-Page-Next-Token"
H_BUFFER_COMPLETE = "X-Buffer-Complete"
H_TASK_FAILED = "X-Task-Failed"
H_INTERNAL_AUTH = "X-Internal-Auth"

# Cluster-internal shared secret (reference: the
# internal-communication.shared-secret config). There is NO well-known
# default: task bodies are pickled plans, so accepting a guessable
# signature would be remote code execution. Unset, each process generates
# a random secret — a coordinator must export its secret to its workers
# (get_secret() → TRINO_TPU_INTERNAL_SECRET in the worker environment).
_env_secret = os.environ.get("TRINO_TPU_INTERNAL_SECRET")
if _env_secret is None:
    import secrets as _secrets

    _env_secret = _secrets.token_hex(32)
_SECRET = _env_secret.encode()


def get_secret() -> str:
    """This process's cluster secret (pass to spawned workers' env)."""
    return _SECRET.decode()


def sign(body: bytes) -> str:
    return hmac.new(_SECRET, body, hashlib.sha256).hexdigest()


def verify(body: bytes, signature: Optional[str]) -> bool:
    return signature is not None and hmac.compare_digest(sign(body), signature)


def frame_pages(pages: List[bytes]) -> bytes:
    """Length-prefix each serialized page so one body carries a batch."""
    return b"".join(struct.pack("<I", len(p)) + p for p in pages)


def unframe_pages(body: bytes) -> List[bytes]:
    pages = []
    off = 0
    while off < len(body):
        (n,) = struct.unpack_from("<I", body, off)
        off += 4
        pages.append(body[off : off + n])
        off += n
    return pages


def http_request(
    method: str,
    url: str,
    body: bytes = b"",
    content_type: str = "application/octet-stream",
    timeout: float = 30.0,
    headers: Optional[dict] = None,
) -> Tuple[int, bytes, dict]:
    """Minimal signed HTTP call. Returns (status, body, headers)."""
    req = urllib.request.Request(url, data=body if method in ("POST", "PUT") else None, method=method)
    req.add_header("Content-Type", content_type)
    req.add_header(H_INTERNAL_AUTH, sign(body))
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def json_request(method: str, url: str, payload=None, timeout: float = 30.0):
    body = json.dumps(payload).encode() if payload is not None else b""
    status, data, _ = http_request(method, url, body, "application/json", timeout)
    if status >= 400:
        raise RuntimeError(f"{method} {url} -> {status}: {data[:500].decode(errors='replace')}")
    return json.loads(data) if data else None
