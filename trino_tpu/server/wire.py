"""Wire helpers for the DCN tier: framed page bodies + internal auth.

Reference: ``application/X-trino-pages`` bodies (concatenated serialized
pages) with sequence-id headers (``server/InternalHeaders.java:21-25``,
SURVEY.md §A.4), and HMAC-style internal authentication
(``server/InternalAuthenticationManager.java`` — JWT there, keyed digest
here; same role: workers only accept control-plane calls from the cluster).
"""
from __future__ import annotations

import hashlib
import hmac
import http.client
import json
import os
import struct
import threading
import urllib.error
import urllib.request
from typing import List, Optional, Tuple
from urllib.parse import urlsplit

MEDIA_PAGES = "application/x-trino-tpu-pages"

H_PAGE_TOKEN = "X-Page-Token"
H_NEXT_TOKEN = "X-Page-Next-Token"
H_BUFFER_COMPLETE = "X-Buffer-Complete"
H_TASK_FAILED = "X-Task-Failed"
H_INTERNAL_AUTH = "X-Internal-Auth"

# Cluster-internal shared secret (reference: the
# internal-communication.shared-secret config). There is NO well-known
# default: task bodies are pickled plans, so accepting a guessable
# signature would be remote code execution. Unset, each process generates
# a random secret — a coordinator must export its secret to its workers
# (get_secret() → TRINO_TPU_INTERNAL_SECRET in the worker environment).
_env_secret = os.environ.get("TRINO_TPU_INTERNAL_SECRET")
if _env_secret is None:
    import secrets as _secrets

    _env_secret = _secrets.token_hex(32)
_SECRET = _env_secret.encode()


def get_secret() -> str:
    """This process's cluster secret (pass to spawned workers' env)."""
    return _SECRET.decode()


def sign(body: bytes) -> str:
    return hmac.new(_SECRET, body, hashlib.sha256).hexdigest()


def verify(body: bytes, signature: Optional[str]) -> bool:
    return signature is not None and hmac.compare_digest(sign(body), signature)


def frame_pages(pages: List[bytes]) -> bytes:
    """Length-prefix each serialized page so one body carries a batch."""
    return b"".join(struct.pack("<I", len(p)) + p for p in pages)


def unframe_pages(body: bytes) -> List[bytes]:
    pages = []
    off = 0
    while off < len(body):
        (n,) = struct.unpack_from("<I", body, off)
        off += 4
        pages.append(body[off : off + n])
        off += n
    return pages


# ------------------------------------------------------------ keep-alive
# Connection pool for the control plane and clients: one TCP connect per
# (host, port) instead of per REQUEST (the reference's jetty/OkHttp
# clients pool connections; urllib opened a fresh socket every call —
# three connects per served query on the statement protocol alone).
# Idle connections age out (the server side closes idles on its own
# timeout, so the client TTL stays shorter to avoid request-on-closing
# races) and stale sockets retry once on a fresh connection.
_IDLE_MAX_PER_HOST = 8
_IDLE_TTL_S = 20.0


class _ConnectionPool:
    def __init__(self):
        self._idle = {}  # (host, port) -> [(conn, idle_since), ...]
        self._lock = threading.Lock()

    def get(self, key):
        """A pooled connection that has not idled out, or None."""
        import time as _time

        now = _time.monotonic()
        with self._lock:
            stack = self._idle.get(key)
            while stack:
                conn, since = stack.pop()
                if now - since <= _IDLE_TTL_S:
                    return conn
                try:
                    conn.close()
                except OSError:
                    pass
        return None

    def put(self, key, conn) -> None:
        import time as _time

        with self._lock:
            stack = self._idle.setdefault(key, [])
            if len(stack) < _IDLE_MAX_PER_HOST:
                stack.append((conn, _time.monotonic()))
                return
        try:
            conn.close()
        except OSError:
            pass

    def clear(self) -> None:
        with self._lock:
            stacks, self._idle = list(self._idle.values()), {}
        for stack in stacks:
            for conn, _since in stack:
                try:
                    conn.close()
                except OSError:
                    pass


class _KeepAliveConnection(http.client.HTTPConnection):
    """HTTPConnection with TCP_NODELAY: on a REUSED connection Nagle
    batches the request bytes behind the previous response's delayed ACK
    (a ~40ms stall per request on loopback) — pooling without this is
    slower than fresh connects."""

    def connect(self):
        import socket

        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


_POOL = _ConnectionPool()

# errors that mean "the pooled socket went stale" (server closed it
# between requests) — safe to retry ONCE on a fresh connection; anything
# else (including timeouts) propagates
_STALE_ERRORS = (http.client.BadStatusLine, http.client.CannotSendRequest,
                 http.client.ResponseNotReady, ConnectionResetError,
                 ConnectionAbortedError, BrokenPipeError)


def reset_connection_pool() -> None:
    """Drop every pooled connection (tests / fork hygiene)."""
    _POOL.clear()


def http_request(
    method: str,
    url: str,
    body: bytes = b"",
    content_type: str = "application/octet-stream",
    timeout: float = 30.0,
    headers: Optional[dict] = None,
) -> Tuple[int, bytes, dict]:
    """Minimal signed HTTP call over a pooled keep-alive connection.
    Returns (status, body, headers)."""
    parts = urlsplit(url)
    if parts.scheme != "http":
        return _urllib_request(method, url, body, content_type, timeout,
                               headers)
    from trino_tpu.obs import metrics as M

    key = (parts.hostname, parts.port or 80)
    path = parts.path or "/"
    if parts.query:
        path += "?" + parts.query
    hdrs = {"Content-Type": content_type, H_INTERNAL_AUTH: sign(body),
            "Accept-Encoding": "identity"}
    for k, v in (headers or {}).items():
        hdrs[k] = v
    payload = body if method in ("POST", "PUT") else None
    # stale-socket retry safety: GET/DELETE/PUT are idempotent on this
    # protocol (status polls, cancels, announces), so a reused socket
    # that dies mid-RESPONSE may retry. POST is not (a statement may
    # already have executed) — it retries only when the failure happened
    # while SENDING, i.e. the server cannot have received the request.
    response_retry_ok = method != "POST"
    conn = _POOL.get(key)
    reused = conn is not None
    while True:
        if conn is None:
            conn = _KeepAliveConnection(key[0], key[1], timeout=timeout)
            M.HTTP_CONNECTIONS_OPENED.inc()
        else:
            conn.timeout = timeout  # reconnect-after-close honors it too
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
        sent = False
        try:
            conn.request(method, path, body=payload, headers=hdrs)
            sent = True
            resp = conn.getresponse()
            data = resp.read()
            resp_headers = dict(resp.getheaders())
            if resp.will_close:
                conn.close()
            else:
                _POOL.put(key, conn)
            if reused:
                M.HTTP_CONNECTION_REUSES.inc()
            return resp.status, data, resp_headers
        except _STALE_ERRORS:
            try:
                conn.close()
            except OSError:
                pass
            if not reused or (sent and not response_retry_ok):
                raise
            conn, reused = None, False  # one retry on a fresh socket
        except Exception:
            try:
                conn.close()
            except OSError:
                pass
            raise


def _urllib_request(method, url, body, content_type, timeout, headers):
    """Non-http schemes fall back to the original urllib path."""
    req = urllib.request.Request(url, data=body if method in ("POST", "PUT") else None, method=method)
    req.add_header("Content-Type", content_type)
    req.add_header(H_INTERNAL_AUTH, sign(body))
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def json_request(method: str, url: str, payload=None, timeout: float = 30.0):
    import time as _time

    body = json.dumps(payload).encode() if payload is not None else b""
    t0 = _time.perf_counter()
    status, data, _ = http_request(method, url, body, "application/json", timeout)
    elapsed = _time.perf_counter() - t0
    # control-plane flow accounting (announce, task submit/status,
    # cancel): rollup-only (ring=False) — heartbeats at 2/s/worker must
    # not evict the data-plane records a postmortem wants. The wall is
    # charged to the response leg so link seconds never double-count.
    try:
        from trino_tpu.obs.flowledger import FLOW_LEDGER

        if body:
            FLOW_LEDGER.record_transfer(
                "control", "control", len(body), 0.0, direction="send",
                ring=False)
        FLOW_LEDGER.record_transfer(
            "control", "control", len(data), elapsed, direction="recv",
            status=str(status), ring=False)
    except Exception:  # noqa: BLE001 — accounting never fails work
        pass
    if status >= 400:
        raise RuntimeError(f"{method} {url} -> {status}: {data[:500].decode(errors='replace')}")
    return json.loads(data) if data else None
