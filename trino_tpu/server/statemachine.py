"""Listener-based state machines for queries and tasks.

Reference: the single generic FSM that underpins all lifecycle tracking —
``core/trino-main/.../execution/StateMachine.java:43`` — and its two main
instantiations ``QueryState.java:21`` (QUEUED→…→FINISHED/FAILED) and
``TaskState.java:21``.
"""
from __future__ import annotations

import threading
from typing import Callable, Generic, List, Optional, Set, TypeVar

S = TypeVar("S")


class StateMachine(Generic[S]):
    """Thread-safe state holder with terminal-state latching and listeners.

    Listeners fire outside the lock (the reference dispatches on an executor;
    here callers are short non-blocking callbacks).
    """

    def __init__(self, initial: S, terminal: Set[S]):
        self._state = initial
        self._terminal = frozenset(terminal)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._listeners: List[Callable[[S], None]] = []

    def get(self) -> S:
        with self._lock:
            return self._state

    def is_terminal(self) -> bool:
        with self._lock:
            return self._state in self._terminal

    def set(self, new_state: S) -> bool:
        """Transition unconditionally unless already terminal. Returns True
        if the state changed."""
        with self._lock:
            if self._state in self._terminal or self._state == new_state:
                return False
            self._state = new_state
            listeners = list(self._listeners)
            self._cond.notify_all()
        for fn in listeners:
            fn(new_state)
        return True

    def compare_and_set(self, expect: S, new_state: S) -> bool:
        with self._lock:
            if self._state != expect or self._state in self._terminal:
                return False
            self._state = new_state
            listeners = list(self._listeners)
            self._cond.notify_all()
        for fn in listeners:
            fn(new_state)
        return True

    def add_listener(self, fn: Callable[[S], None]) -> None:
        with self._lock:
            self._listeners.append(fn)
            current = self._state
        fn(current)

    def wait_for_terminal(self, timeout: Optional[float] = None) -> S:
        with self._cond:
            # lint: allow(blocking-under-lock) Condition.wait_for RELEASES the lock; set()/compare_and_set never block
            self._cond.wait_for(lambda: self._state in self._terminal, timeout)
            return self._state


# Query lifecycle (reference: QueryState.java:21).
QUERY_STATES = [
    "QUEUED", "PLANNING", "STARTING", "RUNNING", "FINISHING",
    "FINISHED", "FAILED", "CANCELED",
]
QUERY_TERMINAL = {"FINISHED", "FAILED", "CANCELED"}

# Task lifecycle (reference: TaskState.java:21).
TASK_STATES = ["PLANNED", "RUNNING", "FLUSHING", "FINISHED", "FAILED", "CANCELED"]
TASK_TERMINAL = {"FINISHED", "FAILED", "CANCELED"}


def query_state_machine() -> StateMachine[str]:
    return StateMachine("QUEUED", QUERY_TERMINAL)


def task_state_machine() -> StateMachine[str]:
    return StateMachine("PLANNED", TASK_TERMINAL)
