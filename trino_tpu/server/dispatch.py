"""Dispatch plane / executor plane split: the concurrent serving spine.

Reference: the dispatcher/executor split of the reference engine —
``dispatcher/QueuedStatementResource.java`` (dispatch: cheap, high
fan-in, owns admission + queueing and never does query work) vs
``server/protocol/ExecutingStatementResource.java`` +
``execution/SqlQueryExecution.java`` (execution). Before this module the
coordinator spawned TWO fresh threads per query (an admission waiter and
the query thread) and every submitted query got a thread no matter how
overloaded the server was — the thread pile-up IS the single-process QPS
ceiling QPS_r01 measured.

Three pieces:

- ``DispatchQueue`` — the bounded admission buffer between the HTTP
  front and the executor plane. Overload is TYPED: a full queue raises
  ``DispatchRejected`` (the QUERY_QUEUE_FULL analog) which the protocol
  surface turns into a 429 + ``Retry-After`` response with structured
  retry guidance — never a hang, never an unbounded thread pile-up.

- ``Dispatcher`` — the dispatch front. Its threads (the HTTP handler
  calling ``dispatch()``) do NO query work: they consult the
  ``ServingIndex`` (the dispatch-plane result-cache index: repeat
  queries whose cached entry is still version-valid are answered
  without ever touching an executor lane), then enqueue. A fixed pool
  of long-lived EXECUTOR LANES drains the queue: admission (resource
  group + cluster memory) and the query lifecycle run on a lane, so
  per-query thread creation is zero and concurrency is bounded by
  design instead of by accident.

- ``ProcessExecutorPlane`` (opt-in: ``executor_plane="process"`` /
  ``TRINO_TPU_EXECUTOR_PLANE=process``) — executor workers as separate
  OS processes. Each child is a full execution coordinator
  (``python -m trino_tpu.server.dispatch`` — a ``CoordinatorServer``
  reached over loopback HTTP with the existing statement protocol),
  which is exactly the reference's disaggregated-coordinator shape.
  Ownership story (surfaced by ``system.runtime.serving``):

  * dispatch process — query registry/history, prepared-statement
    registry (authoritative copy; PREPARE/DEALLOCATE replicate to
    children), the dispatch queue, admission state, the serving index,
    stateful process-local catalogs (memory, system) AND the
    accelerator: the dispatch process is the single device owner, so
    device-cache-warm and distributed queries always run on its
    inline lanes;
  * executor processes — their own plan-cache + result-cache SHARDS
    and a CPU jax context. Routing is STICKY by (user, statement)
    hash, so the second EXECUTE of a prepared statement lands on the
    child that already holds its parameterized plan (zero planning
    work, cross-process). Shard correctness across processes holds
    because every cache key embeds connector data versions: a DML
    (which always runs on the dispatch owner) moves the version that
    the child's next lookup recomputes, so stale shard entries miss
    naturally; per-user partitioning is in the key everywhere.
  * Work a child cannot own BOUNCES back to a dispatch-side lane: the
    child fails loudly ("no alive workers" — it has none) and the lane
    re-runs the query inline. The client never sees the detour.
"""
from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

DEFAULT_QUEUE_CAPACITY = 256
DEFAULT_RETRY_AFTER_S = 1.0

# catalogs whose state lives in the dispatch process (process-local
# connectors + the system catalog): statements touching them never route
# to an executor process
OWNER_CATALOGS = ("memory", "system")

_OWNER_CATALOG_RE = re.compile(
    r"(?i)\b(?:%s)\s*\." % "|".join(OWNER_CATALOGS))
_EXECUTE_RE = re.compile(r"(?is)^\s*execute\s+(\S+)")
_SELECT_RE = re.compile(r"(?is)^\s*(?:select|with|values)\b")


def default_lane_count() -> int:
    env = os.environ.get("TRINO_TPU_EXECUTOR_LANES")
    if env:
        return max(1, int(env))
    return max(8, min(32, (os.cpu_count() or 2) * 4))


def default_queue_capacity() -> int:
    env = os.environ.get("TRINO_TPU_DISPATCH_QUEUE_CAPACITY")
    if env:
        return max(1, int(env))
    return DEFAULT_QUEUE_CAPACITY


class DispatchRejected(RuntimeError):
    """Typed overload: the dispatch queue is full. Carries the retry
    guidance the 429 response ships (the QUERY_QUEUE_FULL analog).
    Group-aware admission adds WHICH queue said no (``resource_group``)
    and how many queries sit ahead (``queued_ahead``) so a client can
    tell its own group's saturation from global overload. The message
    keeps the stable "Dispatch queue is full" prefix — the process
    plane's bounce detection matches on it."""

    code = "DISPATCH_QUEUE_FULL"

    def __init__(self, queued: int, capacity: int,
                 retry_after_s: float = DEFAULT_RETRY_AFTER_S,
                 resource_group: Optional[str] = None,
                 queued_ahead: Optional[int] = None):
        self.queued = queued
        self.capacity = capacity
        self.retry_after_s = retry_after_s
        self.resource_group = resource_group
        self.queued_ahead = queued_ahead
        where = (f" for resource group {resource_group}"
                 if resource_group else "")
        super().__init__(
            f"Dispatch queue is full{where} ({queued}/{capacity} queued); "
            f"retry in {retry_after_s:g}s")

    def payload(self) -> dict:
        err = {
            "message": str(self),
            "code": self.code,
            "retryAfterSeconds": self.retry_after_s,
            "queued": self.queued,
            "capacity": self.capacity,
        }
        if self.resource_group is not None:
            err["resourceGroup"] = self.resource_group
        if self.queued_ahead is not None:
            err["queuedAhead"] = self.queued_ahead
        return {"error": err}


class DispatchQueue:
    """Bounded FIFO between the dispatch front and the executor lanes.
    ``offer`` never blocks: a full queue is a typed rejection, which is
    the overload contract (bounded memory, bounded threads, a clear
    client signal instead of an invisible pile-up)."""

    # recent take() timestamps kept for the drain-rate estimator — the
    # Retry-After a 429 ships is how long the observed rate needs to
    # clear the queue ahead, not a constant
    DRAIN_WINDOW = 64

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._dq: deque = deque()
        self._drains: deque = deque(maxlen=self.DRAIN_WINDOW)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False

    def depth(self) -> int:
        with self._lock:
            return len(self._dq)

    def drain_rate(self) -> float:
        """Observed dequeue rate in items/second over the recent take
        window (0.0 until two takes have happened)."""
        with self._lock:
            drains = list(self._drains)
        if len(drains) < 2:
            return 0.0
        window = drains[-1] - drains[0]
        if window <= 0:
            return 0.0
        return (len(drains) - 1) / window

    def retry_after_s(self, queued_ahead: int) -> float:
        """Honest Retry-After: time for the observed drain rate to clear
        ``queued_ahead`` items, clamped to [0.1, 30]; the constant
        fallback covers a queue that has never drained."""
        rate = self.drain_rate()
        if rate <= 0.0:
            return DEFAULT_RETRY_AFTER_S
        return min(30.0, max(0.1, (queued_ahead + 1) / rate))

    def check_capacity(self) -> None:
        """Cheap pre-admission probe for the HTTP thread: raises
        ``DispatchRejected`` while the queue is at capacity so overload
        turns around before any per-query state is built."""
        from trino_tpu.obs import metrics as M

        with self._lock:
            full = len(self._dq) >= self.capacity
            depth = len(self._dq)
        if full:
            M.DISPATCH_REJECTED.inc(1, "queue-full")
            raise DispatchRejected(depth, self.capacity,
                                   retry_after_s=self.retry_after_s(depth),
                                   queued_ahead=depth)

    def offer(self, item) -> None:
        from trino_tpu.obs import metrics as M

        with self._lock:
            rejected = len(self._dq) >= self.capacity
            if not rejected:
                self._dq.append(item)
                self._cond.notify()
            depth = len(self._dq)
        M.DISPATCH_QUEUE_DEPTH.set(depth)
        if rejected:
            M.DISPATCH_REJECTED.inc(1, "queue-full")
            raise DispatchRejected(depth, self.capacity,
                                   retry_after_s=self.retry_after_s(depth),
                                   queued_ahead=depth)

    def take(self, timeout: float = 0.5):
        """Next queued item, or None on timeout/close (lanes poll so
        shutdown never strands a thread)."""
        from trino_tpu.obs import metrics as M

        with self._lock:
            # lint: allow(blocking-under-lock) Condition.wait_for RELEASES the lock while parked
            self._cond.wait_for(
                lambda: self._dq or self._closed, timeout)
            if not self._dq:
                return None
            item = self._dq.popleft()
            self._drains.append(time.time())
            depth = len(self._dq)
        M.DISPATCH_QUEUE_DEPTH.set(depth)
        return item

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._cond.notify_all()


class GroupDispatchQueue:
    """Group-aware admission buffer: the ``DispatchQueue`` surface
    (offer/take/depth/close/check_capacity) over a
    :class:`~trino_tpu.server.resource_groups.ResourceGroupTree`.
    Queries park in their GROUP's queue (bounded by the group's
    ``max_queued``) and lanes drain by weighted-fair pick among eligible
    groups instead of global FIFO; the global ``capacity`` still bounds
    total parked queries so coordinator memory stays bounded under any
    config. A query parked past its group's ``queue_timeout_ms`` is
    failed HERE, typed ``EXCEEDED_QUEUE_TIMEOUT``, on the lane thread
    that swept it out."""

    def __init__(self, tree, capacity: int):
        self.tree = tree
        self.capacity = max(1, int(capacity))

    def depth(self) -> int:
        return self.tree.total_queued()

    def drain_rate(self) -> float:
        return self.tree.drain_rate()

    def retry_after_s(self, queued_ahead: int) -> float:
        return self.tree.retry_after_s(queued_ahead,
                                       fallback=DEFAULT_RETRY_AFTER_S)

    def check_capacity(self, group: Optional[str] = None) -> None:
        """Overload probe for the HTTP thread: global capacity first,
        then the target group's ``max_queued`` when known."""
        from trino_tpu.obs import metrics as M

        depth = self.depth()
        if depth >= self.capacity:
            M.DISPATCH_REJECTED.inc(1, "queue-full")
            if group is not None:
                M.RESOURCE_GROUP_REJECTED.inc(1, group, "queue-full")
            raise DispatchRejected(
                depth, self.capacity,
                retry_after_s=self.retry_after_s(depth),
                resource_group=group, queued_ahead=depth)
        if group is not None:
            queued, max_queued = self.tree.queue_state(group)
            if queued >= max_queued:
                M.DISPATCH_REJECTED.inc(1, "queue-full")
                M.RESOURCE_GROUP_REJECTED.inc(1, group, "queue-full")
                raise DispatchRejected(
                    queued, max_queued,
                    retry_after_s=self.retry_after_s(queued),
                    resource_group=group, queued_ahead=queued)

    def offer(self, execution) -> None:
        from trino_tpu.obs import metrics as M

        group = getattr(execution, "resource_group", None)
        if group is None:
            group = self.tree.select(execution.user, getattr(
                execution, "source", ""), execution.session_properties)
            execution.resource_group = group
        depth = self.depth()
        if depth >= self.capacity:
            M.DISPATCH_REJECTED.inc(1, "queue-full")
            M.RESOURCE_GROUP_REJECTED.inc(1, group, "queue-full")
            raise DispatchRejected(
                depth, self.capacity,
                retry_after_s=self.retry_after_s(depth),
                resource_group=group, queued_ahead=depth)
        try:
            ahead = self.tree.enqueue(group, execution.query_id, execution)
        except IndexError:
            queued, max_queued = self.tree.queue_state(group)
            M.DISPATCH_REJECTED.inc(1, "queue-full")
            M.RESOURCE_GROUP_REJECTED.inc(1, group, "queue-full")
            raise DispatchRejected(
                queued, max_queued,
                retry_after_s=self.retry_after_s(queued),
                resource_group=group, queued_ahead=queued)
        execution.queued_ahead = ahead
        M.DISPATCH_QUEUE_DEPTH.set(self.depth())

    def take(self, timeout: float = 0.5):
        """Next ADMITTED execution (weighted-fair, concurrency- and
        memory-eligible), or None on timeout/close. Aged-out queries are
        failed inline and the wait continues — a lane never returns a
        query that was not admitted."""
        from trino_tpu.obs import metrics as M

        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            out = self.tree.dequeue(timeout=remaining)
            if out is None:
                return None
            kind, execution, group, waited = out
            M.DISPATCH_QUEUE_DEPTH.set(self.depth())
            if kind == "run":
                return execution
            self._fail_aged(execution, group, waited)

    def _fail_aged(self, execution, group: str, waited: float) -> None:
        """Typed queue-timeout failure: the query never ran, its whole
        wall clock IS the queued phase (the timeline synthesizes it from
        the created->first-span gap)."""
        from trino_tpu.obs import metrics as M

        M.RESOURCE_GROUP_REJECTED.inc(1, group, "queue-timeout")
        sp = getattr(execution, "_dispatch_queue_span", None)
        if sp is not None:
            execution.tracer.end_span(sp)
            execution._dispatch_queue_span = None
        execution.failure = (
            f"Query exceeded the queue timeout of resource group {group}: "
            f"EXCEEDED_QUEUE_TIMEOUT after {waited:.1f}s queued")
        execution.ended_at = time.time()
        execution.state.set("FAILED")

    def close(self) -> None:
        self.tree.close()


class ServingIndex:
    """The dispatch-plane result-cache index: (user, catalog, schema,
    SQL text) -> (result-cache key, captured data versions) for queries
    that completed as cache MISS-then-fill. A repeat of the exact
    statement revalidates the versions with cheap connector calls and —
    still valid — is served straight from the result cache ON THE
    DISPATCH THREAD: a warm HIT never occupies an executor lane or a
    queue slot. Anything that could change results outside the version
    vocabulary (DDL, CREATE FUNCTION, SET — any non-SELECT statement)
    clears the whole index; DML clears it too, and also moves the data
    versions, so even a racily re-learned entry revalidates false."""

    MAX_ENTRIES = 512

    def __init__(self):
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def _key(user: str, properties: dict, sql: str) -> tuple:
        return (user, str(properties.get("catalog", "")),
                str(properties.get("schema", "")), sql.strip())

    def note(self, user: str, properties: dict, sql: str,
             cache_key: str, versions) -> None:
        if not versions:
            return
        key = self._key(user, properties, sql)
        with self._lock:
            self._entries[key] = (cache_key, tuple(versions))
            self._entries.move_to_end(key)
            while len(self._entries) > self.MAX_ENTRIES:
                self._entries.popitem(last=False)

    def lookup(self, user: str, properties: dict, sql: str):
        key = self._key(user, properties, sql)
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                # hits refresh recency: lane repeats of a learned query
                # are cache HITs (never re-learned), so without this the
                # hottest entries would age out of the LRU first
                self._entries.move_to_end(key)
        return ent

    def forget(self, user: str, properties: dict, sql: str) -> None:
        with self._lock:
            self._entries.pop(self._key(user, properties, sql), None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class Dispatcher:
    """The dispatch front + executor plane of one coordinator.

    ``dispatch()`` runs on the caller's (HTTP) thread and does only
    dispatch-plane work: serving-index consult, then a bounded enqueue.
    The executor lanes — long-lived threads created once — pop queued
    executions, run admission, and execute inline (thread plane) or
    forward to an executor process (process plane)."""

    def __init__(self, server, lanes: Optional[int] = None,
                 queue_capacity: Optional[int] = None,
                 plane: Optional[str] = None,
                 processes: Optional[int] = None,
                 groups=None):
        self._server = server
        self.lane_count = (default_lane_count()
                           if lanes is None else max(0, int(lanes)))
        capacity = (default_queue_capacity()
                    if queue_capacity is None else queue_capacity)
        # a coordinator with a ResourceGroupTree gets group-aware
        # admission; one with an injected flat gate keeps the single FIFO
        self.groups = groups
        self.queue = (GroupDispatchQueue(groups, capacity)
                      if groups is not None else DispatchQueue(capacity))
        self.plane = (plane or os.environ.get(
            "TRINO_TPU_EXECUTOR_PLANE") or "thread").lower()
        self.index = ServingIndex()
        self.process_plane = None
        if self.plane == "process":
            self.process_plane = ProcessExecutorPlane(
                server, processes or int(os.environ.get(
                    "TRINO_TPU_EXECUTOR_PROCESSES", "2")))
        self._threads: List[threading.Thread] = []
        self._busy = 0
        self._lock = threading.Lock()
        self._stopped = False

    # ------------------------------------------------------------ dispatch
    def dispatch(self, execution) -> bool:
        """Dispatch one registered execution. Returns True when the query
        was answered entirely on the dispatch plane (serving index),
        False when it was enqueued for the executor plane. Raises
        ``DispatchRejected`` when the queue is full."""
        self.ensure_lanes()
        if self._serve_from_index(execution):
            if self.groups is not None:
                group = getattr(execution, "resource_group", None)
                if group:
                    # a serving-index hit is concurrency-free but NOT
                    # invisible: it counts against the group's served
                    # tally so a saturated group's cached repeats stay
                    # auditable
                    self.groups.note_served(group)
            return True
        sp = execution.tracer.start_span("dispatch/queue")
        try:
            self.queue.offer(execution)
        except DispatchRejected:
            execution.tracer.end_span(sp)
            raise
        execution._dispatch_queue_span = sp
        return False

    def precheck(self, group: Optional[str] = None) -> None:
        """HTTP-thread overload probe, before any per-query state.
        ``group`` (known only under group-aware admission) adds the
        target group's ``max_queued`` bound to the global-capacity
        check."""
        if group is not None and self.groups is not None:
            self.queue.check_capacity(group)
        else:
            self.queue.check_capacity()

    def _serve_from_index(self, execution) -> bool:
        """Dispatch-plane result-cache consult: answer a repeat query
        whose cached entry is still version-valid without queueing it.
        Only dict lookups + per-table ``data_version`` calls run here —
        no parsing, no planning, no execution."""
        from trino_tpu.obs import metrics as M

        props = execution.session_properties
        if str(props.get("result_cache_enabled", "")).lower() not in (
                "true", "1"):
            return False
        ent = self.index.lookup(execution.user, props, execution.sql)
        if ent is None:
            return False
        cache_key, versions = ent
        catalogs = self._server.catalogs
        for (catalog, schema, table), version in versions:
            conn = catalogs.get(catalog)
            try:
                current = (conn.data_version(schema, table)
                           if conn is not None else None)
            except Exception:  # noqa: BLE001 — revalidation must not throw
                current = None
            if current is None or str(current) != version:
                self.index.forget(execution.user, props, execution.sql)
                return False
        payload = self._server.query_cache.results.peek(cache_key)
        if payload is None:
            self.index.forget(execution.user, props, execution.sql)
            return False
        columns, rows = payload
        # the served statement IS a plain SELECT (only those are learned)
        # — without this, note_completion would treat the dispatch-plane
        # hit as a non-SELECT and wipe the very index that served it
        execution.is_plain_select = True
        root_span = execution.tracer.start_span(
            "query", query_id=execution.query_id, user=execution.user)
        sp = execution.tracer.start_span(
            "dispatch/serve", parent_id=root_span.span_id)
        sp.set("rows", len(rows))
        execution.columns = list(columns)
        execution.rows = list(rows)
        execution.cache_status = "HIT"
        execution.tracer.end_span(sp)
        execution.tracer.end_span(root_span)
        execution.ended_at = time.time()
        M.RESULT_CACHE_HITS.inc()
        M.DISPATCH_CACHE_SERVED.inc()
        execution.state.set("FINISHING")
        execution.state.set("FINISHED")
        return True

    def note_completion(self, execution, stmt_was_select: bool) -> None:
        """Completion hook (from the server's terminal listener): learn
        MISS-then-filled SELECTs into the serving index; clear the index
        on any statement that is not a plain SELECT."""
        if not stmt_was_select:
            self.index.clear()
            return
        key = getattr(execution, "result_cache_key", None)
        versions = getattr(execution, "result_cache_versions", None)
        if (key and versions and execution.cache_status == "MISS"
                and execution.state.get() == "FINISHED"):
            self.index.note(execution.user, execution.session_properties,
                            execution.sql, key, versions)

    # --------------------------------------------------------------- lanes
    def ensure_lanes(self) -> None:
        if self._threads or self.lane_count <= 0 or self._stopped:
            return
        with self._lock:
            if self._threads or self._stopped:
                return
            for i in range(self.lane_count):
                t = threading.Thread(
                    target=self._lane_loop, name=f"executor-lane-{i}",
                    daemon=True)
                self._threads.append(t)
                t.start()

    def start_lanes(self, count: Optional[int] = None) -> None:
        """Test hook + explicit start: bring up the lanes (optionally
        overriding the count before first start)."""
        if count is not None and not self._threads:
            self.lane_count = count
        self.ensure_lanes()

    def busy_lanes(self) -> int:
        with self._lock:
            return self._busy

    def _lane_loop(self) -> None:
        from trino_tpu.obs import metrics as M

        while not self._stopped:
            execution = self.queue.take(timeout=0.5)
            if execution is None:
                continue
            sp = getattr(execution, "_dispatch_queue_span", None)
            if sp is not None:
                execution.tracer.end_span(sp)
            with self._lock:
                self._busy += 1
            M.EXECUTOR_LANES_BUSY.set(self._busy)
            try:
                self._run_one(execution)
            except Exception as e:  # noqa: BLE001 — a lane never dies
                execution.failure = execution.failure or str(e)
                execution.ended_at = execution.ended_at or time.time()
                execution.state.set("FAILED")
            finally:
                with self._lock:
                    self._busy -= 1
                M.EXECUTOR_LANES_BUSY.set(self._busy)

    def _run_one(self, execution) -> None:
        from trino_tpu.obs import metrics as M
        from trino_tpu.server import resource_groups as rg

        if not self._server._admit(execution):
            return
        # bind the query's group to this lane for the run: cache tiers
        # read it at admission time to tag entries with their owner
        # group (the carve-out bookkeeping)
        token = rg.set_current_group(
            getattr(execution, "resource_group", None))
        try:
            pp = self.process_plane
            if pp is not None:
                key = pp.route_key(execution)
                if key is not None:
                    M.EXECUTOR_PLANE_QUERIES.inc(1, "process")
                    pp.run(execution, key=key)
                    return
            M.EXECUTOR_PLANE_QUERIES.inc(1, "inline")
            execution.run()
        finally:
            rg.reset_current_group(token)

    def refresh_gauges(self) -> None:
        from trino_tpu.obs import metrics as M

        M.DISPATCH_QUEUE_DEPTH.set(self.queue.depth())
        M.EXECUTOR_LANES_BUSY.set(self.busy_lanes())

    # ----------------------------------------------------------- ownership
    def serving_rows(self) -> List[tuple]:
        """Rows of ``system.runtime.serving``: every shared serving-plane
        structure with its owner, so the ownership story of the
        dispatch/executor split is introspectable over SQL."""
        s = self._server
        proc = self.plane == "process"
        owner = "dispatch-process"
        shard = ("executor-process (sticky shard)" if proc
                 else "dispatch-process")
        cache = s.query_cache
        rows = [
            ("dispatch_queue", owner, self.plane, self.queue.depth(), None,
             f"capacity={self.queue.capacity}"),
            ("executor_lanes", owner, self.plane, self.busy_lanes(), None,
             f"lanes={self.lane_count}" + (
                 f" processes={self.process_plane.process_count()}"
                 if proc else "")),
            ("serving_index", owner, self.plane, len(self.index), None,
             "result-cache index consulted on the dispatch thread"),
            ("result_cache", shard, self.plane, len(cache.results),
             cache.results.cached_bytes(),
             "keys embed user + connector data versions"),
            ("plan_cache", shard, self.plane, len(cache.plans._entries),
             None, "keys embed user + session properties + data versions"),
            ("prepared_statements", owner, self.plane,
             len(s.prepared.snapshot()), None,
             "authoritative registry; replicated to executor processes"
             if proc else "authoritative registry"),
            ("materialized_views", owner, self.plane,
             len(s.matviews), None,
             "authoritative registry; replicated to executor processes"
             if proc else "authoritative registry"),
            ("query_registry", owner, self.plane, len(s.queries), None,
             "every query registers here regardless of executing plane"),
            ("query_history", owner, self.plane, len(s.history), None,
             "bounded completed-query ring"),
            ("device", owner, self.plane, None, None,
             "single device owner: device-cache/distributed work runs on "
             "dispatch-side lanes"),
        ]
        return rows

    def shutdown(self) -> None:
        self._stopped = True
        self.queue.close()
        if self.process_plane is not None:
            self.process_plane.shutdown()


# --------------------------------------------------------- process plane
def executor_process_main(argv=None) -> None:
    """Entry point of one executor process
    (``python -m trino_tpu.server.dispatch``): a full execution
    coordinator on loopback HTTP with small inline lanes and NO process
    plane of its own. Prints a one-line JSON hello with its URL, then
    serves until stdin closes (the dispatch process owns the lifetime).
    The jax platform pins to the CPU backend — the accelerator belongs
    to the dispatch process (the single device owner)."""
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, default=4)
    ap.add_argument("--platforms", default="cpu")
    args = ap.parse_args(argv)
    try:
        import jax  # lint: allow(jnp-in-host-module) executor-process entry point: pins the child's platform to CPU BEFORE the engine imports (the accelerator stays with the dispatch-process device owner); never runs in the dispatch process

        jax.config.update("jax_platforms", args.platforms)
    except Exception:  # noqa: BLE001 — platform pinning is best-effort
        pass
    from trino_tpu.server.coordinator import CoordinatorServer

    server = CoordinatorServer(executor_lanes=args.lanes,
                               executor_plane="thread")
    server.start()
    print(json.dumps({"url": server.base_url, "pid": os.getpid()}),
          flush=True)
    try:
        while sys.stdin.readline():
            pass  # ignore chatter; EOF = dispatch process is done with us
    except (OSError, KeyboardInterrupt):
        pass
    server.stop()


class _Bounce(Exception):
    """The child cannot own this query (needs workers / owner state) —
    re-run it on a dispatch-side lane."""


class ProcessExecutorPlane:
    """Pool of executor processes, each a spawned execution coordinator
    reached over loopback HTTP. Children boot lazily on first routed
    query (spawn + engine import is seconds — paid once)."""

    BOOT_TIMEOUT_S = 120.0

    def __init__(self, server, processes: int = 2,
                 platforms: Optional[str] = None):
        self._server = server
        self._n = max(1, int(processes))
        self._platforms = platforms or os.environ.get(
            "TRINO_TPU_EXECPLANE_PLATFORMS", "cpu")
        self._children: List[dict] = []
        self._boot_lock = threading.Lock()
        self._stopped = False

    def process_count(self) -> int:
        return self._n

    # ------------------------------------------------------------- routing
    def route_key(self, execution) -> Optional[str]:
        """Sticky routing key, or None when the query must run on a
        dispatch-side lane (owner-catalog state, the device, distributed
        shapes, non-SELECT statements). The probe is syntactic — cheap
        enough for the lane — and the child's loud failure is the
        semantic backstop (``_Bounce``)."""
        props = execution.session_properties
        sql = execution.sql
        if str(props.get("catalog", "tpch")).lower() in OWNER_CATALOGS:
            return None
        if _OWNER_CATALOG_RE.search(sql):
            return None
        if str(props.get("device_cache_enabled", "")).lower() in (
                "true", "1"):
            return None  # the dispatch process owns the device
        if str(props.get("retry_policy", "NONE")).upper() == "TASK":
            return None
        if str(props.get("spooled_results_enabled", "")).lower() in (
                "true", "1"):
            # a spooled manifest must point at a segment store the
            # DISPATCH process serves — the child's statement protocol
            # forwards rows, not segments, so these stay inline
            return None
        m = _EXECUTE_RE.match(sql)
        if m:
            return f"execute:{execution.user}:{m.group(1).lower()}"
        if _SELECT_RE.match(sql):
            return (f"select:{execution.user}:{props.get('catalog', '')}:"
                    f"{props.get('schema', '')}:{sql.strip()}")
        return None

    # ------------------------------------------------------------ children
    def _ensure_children(self) -> None:
        if self._children or self._stopped:
            return
        with self._boot_lock:
            if self._children or self._stopped:
                return
            import json
            import selectors
            import subprocess
            import sys

            from trino_tpu.server import wire

            env = dict(os.environ)
            # same cluster secret so internal calls verify both ways
            env["TRINO_TPU_INTERNAL_SECRET"] = wire.get_secret()
            env["JAX_PLATFORMS"] = self._platforms
            # the child must import the SAME engine tree regardless of
            # its working directory
            import trino_tpu

            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(trino_tpu.__file__)))
            env["PYTHONPATH"] = pkg_root + (
                os.pathsep + env["PYTHONPATH"]
                if env.get("PYTHONPATH") else "")
            children = []
            for i in range(self._n):
                proc = subprocess.Popen(
                    [sys.executable, "-m", "trino_tpu.server.dispatch",
                     "--lanes", "4", "--platforms", self._platforms],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    env=env, text=True)
                children.append({"proc": proc, "url": None, "index": i})
            deadline = time.monotonic() + self.BOOT_TIMEOUT_S
            for ch in children:
                sel = selectors.DefaultSelector()
                sel.register(ch["proc"].stdout, selectors.EVENT_READ)
                line = ""
                while time.monotonic() < deadline and not line:
                    if sel.select(timeout=0.5):
                        line = ch["proc"].stdout.readline()
                    if ch["proc"].poll() is not None:
                        break
                sel.close()
                if not line:
                    for c in children:
                        c["proc"].terminate()
                    raise RuntimeError(
                        "executor process failed to boot within "
                        f"{self.BOOT_TIMEOUT_S:g}s")
                ch["url"] = json.loads(line)["url"]
            self._children = children

    def child_for(self, key: str) -> dict:
        self._ensure_children()
        import zlib

        return self._children[zlib.crc32(key.encode()) % len(self._children)]

    def children_urls(self) -> List[str]:
        return [ch["url"] for ch in self._children]

    # ------------------------------------------------------------- running
    def run(self, execution, key: Optional[str] = None) -> None:
        """Forward one admitted execution to its sticky child; on bounce
        (the child cannot own it) run inline on this lane. ``key`` is the
        routing key the lane already computed (recomputed if omitted)."""
        if key is None:
            key = self.route_key(execution)
        try:
            child = self.child_for(key)
        except Exception as e:  # noqa: BLE001 — boot failure -> inline
            execution.tracer.start_span(
                "dispatch/forward", error=str(e)[:200]).close()
            execution.run()
            return
        try:
            self._forward(execution, child)
        except _Bounce as b:
            from trino_tpu.obs import metrics as M

            M.EXECUTOR_PLANE_QUERIES.inc(1, "bounced")
            sp = execution.tracer.start_span("dispatch/forward")
            sp.set("bounced", str(b)[:200])
            execution.tracer.end_span(sp)
            execution.run()

    # statement-protocol headers the child's session should see — the
    # ONE builder every child-bound request goes through
    @staticmethod
    def _session_headers(user: str, properties: dict) -> Dict[str, str]:
        headers = {"X-Trino-User": user}
        for k, v in properties.items():
            headers[f"X-Trino-Session-{k}"] = str(v)
        return headers

    def _replay_prepare(self, execution, child) -> bool:
        """Child lost (or never saw) a prepared statement: replay the
        PREPARE from the authoritative dispatch-side registry."""
        from trino_tpu.server import wire

        m = _EXECUTE_RE.match(execution.sql)
        if not m:
            return False
        ps = self._server.prepared.get(execution.user, m.group(1))
        if ps is None:
            return False
        status, _, _ = wire.http_request(
            "POST", f"{child['url']}/v1/statement",
            f"PREPARE {ps.name} FROM {ps.sql}".encode(), "text/plain",
            headers=self._session_headers(execution.user,
                                          execution.session_properties))
        return status < 400

    def broadcast(self, sql: str, user: str, properties: dict) -> None:
        """Replicate a registry mutation (PREPARE / DEALLOCATE) to every
        booted child, best-effort — a child that missed it re-syncs on
        its first EXECUTE via ``_replay_prepare``."""
        from trino_tpu.server import wire

        headers = self._session_headers(user, properties)
        for ch in self._children:
            try:
                wire.http_request("POST", f"{ch['url']}/v1/statement",
                                  sql.encode(), "text/plain",
                                  headers=headers, timeout=10.0)
            except Exception:  # noqa: BLE001 — replay covers the miss
                pass

    def _forward(self, execution, child) -> None:
        """One forwarded statement: POST + poll on the child's statement
        protocol, result fields copied onto the dispatch-side execution
        so every read surface (registry, system tables, events, the
        client protocol) covers it like an inline query."""
        import json

        from trino_tpu.server import wire

        execution.state.set("PLANNING")
        root_span = execution.tracer.start_span(
            "query", query_id=execution.query_id, user=execution.user)
        qs = getattr(execution, "_dispatch_queue_span", None)
        if qs is not None:  # adopt the pre-root queue span (single root)
            qs.parent_id = root_span.span_id
        fwd = execution.tracer.start_span(
            "dispatch/forward", parent_id=root_span.span_id)
        fwd.set("child", child["url"])
        headers = self._session_headers(execution.user,
                                        execution.session_properties)
        try:
            # at most two attempts UNDER THE SAME root/forward spans (the
            # trace tree stays single-rooted): the second one only after
            # a prepared-statement replay to a child that lost its replica
            for attempt in range(2):
                cache_status = None
                status, body, resp_headers = wire.http_request(
                    "POST", f"{child['url']}/v1/statement",
                    execution.sql.encode(), "text/plain", headers=headers)
                if status >= 400:
                    raise _Bounce(f"child submit failed: {status}")
                payload = json.loads(body)
                execution.state.set("RUNNING")
                columns: List[str] = []
                rows: List[list] = []
                stats: dict = {}
                child_qid = payload.get("id")
                deadline = time.monotonic() + 600.0
                replayed = False
                while True:
                    for k, v in (resp_headers or {}).items():
                        if k.lower() == "x-trino-tpu-cache":
                            cache_status = v
                    child_qid = payload.get("id", child_qid)
                    stats = payload.get("stats") or stats
                    if "error" in payload:
                        msg = payload["error"].get("message", "")
                        if ("no alive workers" in msg
                                or "Dispatch queue is full" in msg):
                            raise _Bounce(msg)
                        if ("prepared statement not found" in msg
                                and attempt == 0
                                and self._replay_prepare(execution,
                                                         child)):
                            replayed = True
                            break
                        raise RuntimeError(msg)
                    if "columns" in payload:
                        columns = [c["name"] for c in payload["columns"]]
                    rows.extend(payload.get("data", []))
                    next_uri = payload.get("nextUri")
                    if next_uri is None:
                        break
                    if time.monotonic() > deadline:
                        raise RuntimeError("executor-process poll timeout")
                    status, body, resp_headers = wire.http_request(
                        "GET", next_uri, timeout=60.0)
                    if status >= 400:
                        raise RuntimeError(
                            f"executor-process poll failed: {status}")
                    payload = json.loads(body)
                if replayed:
                    fwd.set("replayedPrepare", True)
                    continue
                break
            execution.columns = columns
            execution.rows = [tuple(r) for r in rows]
            execution.cache_status = cache_status or stats.get(
                "cacheStatus")
            execution.fast_path = stats.get("fastPath")
            # MV substitutions decided in the child's planner surface on
            # the dispatch-side execution too (queryStats.mvHits/mvNames)
            execution.mv_substitutions = list(stats.get("mvNames") or ())
            execution.plane = f"executor-process:{child['index']}"
            fwd.set("childQueryId", child_qid)
            self._note_child_stats(execution, child, stats)
            self._pull_child_spans(execution, child, child_qid)
            m = _EXECUTE_RE.match(execution.sql)
            if m:
                # keep the authoritative registry's execution counters
                # live (the child bumped only its replica)
                self._server.prepared.touch(execution.user, m.group(1))
        except _Bounce:
            fwd.set("bounced", True)
            execution.tracer.end_span(fwd)
            execution.tracer.end_span(root_span)
            raise
        except Exception as e:  # noqa: BLE001 — reported via query info
            execution.failure = str(e)
            fwd.set("error", str(e)[:300])
            execution.tracer.end_span(fwd)
            execution.tracer.end_span(root_span)
            execution.ended_at = time.time()
            execution._warm_timeline()
            execution.state.set("FAILED")
            return
        execution.tracer.end_span(fwd)
        execution.tracer.end_span(root_span)
        execution.ended_at = time.time()
        execution._warm_timeline()
        execution.state.set("FINISHED")

    def _note_child_stats(self, execution, child, stats: dict) -> None:
        """Feed the child-reported rollup into the dispatch-side task
        map (one synthetic slot) so stats surfaces cover forwarded
        queries."""
        if not stats:
            return
        execution._note_task_status(
            f"{execution.query_id}.0.proc{child['index']}.a0",
            {"state": "FINISHED", "stats": {
                "elapsedS": float(stats.get("elapsedMs", 0)) / 1e3,
                "deviceS": float(stats.get("deviceS", 0.0)),
                "completedSplits": int(stats.get("completedSplits", 0)),
                "totalSplits": int(stats.get("totalSplits", 0)),
                "inputRows": int(stats.get("totalRows", 0)),
                "outputRows": len(execution.rows),
                "outputBytes": int(stats.get("totalBytes", 0)),
                "peakBytes": int(stats.get("peakBytes", 0)),
                "spills": int(stats.get("spills", 0)),
                "operatorStats": [],
            }})

    def _pull_child_spans(self, execution, child, child_qid) -> None:
        """Merge the child's span tree into the dispatch-side execution
        (``extra_spans`` rides the trace endpoint and the phase ledger),
        so "where did the time go" answers across the process split."""
        import json

        from trino_tpu.server import wire

        if not child_qid:
            return
        try:
            status, body, _ = wire.http_request(
                "GET", f"{child['url']}/v1/query/{child_qid}/trace",
                timeout=5.0)
            if status >= 400:
                return
            from trino_tpu.obs.trace import flatten_tree

            tree = json.loads(body).get("root")
            spans = []
            for node in flatten_tree(tree):
                spans.append({k: v for k, v in node.items()
                              if k != "children"})
            execution.extra_spans = spans
        except Exception:  # noqa: BLE001 — spans are observability
            pass

    def shutdown(self) -> None:
        self._stopped = True
        for ch in self._children:
            try:
                ch["proc"].stdin.close()  # EOF = shut down cleanly
            except OSError:
                pass
        for ch in self._children:
            try:
                ch["proc"].wait(timeout=10.0)
            except Exception:  # noqa: BLE001 — escalate to terminate
                ch["proc"].terminate()
        self._children = []


if __name__ == "__main__":
    executor_process_main()
