"""Worker task engine: task lifecycle + fragment execution.

Reference: ``execution/SqlTaskManager.java:109`` (owns all tasks on a
worker), ``SqlTaskExecution.java:85`` (fragment → drivers), ``TaskState``
FSM. The driver loop's role is filled by whole-fragment execution over the
device (exec/executor.py) — one task = one fragment instance = one batch
program, not a page-at-a-time operator chain (SURVEY.md §7.1).

A ``TaskRequest`` ships the plan-fragment subtree (pickled — the analog of
the reference's JSON-serialized ``PlanFragment``), the splits assigned to
this task (``SOURCE_DISTRIBUTION`` placement, chosen by the coordinator),
and upstream task locations per RemoteSourceNode fragment id.
"""
from __future__ import annotations

import dataclasses
import pickle
import threading
import time
import traceback
from typing import Dict, List, Optional

from trino_tpu.data.page import Page
from trino_tpu.data.serde import serialize_page
from trino_tpu.exec.executor import Executor
from trino_tpu.exec.operator_stats import OperatorStats
from trino_tpu.obs import metrics as M
from trino_tpu.obs import trace as tracing
from trino_tpu.server.buffer import OutputBuffer, PartitionedOutputBuffer
from trino_tpu.server.statemachine import StateMachine, task_state_machine
from trino_tpu.sql.planner import plan as P
from trino_tpu.sql.planner.fragmenter import RemoteSourceNode


@dataclasses.dataclass
class TaskRequest:
    """Everything a worker needs to run one task (pickle wire format;
    reference: TaskUpdateRequest posted to POST /v1/task/{taskId})."""

    task_id: str
    query_id: str
    fragment_root: P.PlanNode
    splits: Dict[int, List]  # scan plan-node id -> [Split]
    upstream: Dict[int, List]  # fragment id -> [(base_url, task_id, buffer_id)]
    session_properties: Dict[str, object]
    # how many downstream consumers will pull this task's output (reference:
    # OutputBuffers — the consumer set is declared when the task is created)
    consumer_count: int = 1
    # when set, the task's output page is hash-partitioned by these channels
    # into consumer_count DISTINCT streams — consumer i pulls only partition
    # i (reference: PagePartitioner.java:134-149, FIXED_HASH_DISTRIBUTION's
    # producer half). None = every consumer reads the same stream.
    output_partition_channels: Optional[List[int]] = None
    # adaptive skew mitigation (trino_tpu/adaptive/): HOT partitions whose
    # rows this producer spreads round-robin across all partitions (probe
    # side of a salted repartition join) or replicates into every
    # partition (build side) — see parallel/exchange.spread_partition_ids
    # for the exactness argument
    skew_spread_partitions: Optional[List[int]] = None
    skew_replicate_partitions: Optional[List[int]] = None
    # spooled result protocol (server/segments.py): this task produces
    # the query's RESULT — its output writes size-bounded segments into
    # the worker's segment store instead of the output buffer, and the
    # statement response carries their URIs (the coordinator never pulls
    # the data). Set only on the root fragment's gather producers.
    spool_results: bool = False

    def to_bytes(self) -> bytes:
        return pickle.dumps(self)

    @staticmethod
    def from_bytes(data: bytes) -> "TaskRequest":
        return pickle.loads(data)


class FragmentExecutor(Executor):
    """Executes one plan fragment: scans read only the task's assigned
    splits; RemoteSourceNodes read pages pulled from upstream tasks."""

    def __init__(self, session, splits: Dict[int, List], remote_pages: Dict[int, List[Page]]):
        super().__init__(session)
        self._splits = splits
        self._remote_pages = remote_pages

    def _exec_TableScanNode(self, node: P.TableScanNode) -> Page:
        from trino_tpu import devcache
        from trino_tpu.exec import memory as _mem
        from trino_tpu.exec import staging

        conn = self.session.catalogs[node.catalog]
        splits = self._splits.get(node.id, [])
        # splits were assigned by the coordinator (static constraint already
        # applied); dynamic-filter domains collected in THIS fragment still
        # narrow the per-split scan
        constraint = self.scan_constraint(node)

        def load():
            # the pipelined engine (exec/staging.py): the task's assigned
            # splits scan in parallel on the shared pool, each consulting
            # the host-RAM tier, and the assembled columns transfer in
            # double-buffered blocks. STAGING_SECONDS keeps its worker
            # semantics: the whole fresh scan+assemble+transfer wall
            # (device-cache hits never reach this loader).
            t0 = time.perf_counter()
            page, rows, _prof = staging.staged_scan_page(
                self.session, node, conn, splits, constraint)
            M.STAGED_ROWS.inc(rows)
            M.STAGING_SECONDS.inc(time.perf_counter() - t0)
            return page, rows, _mem.page_bytes(page), len(splits)

        with tracing.span("device/staging", table=node.table,
                          splits=len(splits)) as sp:
            # the worker-side buffer pool: this task's assigned split set
            # is the shard component, so a retried/speculative attempt of
            # the same splits — or the next query over them — stays warm
            ent, disposition = devcache.cached_stage(
                self.session, node, constraint, {},
                devcache.splits_shard(splits), load)
            page, rows = ent.value, ent.rows
            self.scan_stats[node.id] = rows
            self._pending_scan[node.id] = (len(splits), rows)
            self.scan_cache[node.id] = disposition
            # a warm scan transferred nothing: the span's staged_rows is
            # the zero-transfer proof signal (see trino_tpu/devcache/)
            sp.set("staged_rows", 0 if disposition == "hit" else rows)
            sp.set("cache", disposition)
        return page

    def _exec_RemoteSourceNode(self, node: RemoteSourceNode) -> Page:
        pages = self._remote_pages.get(node.fragment_id, [])
        pages = [p for p in pages if p.num_rows > 0]
        if not pages:
            return Page.all_dead(node.types)
        page = pages[0]
        for p in pages[1:]:
            page = Page.concat_pages(page, p)
        return page


class SqlTask:
    """One task: FSM + executor thread + output buffer.

    State flow PLANNED→RUNNING→FLUSHING→FINISHED mirrors TaskState.java:21;
    FLUSHING = body finished, buffer still draining to consumers.
    """

    def __init__(self, request: TaskRequest, session_factory,
                 traceparent: Optional[str] = None, recorder=None,
                 otlp=None, segment_store=None):
        self.request = request
        self.state: StateMachine[str] = task_state_machine()
        # worker half of the query's trace: same trace id, spans rooted
        # under the coordinator's propagated (schedule) span; a missing
        # header starts a detached local trace (direct task POSTs in tests)
        ctx = tracing.parse_traceparent(traceparent)
        self.tracer = tracing.Tracer(
            trace_id=ctx[0] if ctx else None,
            root_parent_id=ctx[1] if ctx else None)
        # worker-process flight recorder + OTLP exporter (both optional):
        # closed spans mirror into the ring; the finished task's span
        # dump ships to the collector under the propagated trace id
        self.tracer.recorder = recorder
        self._otlp = otlp
        from trino_tpu.server.buffer import DEFAULT_MAX_BUFFER_BYTES

        sink_max = int(request.session_properties.get(
            "sink_max_buffer_bytes") or DEFAULT_MAX_BUFFER_BYTES)
        # flow-ledger labels: full-wait stall samples carry this task's
        # stage (task ids are {query}.{fragment}.{worker}.a{attempt})
        self.stage_id = _task_stage_id(request.task_id)
        if request.output_partition_channels is not None:
            self.output = PartitionedOutputBuffer(
                request.consumer_count, max_buffer_bytes=sink_max,
                stall_stage=self.stage_id)
        else:
            self.output = OutputBuffer(
                request.consumer_count, max_buffer_bytes=sink_max,
                stall_key=(self.stage_id, None))
        # spooled result protocol: when this task produces the query's
        # result, its serialized output chunks roll into size-bounded
        # segments in the worker's segment store (server/segments.py)
        # instead of the output buffer — the coordinator collects the
        # segment metadata from task status and never pulls the data
        self._result_writer = None
        self.result_segments: List[dict] = []
        if (request.spool_results and segment_store is not None
                and request.output_partition_channels is None):
            props = request.session_properties
            from trino_tpu.server.segments import DEFAULT_SEGMENT_BYTES

            self._result_writer = segment_store.writer(
                request.query_id,
                target_bytes=int(props.get("spooled_results_segment_bytes")
                                 or DEFAULT_SEGMENT_BYTES),
                ttl_s=int(props.get("result_segment_ttl_ms")
                          or 300_000) / 1e3)
        self.failure: Optional[str] = None
        # peak device/host bytes observed by this task's executors — rolls
        # up into the worker announce for cluster memory management
        # (reference: QueryContext reservations -> ClusterMemoryPool)
        self.peak_memory_bytes = 0
        # --- task-level stats (reference: TaskStats + the OperatorStats it
        # aggregates): every retired executor folds its node_stats in here
        # under _stats_lock, and status responses snapshot the same way —
        # so a coordinator poll mid-execution reads a consistent rollup.
        self.operator_stats: Dict[int, "OperatorStats"] = {}
        # kernel-ledger rollup (obs/devprofiler.py): retired executors
        # fold their kernel_stats here; status snapshots ship the rows
        self.kernel_stats: Dict[tuple, dict] = {}
        self._stats_lock = threading.Lock()
        self.total_splits = sum(len(v) for v in request.splits.values())
        self.splits_completed = 0
        self.device_seconds = 0.0
        self.input_rows = 0  # connector/exchange rows entering the fragment
        self.output_rows = 0
        self.output_bytes = 0
        # per-partition LIVE output rows (hash-partitioned producers only):
        # the adaptive skew signal — counted pre-serialization because
        # serde compression flattens a constant hot key to almost no bytes
        self.partition_rows: Optional[List[int]] = None
        self.spill_count = 0
        # revocable-tier bytes shed on this task's behalf + yield-event
        # count (exec/memory.py spill path) — queryStats.memory inputs
        self.shed_bytes = 0
        self.yield_events = 0
        # device-cache dispositions of this task's scans (warm-serving
        # telemetry: rolls up task -> stage -> query and into the CLI)
        self.device_cache_hits = 0
        self.device_cache_misses = 0
        # exchange clients this task created (flow-ledger rollup: their
        # pull/stall seconds feed the transferS/stallS stats the straggler
        # detector attributes causes from)
        self._exchange_clients: List = []
        self.started_at = time.monotonic()
        self.ended_at: Optional[float] = None
        self._session_factory = session_factory
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _track_executor(self, ex) -> None:
        self._live_executor = ex
        if ex.memory.peak > self.peak_memory_bytes:
            # task-level reservation event: the TASK peak is max over its
            # (sequential) executors, so deltas here never double-count
            # the per-split/per-batch executor peaks the way summing
            # per-executor events would (exec/memory.py owner mode is for
            # the coordinator-local one-executor-per-query path)
            delta = ex.memory.peak - self.peak_memory_bytes
            self.peak_memory_bytes = ex.memory.peak
            from trino_tpu.obs.memledger import MEMORY_LEDGER, POOL_DEVICE

            MEMORY_LEDGER.record_event(
                "reserve", POOL_DEVICE,
                f"query:{self.request.query_id}", delta)

    def _retire_executor(self, ex, splits: int = 0, input_rows: int = 0,
                         device_s: float = 0.0) -> None:
        """Fold a finished executor's per-operator stats into the task's
        accumulated rollup (one executor per bulk body, per split, or per
        streaming batch — accumulation keeps stats additive across all
        three driver shapes)."""
        import dataclasses as _dc

        self._track_executor(ex)
        with self._stats_lock:
            for nid, st in ex.node_stats.items():
                have = self.operator_stats.get(nid)
                if have is None:
                    self.operator_stats[nid] = _dc.replace(st)
                else:
                    have.add(st)
            from trino_tpu.obs.devprofiler import merge_kernel_rows

            merge_kernel_rows(
                self.kernel_stats,
                list(getattr(ex, "kernel_stats", {}).values()))
            # the fragment body IS the device execution: charge its wall to
            # the fragment root's device-seconds
            root_st = self.operator_stats.get(self.request.fragment_root.id)
            if root_st is not None:
                root_st.device_s += device_s
            self.device_seconds += device_s
            self.splits_completed += splits
            self.input_rows += input_rows
            self.spill_count += len(ex.memory.spills)
            self.shed_bytes += ex.memory.shed_bytes
            self.yield_events += ex.memory.yields
            self.device_cache_hits += sum(
                1 for d in ex.scan_cache.values() if d == "hit")
            self.device_cache_misses += sum(
                1 for d in ex.scan_cache.values() if d == "miss")

    def stats_snapshot(self) -> dict:
        """Point-in-time task stats for ``GET /v1/task/{id}/status`` —
        the wire shape the coordinator's stage/query rollup consumes."""
        live = getattr(self, "_live_executor", None)
        peak = max(self.peak_memory_bytes,
                   live.memory.peak if live is not None else 0)
        # hash-partitioned producers break their output bytes down per
        # partition — the skew signal the adaptive re-planner reads
        part_bytes = (self.output.partition_enqueued_bytes
                      if isinstance(self.output, PartitionedOutputBuffer)
                      else None)
        # flow-ledger per-task seconds: exchange/spool pull wall and
        # backpressure stalls (producer full-waits + consumer empty
        # polls) — the straggler detector's cause inputs
        transfer_s = sum(c.pulled_seconds for c in self._exchange_clients)
        stall_s = (self.output.stalled_seconds
                   + sum(c.stalled_seconds for c in self._exchange_clients))
        with self._stats_lock:
            ops = [self.operator_stats[k].to_dict()
                   for k in sorted(self.operator_stats)]
            elapsed = (self.ended_at or time.monotonic()) - self.started_at
            snap = {
                "elapsedS": round(elapsed, 6),
                "deviceS": round(self.device_seconds, 6),
                "transferS": round(transfer_s, 6),
                "stallS": round(stall_s, 6),
                "completedSplits": self.splits_completed,
                "totalSplits": self.total_splits,
                "inputRows": self.input_rows,
                "outputRows": self.output_rows,
                "outputBytes": self.output_bytes,
                "peakBytes": peak,
                "spills": self.spill_count,
                "shedBytes": self.shed_bytes,
                "yieldEvents": self.yield_events,
                "deviceCacheHits": self.device_cache_hits,
                "deviceCacheMisses": self.device_cache_misses,
                "operatorStats": ops,
                "kernelStats": [dict(self.kernel_stats[k])
                                for k in sorted(self.kernel_stats)],
            }
            if part_bytes is not None:
                snap["partitionBytes"] = part_bytes
            if self.partition_rows is not None:
                snap["partitionRows"] = list(self.partition_rows)
            return snap

    @property
    def memory_bytes(self) -> int:
        """Reservation gauge for cluster memory management: the executor's
        peak while the body RUNS; once the body finished (FLUSHING) it
        decays to what the drain actually still holds — the result page
        being chunked out plus buffered frames — so a transient
        mid-execution peak does not outlive the body and starve admission
        (exact liveness would need per-page refcounts)."""
        state = self.state.get()
        if state in ("FINISHED", "FAILED", "CANCELED"):
            return 0
        if state not in ("PLANNED", "RUNNING"):
            return int(getattr(self, "flushing_bytes", 0)
                       + self.output.buffered_bytes)
        live = getattr(self, "_live_executor", None)
        peak = live.memory.peak if live is not None else 0
        return max(self.peak_memory_bytes, peak)

    def start(self) -> None:
        if self.state.compare_and_set("PLANNED", "RUNNING"):
            self._thread.start()

    def _run(self) -> None:
        task_span = self.tracer.start_span(
            "task", task_id=self.request.task_id,
            query_id=self.request.query_id)
        try:
            with tracing.activate(self.tracer, task_span.span_id):
                self._run_body()
        except Exception as e:  # noqa: BLE001 — reported through task status
            self.failure = f"{e}\n{traceback.format_exc()}"
            task_span.set("error", str(e).split("\n")[0][:300])
            if self._result_writer is not None:
                # no manifest will ever point at a failed attempt's
                # segments — reclaim them now, not at TTL
                self._result_writer.abandon()
            self.output.abort(str(e))
            self.state.set("FAILED")
        finally:
            self.ended_at = time.monotonic()
            self._observe_operator_metrics()
            if self.peak_memory_bytes:
                from trino_tpu.obs.memledger import (MEMORY_LEDGER,
                                                     POOL_DEVICE)

                MEMORY_LEDGER.record_event(
                    "release", POOL_DEVICE,
                    f"query:{self.request.query_id}",
                    self.peak_memory_bytes, reason="done")
            task_span.set("state", self.state.get())
            self.tracer.end_span(task_span)
            if self._otlp is not None:
                self._otlp.export_spans(
                    self.tracer.to_dicts(), self.tracer.trace_id,
                    {"query_id": self.request.query_id,
                     "task_id": self.request.task_id,
                     "task.state": self.state.get()})

    def _observe_operator_metrics(self) -> None:
        """Feed the per-operator-kind registry metrics from this task's
        accumulated stats, once, at task completion."""
        with self._stats_lock:
            snapshot = [(st.operator, st.wall_s, st.output_rows)
                        for st in self.operator_stats.values()]
        for operator, wall_s, rows in snapshot:
            M.OPERATOR_WALL_SECONDS.observe(wall_s, operator)
            if rows:
                M.OPERATOR_ROWS.inc(rows, operator)

    def _run_body(self) -> None:
        req = self.request
        # fault injection (reference: FailureInjector.java:41-69 —
        # keyed by trace/stage/partition/attempt; here by task-id match)
        inject = str(req.session_properties.get("failure_injection") or "")
        if inject and inject in req.task_id:
            raise RuntimeError(f"injected failure for {req.task_id}")
        # straggler injection ("substr:seconds") — exercises the FTE
        # scheduler's speculative execution (reference:
        # FailureInjector's sleep mode)
        slow = str(req.session_properties.get("slow_injection") or "")
        if slow:
            sub, _, secs = slow.partition(":")
            if sub and sub in req.task_id:
                time.sleep(float(secs or "5"))
        session = self._session_factory(req.session_properties)
        if self._try_streaming(req, session):
            return
        # pull all upstream fragments first (bulk-synchronous bodies:
        # joins/final aggs/sorts need their whole input; the pull itself
        # streams + backpressures)
        remote_pages: Dict[int, List[Page]] = {}
        for fid, locations in req.upstream.items():
            from trino_tpu.server.exchange_client import ExchangeClient, TaskLocation

            client = ExchangeClient(
                [TaskLocation(u, t, b) for u, t, b in locations],
                owner=f"task:{req.task_id}",
                stall_key=(self.stage_id, None))
            self._exchange_clients.append(client)
            client.start()
            remote_pages[fid] = client.pages()
        ex = FragmentExecutor(session, req.splits, remote_pages)
        self._track_executor(ex)
        with tracing.span("device/execute") as sp:
            t0 = time.perf_counter()
            page = ex.execute_checked(req.fragment_root)
            device_s = time.perf_counter() - t0
            sp.set("device_seconds", round(device_s, 6))
            sp.set("staged_rows", sum(ex.scan_stats.values()))
            sp.set("output_rows", int(page.num_rows))
        M.DEVICE_SECONDS.inc(device_s)
        remote_rows = sum(
            p.num_rows for pages in remote_pages.values() for p in pages)
        self._retire_executor(
            ex, splits=self.total_splits,
            input_rows=sum(ex.scan_stats.values()) + remote_rows,
            device_s=device_s)
        from trino_tpu.exec.memory import page_bytes

        page = page.compact()
        self.flushing_bytes = page_bytes(page)  # held through the drain
        with self._stats_lock:
            self.output_rows += page.num_rows
            self.output_bytes += self.flushing_bytes
        self.state.set("FLUSHING")
        chunk_rows = self._chunk_rows(page)
        if req.output_partition_channels is not None:
            # hash-partitioned shuffle producer: split the output by
            # key hash (same splitmix64 combine as the device exchange,
            # so every producer places a key identically) and enqueue
            # each partition into its consumer's stream. Under FTE the
            # per-partition streams spool FIRST (durability before
            # visibility — retried consumers re-read partition files).
            parts = self._partition_pages(page)
            part_frames = [
                [serialize_page(c)
                 for c in _chunk_pages(part.compact(), chunk_rows)]
                for part in parts
            ]
            if spool_directory():
                self._spool_partitioned(part_frames)
            for pid, frames in enumerate(part_frames):
                for pb in frames:
                    self.output.enqueue_partition(pid, pb)
            self.output.set_complete()
            self.state.set("FINISHED")
            return
        if self._result_writer is not None:
            # spooled result output: serialized chunks roll straight into
            # size-bounded segments in the worker's segment store —
            # nothing enters the output buffer, so this producer never
            # parks on a consumer that, by design, is not coming
            with tracing.span("segment/write") as sp:
                for c in _chunk_pages(page, chunk_rows):
                    self._result_writer.add(serialize_page(c),
                                            int(c.num_rows))
                self._finish_result_spool()
                sp.set("segments", len(self.result_segments))
                sp.set("rows", int(page.live_count()))
            self.output.set_complete()
            self.state.set("FINISHED")
            return
        # STREAMING output: size-bounded chunks enqueue as they
        # serialize, so consumers pull chunk 0 while chunk 1 encodes,
        # and the bounded buffer's watermark gives real backpressure
        # (reference invariant SURVEY §A.6: incremental page flow).
        # Under FTE (spool configured) the whole output spools FIRST —
        # retried consumers must find the complete durable copy — which
        # trades pipelining for recoverability, as the reference's FTE
        # exchanges do.
        if spool_directory():
            page_frames = [
                serialize_page(c) for c in _chunk_pages(page, chunk_rows)
            ]
            self._spool(page_frames)
            for pb in page_frames:
                self.output.enqueue(pb)
        else:
            for c in _chunk_pages(page, chunk_rows):
                self.output.enqueue(serialize_page(c))  # blocks at watermark
        self.output.set_complete()
        self.state.set("FINISHED")

    # ------------------------------------------------------- streaming loop
    @staticmethod
    def _streamable_leaf(root: P.PlanNode, leaf_type):
        """The single ``leaf_type`` leaf of a streamable fragment, else
        None. Streamable = every operator on the chain is row-local or a
        PARTIAL aggregation: executing it per arriving chunk/split and
        concatenating outputs is semantically identical to one bulk run
        (partial-agg outputs may legally contain multiple rows per group —
        the downstream FINAL merge makes them one). This is the
        WorkProcessor pull model (reference: operator/WorkProcessor.java:31,
        Driver.java:449's blocked futures) with the micro-batch as the unit
        instead of the page."""
        node = root
        while True:
            if isinstance(node, leaf_type):
                return node
            if isinstance(node, (P.FilterNode, P.ProjectNode, P.CompactNode)):
                node = node.source
                continue
            if isinstance(node, P.AggregationNode) and node.step == "partial":
                node = node.source
                continue
            return None

    def _streamable_source(self, root: P.PlanNode):
        return self._streamable_leaf(root, RemoteSourceNode)

    @staticmethod
    def _streaming_final_agg(root: P.PlanNode):
        """The (final-agg node, its RemoteSourceNode) when the fragment is a
        hash-distributed FINAL aggregation whose states the intermediate
        fold can merge — the streaming consumer then folds arriving partial
        states instead of buffering them all (reference:
        AggregationNode.Step.INTERMEDIATE)."""
        from trino_tpu.exec.executor import Executor

        if not (isinstance(root, P.AggregationNode) and root.step == "final"
                and isinstance(root.source, RemoteSourceNode)):
            return None
        for call in root.aggregates:
            if call.distinct or call.function not in Executor.MERGEABLE_STATE_FNS:
                return None
        return root, root.source

    # accumulate arriving pages to at least this many rows before running
    # the fragment body over the batch (tiny per-page dispatches would
    # dominate otherwise)
    STREAM_BATCH_ROWS = 65536

    def _streamable_scan(self, root: P.PlanNode):
        """The single TableScanNode leaf of a row-local/partial-agg chain,
        else None — the SPLIT-at-a-time driver shape (reference: the
        driver loop processing one split per quantum, SqlTaskExecution's
        per-split drivers)."""
        return self._streamable_leaf(root, P.TableScanNode)

    def _partition_pages(self, page: Page) -> List[Page]:
        """Hash-partition one output page into consumer_count per-partition
        pages, applying the adaptive skew salting when the re-planner
        annotated this producer: hot partitions spread round-robin (probe
        side) or replicate into every partition (build side) — the
        producer half of the salted repartition join."""
        from trino_tpu.exec.memory import partition_page_host

        import numpy as np

        req = self.request
        # ONE hash pass per page: the pid array is computed once (with the
        # per-dictionary vocab hashes cached across a streaming producer's
        # pages) and reused by the salting spread, the partitioning
        # re-send, AND the skew-detection accounting below — previously
        # the accounting re-walked every partition page (N live_count
        # passes) after the hash pass
        if not hasattr(self, "_vocab_hash_cache"):
            self._vocab_hash_cache = {}
        pids = _canonical_partition_ids(
            page, req.output_partition_channels, req.consumer_count,
            vocab_cache=self._vocab_hash_cache)
        spread = getattr(req, "skew_spread_partitions", None)
        if spread:
            from trino_tpu.parallel.exchange import spread_partition_ids

            # the cursor rotates ACROSS pages so a streaming producer's
            # per-page hot rows don't all restart at partition 0
            pids, self._spread_cursor = spread_partition_ids(
                pids, spread, req.consumer_count,
                start=getattr(self, "_spread_cursor", 0))
        parts = partition_page_host(
            page, req.output_partition_channels, req.consumer_count,
            pid=pids)
        replicate = getattr(req, "skew_replicate_partitions", None)
        if replicate:
            hot = {h: parts[h] for h in replicate if 0 <= h < len(parts)}
            out = []
            for q, part in enumerate(parts):
                for h, hp in hot.items():
                    if h != q and hp.live_count() > 0:
                        part = Page.concat_pages(part, hp)
                out.append(part)
            parts = out
        # detection accounting straight off the (post-spread) pid array:
        # one bincount, and replicated hot-partition copies no longer
        # inflate the skew signal the re-planner reads
        n = page.num_rows
        live = (np.ones(n, bool) if page.sel is None
                else np.asarray(page.sel).astype(bool))
        counts = np.bincount(np.asarray(pids)[live],
                             minlength=req.consumer_count)
        with self._stats_lock:
            if self.partition_rows is None:
                self.partition_rows = [0] * req.consumer_count
            for pid in range(req.consumer_count):
                self.partition_rows[pid] += int(counts[pid])
        return parts

    def _finish_result_spool(self) -> None:
        """Seal the result-segment writer: roll the last partial segment
        and publish the manifest metadata task status carries."""
        if self._result_writer is None:
            return
        metas = self._result_writer.finish()
        self.result_segments = [m.manifest_entry() for m in metas]

    def _complete_output(self) -> None:
        """Completion chokepoint for the streaming driver shapes: seal
        the result spool (if this task produces the query's result),
        then mark the buffer complete."""
        self._finish_result_spool()
        self.output.set_complete()

    def _enqueue_out(self, out: Page, part_channels, consumer_count) -> None:
        """Partition-aware enqueue of one output page (shared by the
        streaming paths: per-batch chains, per-split scans, and the fold
        path's finalization)."""
        if out.num_rows == 0 or out.live_count() == 0:
            return
        from trino_tpu.exec.memory import page_bytes

        with self._stats_lock:
            self.output_rows += int(out.live_count())
            self.output_bytes += page_bytes(out)
        chunk_rows = self._chunk_rows(out)
        if self._result_writer is not None and part_channels is None:
            # spooled result output (streaming shapes): chunks roll into
            # the segment store as they serialize — disk-bounded, so the
            # stream loop never blocks on an output-buffer watermark
            with tracing.span("segment/write") as sp:
                for c in _chunk_pages(out, chunk_rows):
                    self._result_writer.add(serialize_page(c),
                                            int(c.num_rows))
                sp.set("rows", int(out.live_count()))
            return
        if part_channels is not None:
            for pid, part in enumerate(self._partition_pages(out)):
                for c in _chunk_pages(part.compact(), chunk_rows):
                    self.output.enqueue_partition(pid, serialize_page(c))
        else:
            for c in _chunk_pages(out, chunk_rows):
                self.output.enqueue(serialize_page(c))

    def _try_split_streaming(self, req: TaskRequest, session) -> bool:
        """Execute a scan-rooted streamable fragment ONE SPLIT AT A TIME,
        enqueueing each split's output as it completes: consumers pull
        split 0's rows while split 1 scans, and task memory is bounded by
        one split instead of the whole assignment (the per-driver split
        processing of the reference's task execution — splits are no
        longer an all-at-once bulk scan)."""
        scan = self._streamable_scan(req.fragment_root)
        if scan is None or scan.id not in req.splits:
            return False
        splits = req.splits[scan.id]
        if len(splits) <= 1:
            return False  # nothing to pipeline
        # the span covers the whole stage; device_seconds counts ONLY the
        # execute calls (enqueue blocks at the output watermark, and that
        # backpressure wait must not read as device time)
        with tracing.span("device/execute", mode="split-streaming") as sp:
            device_s = 0.0
            staged_rows = 0
            for split in splits:
                ex = FragmentExecutor(session, {scan.id: [split]}, {})
                self._track_executor(ex)
                t0 = time.perf_counter()
                out = ex.execute_checked(req.fragment_root).compact()
                split_s = time.perf_counter() - t0
                device_s += split_s
                staged_rows += sum(ex.scan_stats.values())
                self._retire_executor(
                    ex, splits=1, input_rows=sum(ex.scan_stats.values()),
                    device_s=split_s)
                self._enqueue_out(out, req.output_partition_channels,
                                  req.consumer_count)
            sp.set("device_seconds", round(device_s, 6))
            sp.set("staged_rows", staged_rows)
            sp.set("splits", len(splits))
        M.DEVICE_SECONDS.inc(device_s)
        self.state.set("FLUSHING")
        self._complete_output()
        self.state.set("FINISHED")
        return True

    def _try_streaming(self, req: TaskRequest, session) -> bool:
        """Micro-batch driver loop for streamable consumer fragments: pull
        chunks from the ONE upstream, execute the fragment per batch, and
        enqueue each batch's output immediately — the consumer makes
        progress (and its output becomes pullable) while the producer is
        still FLUSHING, and holds only ~batch rows of input at a time.
        Returns False when the fragment shape or config requires the bulk
        path (joins/final aggs; FTE spooling needs the complete output
        durable before visibility, so it stays bulk)."""
        if spool_directory():
            return False
        if not req.upstream and len(req.splits) == 1:
            return self._try_split_streaming(req, session)
        final_agg = self._streaming_final_agg(req.fragment_root)
        src = (final_agg[1] if final_agg is not None
               else self._streamable_source(req.fragment_root))
        if src is None or len(req.upstream) != 1:
            return False
        if req.splits:  # mixed scan+remote shapes are not chain-shaped
            return False
        locations = req.upstream.get(src.fragment_id)
        if locations is None:
            return False
        from trino_tpu.server.exchange_client import ExchangeClient, TaskLocation

        client = ExchangeClient(
            [TaskLocation(u, t, b) for u, t, b in locations],
            owner=f"task:{req.task_id}", stall_key=(self.stage_id, None))
        self._exchange_clients.append(client)
        client.start()
        # device_clock accumulates ONLY the executor calls: the stream loop
        # also waits on upstream pulls and output backpressure, and that
        # wall time belongs to the exchange/pull spans, not device_seconds
        device_clock = [0.0]

        def enqueue_out(out: Page) -> None:
            self._enqueue_out(out, req.output_partition_channels,
                              req.consumer_count)

        def emit(batch: List[Page]) -> None:
            batch_rows = sum(p.num_rows for p in batch)
            page = batch[0]
            for p in batch[1:]:
                page = Page.concat_pages(page, p)
            ex = FragmentExecutor(session, {}, {src.fragment_id: [page]})
            self._track_executor(ex)
            t0 = time.perf_counter()
            out = ex.execute_checked(req.fragment_root).compact()
            batch_s = time.perf_counter() - t0
            device_clock[0] += batch_s
            self._retire_executor(ex, input_rows=batch_rows, device_s=batch_s)
            enqueue_out(out)

        if final_agg is not None:
            # fold arriving partial-state pages into ONE running state page
            # (intermediate merge), finalize once the upstream is exhausted
            node = final_agg[0]
            running: Optional[Page] = None
            batch: List[Page] = []
            batch_rows = 0

            def record_agg_stats(ex, wall_s, in_rows, out_page,
                                 is_final=False):
                """aggregate_intermediate/final bypass the execute() stats
                wrapper — record the aggregation node's OperatorStats by
                hand so fold fragments still annotate EXPLAIN ANALYZE and
                feed the per-operator metrics. Only the finalization's rows
                count as operator OUTPUT (intermediate folds maintain
                internal state); every pass counts toward wall/input."""
                from trino_tpu.exec.memory import page_bytes

                st = ex.node_stats.setdefault(
                    node.id, OperatorStats(node.id, "Aggregation"))
                st.wall_s += wall_s
                st.input_rows += in_rows
                if is_final:
                    st.output_rows += int(out_page.num_rows)
                    st.output_bytes += page_bytes(out_page)
                st.invocations += 1

            def fold(running, batch):
                batch_rows = sum(p.num_rows for p in batch)
                page = batch[0]
                for p in batch[1:]:
                    page = Page.concat_pages(page, p)
                if running is not None:
                    page = Page.concat_pages(running, page)
                ex = FragmentExecutor(session, {}, {})
                self._track_executor(ex)
                t0 = time.perf_counter()
                out = ex.aggregate_intermediate(node, page).compact()
                ex.raise_errors()
                fold_s = time.perf_counter() - t0
                device_clock[0] += fold_s
                record_agg_stats(ex, fold_s, batch_rows, out)
                self._retire_executor(ex, input_rows=batch_rows,
                                      device_s=fold_s)
                return out

            with tracing.span("device/execute", mode="streaming-fold") as sp:
                in_rows = 0
                for page in client.iter_pages():
                    if page.num_rows == 0:
                        continue
                    batch.append(page)
                    batch_rows += page.num_rows
                    in_rows += page.num_rows
                    if batch_rows >= self.STREAM_BATCH_ROWS:
                        running = fold(running, batch)
                        batch, batch_rows = [], 0
                if batch:
                    running = fold(running, batch)
                if running is None:
                    running = Page.all_dead(src.types)
                ex = FragmentExecutor(session, {}, {})
                t0 = time.perf_counter()
                out = ex.aggregate_final(node, running).compact()
                ex.raise_errors()
                final_s = time.perf_counter() - t0
                device_clock[0] += final_s
                record_agg_stats(ex, final_s, int(running.num_rows), out,
                                 is_final=True)
                self._retire_executor(ex, device_s=final_s)
                sp.set("device_seconds", round(device_clock[0], 6))
                sp.set("input_rows", in_rows)
            M.DEVICE_SECONDS.inc(device_clock[0])
            self.state.set("FLUSHING")
            enqueue_out(out)
            self._complete_output()
            self.state.set("FINISHED")
            return True
        batch: List[Page] = []
        batch_rows = 0
        with tracing.span("device/execute", mode="streaming") as sp:
            in_rows = 0
            for page in client.iter_pages():
                if page.num_rows == 0:
                    continue
                batch.append(page)
                batch_rows += page.num_rows
                in_rows += page.num_rows
                if batch_rows >= self.STREAM_BATCH_ROWS:
                    emit(batch)
                    batch, batch_rows = [], 0
            if batch:
                emit(batch)
            sp.set("device_seconds", round(device_clock[0], 6))
            sp.set("input_rows", in_rows)
        M.DEVICE_SECONDS.inc(device_clock[0])
        self.state.set("FLUSHING")
        self._complete_output()
        self.state.set("FINISHED")
        return True

    # target serialized bytes per output chunk (reference: the page-size
    # targets of PartitionedOutputBuffer / PagesSerde)
    DEFAULT_CHUNK_BYTES = 4 << 20

    def _chunk_rows(self, page: Page) -> int:
        target = int(self.request.session_properties.get(
            "task_output_chunk_bytes") or self.DEFAULT_CHUNK_BYTES)
        return max(1, target // page.row_byte_estimate()) if page.num_rows else 1

    def _spool_partitioned(self, part_frames) -> None:
        """Spool each partition stream to its own durable file
        ({task}.p{pid}.pages) — the FTE contract for hash-distributed
        stages (reference: FileSystemExchange sink files per partition)."""
        spool_dir = spool_directory()
        if not spool_dir:
            return
        import os

        from trino_tpu.server import wire

        os.makedirs(spool_dir, exist_ok=True)
        for pid, frames in enumerate(part_frames):
            path = os.path.join(
                spool_dir, f"{self.request.task_id}.p{pid}.pages")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(wire.frame_pages(frames))
            os.replace(tmp, path)

    def _spool(self, page_frames) -> None:
        """Persist the task's output to the shared spool directory
        (reference: the FTE tier's spooled exchange —
        spi/exchange/ExchangeManager.java:39 + FileSystemExchange.java:70):
        a finished task's pages survive the producing worker, so retried
        consumers re-read them instead of recomputing the stage."""
        spool_dir = spool_directory()
        if not spool_dir:
            return
        import os

        from trino_tpu.server import wire

        os.makedirs(spool_dir, exist_ok=True)
        path = os.path.join(spool_dir, f"{self.request.task_id}.pages")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(wire.frame_pages(page_frames))
        os.replace(tmp, path)  # atomic publish: readers never see partials

    def info(self) -> dict:
        return {
            "taskId": self.request.task_id,
            "state": self.state.get(),
            "failure": self.failure,
            "bufferedBytes": self.output.buffered_bytes,
            "memoryBytes": self.memory_bytes,
            # spooled result protocol: the segments this task wrote (the
            # coordinator assembles the statement manifest from these)
            "resultSegments": list(self.result_segments),
            # worker-reported stats ride every status response — the
            # coordinator's stage/query rollup reads them from its
            # status-polling loop (reference: TaskStatus carrying TaskStats)
            "stats": self.stats_snapshot(),
        }


def _task_stage_id(task_id: str):
    """The fragment (stage) id embedded in a coordinator task id
    ({query}.{fragment}.{worker}.a{attempt}); None for free-form ids
    (direct task POSTs in tests)."""
    parts = task_id.split(".")
    if len(parts) >= 4 and parts[-1].startswith("a"):
        try:
            return int(parts[-3])
        except ValueError:
            return None
    return None


def _chunk_pages(page: Page, chunk_rows: int):
    """Yield size-bounded row slices of a compacted page (empty pages yield
    nothing — downstream treats absence as zero rows)."""
    n = page.num_rows
    if n == 0 or page.live_count() == 0:
        return
    for lo in range(0, n, chunk_rows):
        yield page.slice_rows(lo, min(n, lo + chunk_rows))


_VOCAB_CACHE_MAX = 8  # distinct vocabularies a producer realistically shares


def _canonical_partition_ids(page: Page, channels, parts: int,
                             vocab_cache=None):
    """Per-row partition ids that agree ACROSS producer processes.

    partition_page_host's value hash is dictionary-scoped for varchar
    columns (int32 codes are page-local), which is fine for the spill path
    (one process, one dictionary) but would split equal string keys across
    FINAL tasks here. Varchar columns therefore hash their canonical UTF-8
    string per vocab entry (blake2b-8) and map codes through that table;
    other columns keep the shared splitmix64 value hash.

    ``vocab_cache`` (optional dict) memoizes the per-vocabulary hash
    table across a producer's pages — streaming producers share one
    dictionary across hundreds of pages, and re-blake2b-ing the whole
    vocabulary per page was the dominant per-call hash cost. Entries hold
    a strong reference to their Dictionary so the id key can never be
    reused by a different vocabulary; the cache is capped (FIFO) so
    producers whose pages carry PER-PAGE dictionaries cannot grow it or
    pin vocabularies unboundedly."""
    import hashlib

    import numpy as np

    from trino_tpu.exec.memory import _NULL_HASH, _mix64_np

    def _vocab_hashes(d):
        if vocab_cache is not None:
            hit = vocab_cache.get(id(d))
            if hit is not None and hit[0] is d:
                return hit[1]
        table = np.array(
            [
                int.from_bytes(
                    hashlib.blake2b(v.encode(), digest_size=8).digest(),
                    "little")
                for v in d.values
            ] or [0],
            dtype=np.uint64,
        )
        if vocab_cache is not None:
            while len(vocab_cache) >= _VOCAB_CACHE_MAX:
                vocab_cache.pop(next(iter(vocab_cache)))
            vocab_cache[id(d)] = (d, table)
        return table

    n = page.num_rows
    h = np.zeros(n, np.uint64)
    for ch in channels:
        col = page.columns[ch]
        if col.type.is_varchar and col.dictionary is not None:
            vocab_hash = _vocab_hashes(col.dictionary)
            codes = np.asarray(col.values)
            k = vocab_hash[np.clip(codes, 0, len(vocab_hash) - 1)]
            k = np.where(codes < 0, np.uint64(_NULL_HASH), k)
        else:
            # low limb only: equal values share it and hi-limb presence is
            # data-dependent per producer — mixing hi would break cross-
            # producer placement consistency (see exec/memory.py)
            k = _mix64_np(np.asarray(col.values).astype(np.int64))
        if col.nulls is not None:
            k = np.where(np.asarray(col.nulls), np.uint64(_NULL_HASH), k)
        h = _mix64_np(h ^ k)
    return (h % np.uint64(parts)).astype(np.int64)


def spool_directory() -> Optional[str]:
    """Cluster-shared spool location ('object storage' of the walking
    skeleton); unset disables spooling."""
    import os

    return os.environ.get("TRINO_TPU_SPOOL_DIR") or None


class TaskManager:
    """All tasks on this worker (reference: SqlTaskManager.java:109)."""

    # retained terminal tasks (status queries/late acks) — oldest evicted
    # (reference: SqlTaskManager's task info cache expiry)
    MAX_TASK_HISTORY = 200

    def __init__(self, session_factory, recorder=None, otlp=None,
                 segment_store=None):
        self._tasks: Dict[str, SqlTask] = {}
        self._lock = threading.Lock()
        self._session_factory = session_factory
        # worker-process observability hookups, threaded into every task
        # (obs/flightrecorder.FlightRecorder / obs/otlp.OtlpExporter)
        self._recorder = recorder
        self._otlp = otlp
        # spooled result protocol: the store result-producing tasks
        # (TaskRequest.spool_results) write their segments into
        self._segment_store = segment_store

    def create_task(self, request: TaskRequest,
                    traceparent: Optional[str] = None) -> SqlTask:
        with self._lock:
            terminal = [tid for tid, t in self._tasks.items() if t.state.is_terminal()]
            for tid in terminal[: max(0, len(terminal) - self.MAX_TASK_HISTORY)]:
                del self._tasks[tid]
            task = self._tasks.get(request.task_id)
            if task is None:
                task = SqlTask(request, self._session_factory,
                               traceparent=traceparent,
                               recorder=self._recorder, otlp=self._otlp,
                               segment_store=self._segment_store)
                self._tasks[request.task_id] = task
                created = True
            else:
                created = False
        if created:
            M.TASKS_TOTAL.inc()
            if self._recorder is not None:
                self._recorder.record(
                    "event", "task-created", taskId=request.task_id,
                    queryId=request.query_id,
                    splits=sum(len(v) for v in request.splits.values()))
        task.start()
        return task

    def get(self, task_id: str) -> Optional[SqlTask]:
        with self._lock:
            return self._tasks.get(task_id)

    def cancel(self, task_id: str) -> None:
        with self._lock:
            task = self._tasks.pop(task_id, None)
        if task is not None:
            task.output.abort("canceled")
            task.state.set("CANCELED")
            if self._recorder is not None:
                self._recorder.record("event", "task-canceled",
                                      taskId=task_id,
                                      queryId=task.request.query_id)

    def list_info(self) -> List[dict]:
        with self._lock:
            return [t.info() for t in self._tasks.values()]

    def query_memory(self) -> Dict[str, int]:
        """Reserved bytes per query on this worker (peak-while-running /
        buffered-while-flushing, see SqlTask.memory_bytes): the per-node
        half of the cluster memory pool (reference:
        memory/LocalMemoryManager feeding ClusterMemoryManager through
        node status)."""
        with self._lock:
            out: Dict[str, int] = {}
            for t in self._tasks.values():
                if t.state.is_terminal():
                    continue
                qid = t.request.query_id
                out[qid] = out.get(qid, 0) + t.memory_bytes
            return out
