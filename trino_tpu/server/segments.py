"""Spooled result segments: the data plane of the spooled client protocol.

Reference: Trino 455's spooled client protocol (the same segment
mechanism the FTE exchange uses for spooling task outputs) — large
results are written as durable, size-bounded SEGMENTS by the process
that produced them (a worker for the root fragment's output, the
coordinator for coordinator-local/fast-path queries), the statement
response carries a segment MANIFEST (`{uri, rows, bytes, codec}`), and
clients fetch the segments directly, in parallel, off the statement
protocol. The coordinator leaves the data path entirely for the
worker-direct shape.

Lifecycle (mirror of the exchange ``_cleanup_spool`` contract):

- a segment is deleted on client ACK (``DELETE /v1/segment/{id}``) —
  the normal path;
- un-acked segments expire by TTL (``result_segment_ttl_ms``), swept
  opportunistically (worker announce loop / coordinator submit);
- a server start sweeps ORPHANED segment files left in a shared spool
  directory by dead processes — a file's mtime is stamped with its
  EXPIRY at write, so only segments whose own TTL has passed are ever
  touched;
- every reclaimed byte is counted, by reason (ack | ttl | orphan).

Segment ids are unguessable capabilities (``{query_id}.s{n}-{token}``):
the segment endpoints are served without the cluster-internal HMAC so
plain protocol clients can fetch them — the reference's pre-signed
segment URI model.
"""
from __future__ import annotations

import dataclasses
import os
import secrets
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from trino_tpu.obs import metrics as M
from trino_tpu.obs.flowledger import FLOW_LEDGER

# default lifetime of an un-acked segment; the per-query
# ``result_segment_ttl_ms`` session property overrides per segment
DEFAULT_TTL_S = 300.0
# default target serialized bytes per segment
# (``spooled_results_segment_bytes`` overrides)
DEFAULT_SEGMENT_BYTES = 8 << 20

_SEGMENT_SUFFIX = ".seg"


@dataclasses.dataclass
class SegmentMeta:
    """One registered segment: identity + manifest fields + expiry."""

    segment_id: str
    query_id: str
    path: str
    rows: int
    bytes: int
    codec: str
    expires_at: float

    def manifest_entry(self) -> dict:
        """The fields a statement-response manifest carries (uri/ackUri
        are added by the coordinator, which knows the serving URLs)."""
        return {"id": self.segment_id, "rows": self.rows,
                "bytes": self.bytes, "codec": self.codec}


class SegmentStore:
    """Durable result segments served by one process.

    Backed by a directory: the cluster-shared spool (``TRINO_TPU_SPOOL_DIR``,
    under ``result-segments/``) when configured — the same "object storage"
    the FTE exchange spools to — else a per-process temp directory."""

    def __init__(self, node_id: str = "node",
                 base_dir: Optional[str] = None,
                 default_ttl_s: float = DEFAULT_TTL_S):
        from trino_tpu.server.task import spool_directory

        self.node_id = node_id
        self.default_ttl_s = float(default_ttl_s)
        if base_dir is None:
            spool = spool_directory()
            base_dir = (os.path.join(spool, "result-segments") if spool
                        else tempfile.mkdtemp(prefix="trino-tpu-segments-"))
        self.base_dir = base_dir
        self._segments: Dict[str, SegmentMeta] = {}
        self._lock = threading.Lock()
        self._last_sweep = time.monotonic()
        self.orphans_reclaimed_bytes = self._sweep_orphans()

    # ------------------------------------------------------------- writing
    def writer(self, query_id: str,
               target_bytes: int = DEFAULT_SEGMENT_BYTES,
               ttl_s: Optional[float] = None) -> "SegmentWriter":
        return SegmentWriter(self, query_id, target_bytes,
                             self.default_ttl_s if ttl_s is None else ttl_s)

    def _register(self, query_id: str, seq: int, frames: List[bytes],
                  rows: int, ttl_s: float) -> SegmentMeta:
        """Write one segment file (frames are length-prefixed serialized
        pages, the exchange wire framing) and register it for serving."""
        import struct

        segment_id = f"{query_id}.s{seq}-{secrets.token_hex(8)}"
        os.makedirs(self.base_dir, exist_ok=True)
        path = os.path.join(self.base_dir, segment_id + _SEGMENT_SUFFIX)
        tmp = path + ".tmp"
        nbytes = 0
        t0 = time.perf_counter()
        with open(tmp, "wb") as f:
            for frame in frames:
                f.write(struct.pack("<I", len(frame)))
                f.write(frame)
                nbytes += 4 + len(frame)
        os.replace(tmp, path)  # atomic publish, like the exchange spool
        FLOW_LEDGER.record_transfer(
            "spool-write", f"query:{query_id}", nbytes,
            time.perf_counter() - t0, pages=len(frames),
            src=FLOW_LEDGER.node_id or None, dst="segment-store",
            direction="send")
        expires_at = time.time() + ttl_s
        # the file's mtime IS its expiry: another server's boot-time
        # orphan sweep over a shared spool dir can then never reclaim a
        # live segment, whatever per-query TTL it was written with
        try:
            os.utime(path, (expires_at, expires_at))
        except OSError:
            pass
        meta = SegmentMeta(segment_id, query_id, path, int(rows), nbytes,
                           "pages", expires_at)
        with self._lock:
            self._segments[segment_id] = meta
        M.RESULT_SEGMENTS_WRITTEN.inc()
        M.RESULT_SEGMENT_BYTES.inc(nbytes, "written")
        return meta

    # ------------------------------------------------------------- serving
    def get(self, segment_id: str) -> Optional[SegmentMeta]:
        with self._lock:
            return self._segments.get(segment_id)

    def read(self, segment_id: str, start: int = 0,
             length: Optional[int] = None) -> Optional[bytes]:
        """Segment bytes (or a range of them); None when unknown/gone."""
        meta = self.get(segment_id)
        if meta is None:
            return None
        t0 = time.perf_counter()
        try:
            with open(meta.path, "rb") as f:
                if start:
                    f.seek(start)
                data = f.read() if length is None else f.read(length)
        except OSError:
            return None
        M.RESULT_SEGMENT_BYTES.inc(len(data), "served")
        FLOW_LEDGER.record_transfer(
            "segment-fetch", f"query:{meta.query_id}", len(data),
            time.perf_counter() - t0, src="segment-store",
            dst=FLOW_LEDGER.node_id or None, direction="send",
            status="range" if (start or length is not None) else "full")
        return data

    def ack(self, segment_id: str) -> bool:
        """Client ack: the segment was fetched — delete it now instead of
        waiting out the TTL. Idempotent."""
        return self._drop(segment_id, "ack")

    def discard(self, segment_id: str) -> bool:
        """Producer-side early drop (failed attempt, EXPLAIN ANALYZE's
        inner query): nobody will ever fetch this segment. Counted under
        the ``ttl`` reclaim reason — same 'never acked' meaning, just
        sooner — so the ack series stays a pure client-fetch signal."""
        return self._drop(segment_id, "ttl")

    def _drop(self, segment_id: str, reason: str) -> bool:
        with self._lock:
            meta = self._segments.pop(segment_id, None)
        if meta is None:
            return False
        self._reclaim(meta, reason)
        return True

    # ------------------------------------------------------------ lifecycle
    SWEEP_INTERVAL_S = 10.0

    def maybe_sweep(self) -> int:
        """Opportunistic TTL sweep (rate-limited): callers on periodic
        paths (announce loop, submit) invoke this instead of timing their
        own sweeps."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_sweep < self.SWEEP_INTERVAL_S:
                return 0
            self._last_sweep = now
        return self.sweep()

    def sweep(self) -> int:
        """Drop every expired segment; returns reclaimed bytes."""
        now = time.time()
        with self._lock:
            expired = [m for m in self._segments.values()
                       if m.expires_at <= now]
            for m in expired:
                del self._segments[m.segment_id]
        reclaimed = 0
        for m in expired:
            reclaimed += self._reclaim(m, "ttl")
        return reclaimed

    def drop_query(self, query_id: str) -> int:
        """Drop a query's segments early (FAILED/CANCELED: no client will
        ever fetch them). Counted as TTL reclaims — same 'nobody acked'
        meaning, just sooner."""
        with self._lock:
            doomed = [m for m in self._segments.values()
                      if m.query_id == query_id]
            for m in doomed:
                del self._segments[m.segment_id]
        return sum(self._reclaim(m, "ttl") for m in doomed)

    def _reclaim(self, meta: SegmentMeta, reason: str) -> int:
        try:
            os.remove(meta.path)
        except OSError:
            pass
        M.RESULT_SEGMENTS_RECLAIMED.inc(1, reason)
        M.RESULT_SEGMENT_RECLAIMED_BYTES.inc(meta.bytes, reason)
        return meta.bytes

    # clock-skew slack for cross-server expiry comparisons in a shared
    # spool directory
    ORPHAN_GRACE_S = 60.0

    def _sweep_orphans(self) -> int:
        """Server-start sweep of segment files left behind by dead
        processes (the exchange ``_cleanup_spool`` contract, applied at
        boot). A segment file's mtime is its EXPIRY (stamped at write),
        so only files whose own TTL has passed are touched — a shared
        spool directory's LIVE segments, owned by other running servers,
        are never reclaimed out from under them, whatever per-query TTL
        they carry."""
        reclaimed = 0
        try:
            names = os.listdir(self.base_dir)
        except OSError:
            return 0
        cutoff = time.time() - self.ORPHAN_GRACE_S
        for name in names:
            if not name.endswith(_SEGMENT_SUFFIX):
                continue
            path = os.path.join(self.base_dir, name)
            try:
                st = os.stat(path)
                if st.st_mtime > cutoff:
                    continue
                os.remove(path)
            except OSError:
                continue
            reclaimed += st.st_size
            M.RESULT_SEGMENTS_RECLAIMED.inc(1, "orphan")
            M.RESULT_SEGMENT_RECLAIMED_BYTES.inc(st.st_size, "orphan")
        return reclaimed

    def close(self) -> None:
        """Server stop: delete every segment this store still holds (a
        stopped server cannot serve them; shared spool dirs must not
        accumulate until someone else's orphan sweep)."""
        with self._lock:
            metas, self._segments = list(self._segments.values()), {}
        for m in metas:
            try:
                os.remove(m.path)
            except OSError:
                pass

    def __len__(self) -> int:
        with self._lock:
            return len(self._segments)

    def held_bytes(self) -> int:
        with self._lock:
            return sum(m.bytes for m in self._segments.values())


class SegmentWriter:
    """Accumulates serialized page frames for one query and rolls a new
    size-bounded segment whenever the target is reached — the producer
    half of the spooled protocol (size-bounded segments are what make
    client-side PARALLEL fetch worth anything)."""

    def __init__(self, store: SegmentStore, query_id: str,
                 target_bytes: int, ttl_s: float):
        self._store = store
        self._query_id = query_id
        self._target = max(1, int(target_bytes))
        self._ttl_s = float(ttl_s)
        self._frames: List[bytes] = []
        self._frame_rows = 0
        self._bytes = 0
        self._seq = 0
        self._metas: List[SegmentMeta] = []

    def add(self, frame: bytes, rows: int) -> None:
        self._frames.append(frame)
        self._frame_rows += int(rows)
        self._bytes += len(frame)
        if self._bytes >= self._target:
            self._roll()

    def _roll(self) -> None:
        if not self._frames:
            return
        self._metas.append(self._store._register(
            self._query_id, self._seq, self._frames, self._frame_rows,
            self._ttl_s))
        self._seq += 1
        self._frames, self._frame_rows, self._bytes = [], 0, 0

    def finish(self) -> List[SegmentMeta]:
        self._roll()
        return list(self._metas)

    @property
    def segment_count(self) -> int:
        return self._seq

    def abandon(self) -> None:
        """Producer failed: drop everything already rolled (nobody will
        ever receive a manifest pointing at these)."""
        self._frames, self._frame_rows, self._bytes = [], 0, 0
        for m in self._metas:
            self._store.discard(m.segment_id)
        self._metas = []


# --------------------------------------------------------- HTTP plumbing
_RANGE_ERR = (416, b'{"error": "unsatisfiable range"}',
              {}, "application/json")


def parse_range(header: Optional[str], total: int
                ) -> Optional[Tuple[int, int]]:
    """``Range: bytes=a-b`` -> (start, length), or None for a full read.
    Raises ValueError on a malformed/unsatisfiable range."""
    if not header:
        return None
    h = header.strip().lower()
    if not h.startswith("bytes="):
        raise ValueError(f"unsupported range unit: {header}")
    spec = h[len("bytes="):]
    start_s, _, end_s = spec.partition("-")
    if start_s == "":  # suffix form: bytes=-N (last N bytes)
        n = int(end_s)
        if n <= 0:
            raise ValueError("empty suffix range")
        start = max(0, total - n)
        return start, total - start
    start = int(start_s)
    end = int(end_s) if end_s else total - 1
    if start >= total or end < start:
        raise ValueError(f"range {header} outside 0..{total - 1}")
    return start, min(end, total - 1) - start + 1


def segment_response(store: SegmentStore, segment_id: str,
                     range_header: Optional[str] = None):
    """Shared GET handler body for the coordinator and worker
    ``/v1/segment/{id}`` routes: returns ``(status, body, headers,
    content_type)``. Range semantics: a ``Range: bytes=a-b`` header gets
    a 206 slice + ``Content-Range`` (clients resume a cut-off fetch
    without re-pulling the prefix)."""
    from trino_tpu.server import wire

    meta = store.get(segment_id)
    if meta is None:
        return 404, b'{"error": "no such segment"}', {}, "application/json"
    try:
        rng = parse_range(range_header, meta.bytes)
    except ValueError:
        return _RANGE_ERR
    if rng is None:
        data = store.read(segment_id)
        if data is None:
            return (404, b'{"error": "segment file gone"}', {},
                    "application/json")
        headers = {"X-Segment-Rows": str(meta.rows),
                   "X-Segment-Bytes": str(meta.bytes)}
        return 200, data, headers, wire.MEDIA_PAGES
    start, length = rng
    data = store.read(segment_id, start, length)
    if data is None:
        return 404, b'{"error": "segment file gone"}', {}, "application/json"
    headers = {
        "X-Segment-Rows": str(meta.rows),
        "X-Segment-Bytes": str(meta.bytes),
        "Content-Range":
            f"bytes {start}-{start + len(data) - 1}/{meta.bytes}",
    }
    return 206, data, headers, wire.MEDIA_PAGES
