"""Access control: the authorization seam of the engine.

Reference: ``security/AccessControlManager`` + SPI ``SystemAccessControl``
(~50 files of authenticators/authorizers). The engine-facing surface here
is the two checks every query path needs — can this identity run queries,
and can it read this table — with an allow-all default and a rule-based
implementation (the file-based access control plugin's role).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


class AccessDeniedError(PermissionError):
    pass


@dataclasses.dataclass(frozen=True)
class Identity:
    """Who is running the query (reference: spi/security/Identity)."""

    user: str = "anonymous"


class AccessControl:
    """Allow-all default (reference: AllowAllSystemAccessControl)."""

    def check_can_execute_query(self, identity: Identity) -> None:
        pass

    def check_can_select(self, identity: Identity, catalog: str,
                         schema: str, table: str) -> None:
        pass

    def check_can_write(self, identity: Identity, catalog: str,
                        schema: str, table: str) -> None:
        pass


@dataclasses.dataclass(frozen=True)
class TableRule:
    """One rule of the file-based access control format: user pattern +
    catalog/schema/table patterns + allowed privileges."""

    users: Sequence[str]  # exact user names, or "*"
    catalog: str = "*"
    schema: str = "*"
    table: str = "*"
    privileges: Sequence[str] = ("SELECT", "INSERT")

    def matches(self, identity: Identity, catalog: str, schema: str, table: str) -> bool:
        def m(pat: str, v: str) -> bool:
            return pat == "*" or pat == v

        user_ok = "*" in self.users or identity.user in self.users
        return user_ok and m(self.catalog, catalog) and m(self.schema, schema) and m(self.table, table)


class RuleBasedAccessControl(AccessControl):
    """First-matching-rule wins; no match = denied (reference:
    plugin file-based FileBasedSystemAccessControl semantics)."""

    def __init__(self, rules: List[TableRule]):
        self.rules = list(rules)

    def check_can_select(self, identity, catalog, schema, table) -> None:
        for r in self.rules:
            if r.matches(identity, catalog, schema, table):
                if "SELECT" in r.privileges:
                    return
                break
        raise AccessDeniedError(
            f"Access Denied: user {identity.user} cannot select from "
            f"{catalog}.{schema}.{table}")

    def check_can_write(self, identity, catalog, schema, table) -> None:
        for r in self.rules:
            if r.matches(identity, catalog, schema, table):
                if "INSERT" in r.privileges:
                    return
                break
        raise AccessDeniedError(
            f"Access Denied: user {identity.user} cannot write to "
            f"{catalog}.{schema}.{table}")
