"""Cluster memory management: pool aggregation + the low-memory killer.

Reference: ``memory/ClusterMemoryManager.java:89`` (aggregates every node's
pool usage from node status, enforces query.max-memory cluster-wide, and
invokes a pluggable LowMemoryKiller when nodes run out) with
``TotalReservationOnBlockedNodesQueryLowMemoryKiller`` as the default
policy. Here the node status ride-along is the worker announce payload
(queryMemory / memoryBytes / memoryLimit, server/worker.py), and the killer
fires when any worker reports usage over its declared pool.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

# policy: {query_id: total_reserved_bytes_across_cluster} -> victim query id
KillerPolicy = Callable[[Dict[str, int]], Optional[str]]


def total_reservation_killer(query_mem: Dict[str, int]) -> Optional[str]:
    """Default policy: kill the query holding the most cluster memory
    (reference: TotalReservationLowMemoryKiller)."""
    if not query_mem:
        return None
    return max(query_mem.items(), key=lambda kv: kv[1])[0]


class ClusterMemoryManager:
    """Aggregates per-worker announce payloads; blocks dispatch over the
    cluster limit; kills the policy's victim when a worker is over its
    pool."""

    def __init__(self, kill, cluster_limit_bytes: Optional[int] = None,
                 policy: KillerPolicy = total_reservation_killer):
        # kill(query_id, reason) — provided by the coordinator
        self._kill = kill
        self.cluster_limit_bytes = cluster_limit_bytes
        self.policy = policy
        self._lock = threading.Lock()
        # node_id -> {"queryMemory": {...}, "memoryBytes": n, "memoryLimit": n|None}
        self._nodes: Dict[str, dict] = {}
        self.kills: list = []  # (query_id, reason) history for tests/UI

    # ------------------------------------------------------------- ingest
    def update(self, node_id: str, payload: dict) -> None:
        with self._lock:
            self._nodes[node_id] = {
                "queryMemory": dict(payload.get("queryMemory") or {}),
                "memoryBytes": int(payload.get("memoryBytes") or 0),
                "memoryLimit": payload.get("memoryLimit"),
                "at": time.monotonic(),
            }
        self._maybe_kill()

    # ----------------------------------------------------------- accessors
    def query_reservations(self) -> Dict[str, int]:
        """Cluster-wide reserved bytes per query."""
        with self._lock:
            out: Dict[str, int] = {}
            for info in self._nodes.values():
                for qid, b in info["queryMemory"].items():
                    out[qid] = out.get(qid, 0) + int(b)
            return out

    def cluster_reserved(self) -> int:
        with self._lock:
            return sum(i["memoryBytes"] for i in self._nodes.values())

    def has_headroom(self) -> bool:
        """Dispatch gate: admit new work only under the cluster limit
        (reference: ClusterMemoryManager's query.max-memory admission)."""
        if self.cluster_limit_bytes is None:
            return True
        return self.cluster_reserved() < self.cluster_limit_bytes

    # -------------------------------------------------------------- killer
    def _maybe_kill(self) -> None:
        over = []
        with self._lock:
            for nid, info in self._nodes.items():
                limit = info["memoryLimit"]
                if limit is not None and info["memoryBytes"] > int(limit):
                    over.append(nid)
        if not over:
            return
        # candidates = queries actually HOLDING memory on an over-limit
        # node (killing anything else frees nothing there — the
        # "OnBlockedNodes" half of the reference policy's name); the
        # policy then ranks candidates by their CLUSTER-wide reservation
        with self._lock:
            blocked = set()
            for nid in over:
                blocked.update(
                    q for q, b in self._nodes[nid]["queryMemory"].items()
                    if int(b) > 0)
        candidates = {
            q: b for q, b in self.query_reservations().items() if q in blocked
        }
        victim = self.policy(candidates)
        if victim is None:
            return
        reason = (
            f"Query exceeded distributed memory limit: worker(s) "
            f"{','.join(sorted(over))} over their memory pool; killed as the "
            f"largest reservation (EXCEEDED_CLUSTER_MEMORY)")
        self.kills.append((victim, reason))
        # forget the victim's reservations immediately so one announce
        # cannot kill two queries for the same pressure window
        with self._lock:
            for info in self._nodes.values():
                info["queryMemory"].pop(victim, None)
                info["memoryBytes"] = sum(info["queryMemory"].values())
        self._kill(victim, reason)
