"""Cluster memory management: pool aggregation + the low-memory killer.

Reference: ``memory/ClusterMemoryManager.java:89`` (aggregates every node's
pool usage from node status, enforces query.max-memory cluster-wide, and
invokes a pluggable LowMemoryKiller when nodes run out) with
``TotalReservationOnBlockedNodesQueryLowMemoryKiller`` as the default
policy. Here the node status ride-along is the worker announce payload
(queryMemory / memoryBytes / memoryLimit, server/worker.py), and the killer
fires when any worker reports usage over its declared pool.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

# policy: {query_id: total_reserved_bytes_across_cluster} -> victim query id
KillerPolicy = Callable[[Dict[str, int]], Optional[str]]


def total_reservation_killer(query_mem: Dict[str, int]) -> Optional[str]:
    """Default policy: kill the query holding the most cluster memory
    (reference: TotalReservationLowMemoryKiller)."""
    if not query_mem:
        return None
    return max(query_mem.items(), key=lambda kv: kv[1])[0]


class ClusterMemoryManager:
    """Aggregates per-worker announce payloads; blocks dispatch over the
    cluster limit; kills the policy's victim when a worker is over its
    pool."""

    # worker announce cadence (server/worker.py announce loop)
    HEARTBEAT_INTERVAL_S = 0.5
    # announces older than this many missed heartbeats are STALE: a dead
    # worker's cache bytes must not keep counting as reclaimable headroom
    STALE_HEARTBEATS = 3

    def __init__(self, kill, cluster_limit_bytes: Optional[int] = None,
                 policy: KillerPolicy = total_reservation_killer,
                 heartbeat_interval_s: float = HEARTBEAT_INTERVAL_S):
        # kill(query_id, reason) — provided by the coordinator
        self._kill = kill
        self.cluster_limit_bytes = cluster_limit_bytes
        self.policy = policy
        self.heartbeat_interval_s = heartbeat_interval_s
        self._lock = threading.Lock()
        # node_id -> {"queryMemory": {...}, "memoryBytes": n, "memoryLimit": n|None}
        self._nodes: Dict[str, dict] = {}
        self.kills: list = []  # (query_id, reason) history for tests/UI

    # ------------------------------------------------------------- ingest
    def update(self, node_id: str, payload: dict) -> None:
        with self._lock:
            self._nodes[node_id] = {
                "queryMemory": dict(payload.get("queryMemory") or {}),
                "memoryBytes": int(payload.get("memoryBytes") or 0),
                "memoryLimit": payload.get("memoryLimit"),
                # real accelerator capacity (HBM bytes) when the worker
                # could discover it — sizes admission from hardware
                # instead of a flat default (trino_tpu/devcache/)
                "deviceMemoryBytes": payload.get("deviceMemoryBytes"),
                # warm-table bytes the worker holds in its device cache:
                # REVOCABLE (the worker sheds them under pressure), so
                # admission never counts them against headroom
                "deviceCacheBytes": int(payload.get("deviceCacheBytes") or 0),
                # host-RAM columnar tier (devcache/hostcache.py): the
                # SECOND revocable tier — the worker sheds it before the
                # HBM tier (devcache.shed_revocable), and admission
                # ignores it for the same reason
                "hostCacheBytes": int(payload.get("hostCacheBytes") or 0),
                # per-pool, per-owner memory-ledger rows + process RSS
                # (the system.runtime.memory per-node source)
                "memoryOwners": list(payload.get("memoryOwners") or ()),
                "rssBytes": payload.get("rssBytes"),
                "at": time.monotonic(),
            }
        self._maybe_kill()

    # ----------------------------------------------------------- accessors
    def query_reservations(self) -> Dict[str, int]:
        """Cluster-wide reserved bytes per query."""
        with self._lock:
            out: Dict[str, int] = {}
            for info in self._nodes.values():
                for qid, b in info["queryMemory"].items():
                    out[qid] = out.get(qid, 0) + int(b)
            return out

    def cluster_reserved(self) -> int:
        with self._lock:
            return sum(i["memoryBytes"] for i in self._nodes.values())

    def device_capacity_total(self) -> Optional[int]:
        """Sum of worker-announced accelerator capacities (HBM bytes), or
        None unless EVERY tracked worker announced one — a partial sum
        would understate the cluster and spuriously refuse admission on
        mixed fleets (some workers cannot discover their capacity)."""
        with self._lock:
            caps = [i.get("deviceMemoryBytes") for i in self._nodes.values()]
        if not caps or any(not c for c in caps):
            return None
        return sum(int(c) for c in caps)

    def revocable_bytes(self) -> int:
        """Cluster-wide revocable bytes across BOTH cache tiers —
        reclaimable on demand (workers shed host-RAM pages first, then
        warm-HBM tables, for running queries' benefit). STALE announces
        (older than STALE_HEARTBEATS missed heartbeats) are skipped: a
        dead worker's cache cannot actually be reclaimed, so its bytes
        must not be promised as headroom."""
        horizon = self.STALE_HEARTBEATS * self.heartbeat_interval_s
        now = time.monotonic()
        with self._lock:
            return sum(int(i.get("deviceCacheBytes") or 0)
                       + int(i.get("hostCacheBytes") or 0)
                       for i in self._nodes.values()
                       if now - i["at"] <= horizon)

    def memory_rows(self) -> list:
        """(node_id, owner-row) pairs from the newest announce of every
        tracked node — the coordinator's system.runtime.memory feed (its
        own process ledger supplies the coordinator rows)."""
        with self._lock:
            return [(nid, dict(row))
                    for nid, info in sorted(self._nodes.items())
                    for row in info.get("memoryOwners") or ()]

    def effective_limit(self) -> Optional[int]:
        """The admission ceiling: the configured cluster limit when set,
        else the REAL announced hardware capacity (reference role:
        query.max-memory sized by ops guesswork, replaced by the workers'
        own HBM reports); None = unlimited (nothing known)."""
        if self.cluster_limit_bytes is not None:
            return self.cluster_limit_bytes
        return self.device_capacity_total()

    def has_headroom(self) -> bool:
        """Dispatch gate: admit new work only under the effective limit
        (reference: ClusterMemoryManager's query.max-memory admission).
        Device-cache bytes never count against headroom — they are the
        revocable tier and yield before a query would be refused. When the
        limit is hardware-derived, each node's counted reservation is
        CLAMPED at that node's announced capacity: reservations are
        projected peaks (a spilling join reports its pre-partition
        projection, exec/memory.py), and a single projection beyond one
        node's HBM must not consume the whole cluster's headroom. Blocked
        dispatch queues (coordinator waits for headroom); reservations
        decay when task bodies finish."""
        limit = self.effective_limit()
        if limit is None:
            return True
        if self.cluster_limit_bytes is not None:
            # the operator chose this ceiling deliberately: gate on raw
            # reservations exactly as configured
            return self.cluster_reserved() < limit
        with self._lock:
            reserved = sum(
                min(int(i["memoryBytes"]),
                    int(i.get("deviceMemoryBytes") or 0) or i["memoryBytes"])
                for i in self._nodes.values())
        return reserved < limit

    # -------------------------------------------------------------- killer
    def _maybe_kill(self) -> None:
        over = []
        with self._lock:
            for nid, info in self._nodes.items():
                limit = info["memoryLimit"]
                if limit is not None and info["memoryBytes"] > int(limit):
                    over.append(nid)
        if not over:
            return
        # candidates = queries actually HOLDING memory on an over-limit
        # node (killing anything else frees nothing there — the
        # "OnBlockedNodes" half of the reference policy's name); the
        # policy then ranks candidates by their CLUSTER-wide reservation
        with self._lock:
            blocked = set()
            for nid in over:
                blocked.update(
                    q for q, b in self._nodes[nid]["queryMemory"].items()
                    if int(b) > 0)
        candidates = {
            q: b for q, b in self.query_reservations().items() if q in blocked
        }
        victim = self.policy(candidates)
        if victim is None:
            return
        reason = (
            f"Query exceeded distributed memory limit: worker(s) "
            f"{','.join(sorted(over))} over their memory pool; killed as the "
            f"largest reservation (EXCEEDED_CLUSTER_MEMORY)")
        self.kills.append((victim, reason))
        # forget the victim's reservations immediately so one announce
        # cannot kill two queries for the same pressure window
        with self._lock:
            for info in self._nodes.values():
                info["queryMemory"].pop(victim, None)
                info["memoryBytes"] = sum(info["queryMemory"].values())
        self._kill(victim, reason)
