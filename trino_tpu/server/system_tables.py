"""Coordinator feeds for the ``system`` catalog + the query-history ring.

Reference: ``core/trino-main/.../connector/system/`` — the coordinator-
state providers behind ``system.runtime.queries`` (``QuerySystemTable``
reading the DispatchManager/QueryTracker), ``system.runtime.tasks``
(``TaskSystemTable``), ``system.runtime.nodes`` (``NodeSystemTable``
reading the discovery registry) and the ``kill_query`` procedure
(``KillQueryProcedure``) — plus the bounded completed-query history of
``execution/QueryTracker`` (``query.max-history`` /
``query.min-expire-age``), which is what lets ``system.runtime.queries``
cover FINISHED/FAILED queries after their executions are pruned.

Locking contract (the tentpole's deadlock clause): every snapshot takes
the query-registry lock only to COPY the execution list, then builds rows
outside it — so ``SELECT * FROM system.runtime.queries`` issued while
that very query runs scans a consistent snapshot of itself without ever
nesting the registry lock under a per-query lock.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from trino_tpu.connector import spi

# retention defaults (the query_max_history / query_min_expire_age_ms
# session properties override per recording query)
DEFAULT_MAX_HISTORY = 100
DEFAULT_MIN_EXPIRE_AGE_MS = 15_000


def query_record(execution, state: Optional[str] = None,
                 ended_at: Optional[float] = None) -> dict:
    """One query's row-shaped record (live executions and history entries
    share this shape, so ``system.runtime.queries`` unions them
    uniformly). Reads only per-query state — never the registry lock."""
    stages = execution.stage_stats(include_operators=False)
    qs = execution.query_stats(stages)
    failure = (execution.failure or "").split("\n")[0] or None
    adaptations = len(execution.plan_versions)
    # phase-ledger rollups (obs/timeline.py): the three coarse buckets
    # plus the residual, NULL until the ledger exists (query terminal)
    tl = qs.get("timeline")
    queued_ms = planning_ms = execution_ms = unattributed_ms = None
    if tl is not None:
        ph = tl["phases"]
        # the dispatch-queue residency is queue time too (the bounded
        # queue of the dispatcher/executor split sits inside admission)
        queued_ms = (ph.get("queued", 0.0)
                     + ph.get("dispatch-queue", 0.0)) * 1000.0
        planning_ms = sum(ph.get(p, 0.0) for p in (
            "dispatch", "parse-analyze", "plan-optimize",
            "prepare-bind")) * 1000.0
        execution_ms = sum(ph.get(p, 0.0) for p in (
            "schedule", "device-staging", "device-execute",
            "exchange-wait", "result-serialization")) * 1000.0
        unattributed_ms = ph.get("unattributed", 0.0) * 1000.0
    return {
        "queryId": execution.query_id,
        "state": state or execution.state.get(),
        "user": execution.user,
        "query": execution.sql,
        "createdAt": float(execution.created_at),
        "endedAt": (float(ended_at) if ended_at is not None
                    else execution.ended_at),
        "elapsedMs": int(qs.get("elapsedMs", 0)),
        "deviceS": float(qs.get("deviceS", 0.0)),
        "totalSplits": int(qs.get("totalSplits", 0)),
        "completedSplits": int(qs.get("completedSplits", 0)),
        "inputRows": int(qs.get("totalRows", 0)),
        "outputBytes": int(qs.get("totalBytes", 0)),
        "peakBytes": int(qs.get("peakBytes", 0)),
        "shedBytes": int(qs.get("shedBytes", 0)),
        "yieldEvents": int(qs.get("yieldEvents", 0)),
        "resultRows": len(execution.rows),
        "cacheStatus": execution.cache_status,
        "adaptations": adaptations,
        # the initial plan is version 1; every adaptive change adds one
        "planVersions": adaptations + 1,
        "failure": failure,
        # control-plane path of the SELECT (server/fastpath.py):
        # fast-path | distributed | local-catalog; None otherwise
        "fastPath": execution.fast_path,
        "queuedMs": queued_ms,
        "planningMs": planning_ms,
        "executionMs": execution_ms,
        "unattributedMs": unattributed_ms,
        # the resource group that admitted the query (None under a
        # legacy injected gate) — history keeps the attribution after
        # the execution is pruned
        "resourceGroup": execution.resource_group,
    }


def _query_row(rec: dict) -> tuple:
    """Record dict -> system.runtime.queries row (column order must match
    connector/system/schemas.py)."""
    return (
        rec["queryId"], rec["state"], rec["user"], rec["query"],
        rec["createdAt"], rec["endedAt"], rec["elapsedMs"], rec["deviceS"],
        rec["totalSplits"], rec["completedSplits"], rec["inputRows"],
        rec["outputBytes"], rec["peakBytes"], rec.get("shedBytes", 0),
        rec.get("yieldEvents", 0), rec["resultRows"],
        rec["cacheStatus"], rec["adaptations"], rec["planVersions"],
        rec["failure"], rec.get("fastPath"),
        rec.get("queuedMs"), rec.get("planningMs"),
        rec.get("executionMs"), rec.get("unattributedMs"),
        rec.get("resourceGroup"),
    )


class QueryHistory:
    """Bounded ring of completed-query records (QueryTracker's
    ``expireQueries`` analog). Eviction honors BOTH retention knobs: the
    ring prunes to ``max_history`` but never evicts a record younger than
    ``min_expire_age_ms`` — a burst of short queries stays inspectable for
    at least that long; ``HARD_CAP`` bounds memory regardless."""

    HARD_CAP = 1000

    def __init__(self):
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def record(self, entry: dict,
               max_history: int = DEFAULT_MAX_HISTORY,
               min_expire_age_ms: int = DEFAULT_MIN_EXPIRE_AGE_MS) -> None:
        from trino_tpu.obs import metrics as M

        now = time.time()
        evicted = 0
        with self._lock:
            self._entries[entry["queryId"]] = entry
            self._entries.move_to_end(entry["queryId"])
            while len(self._entries) > self.HARD_CAP:
                self._entries.popitem(last=False)
                evicted += 1
            while len(self._entries) > max(0, int(max_history)):
                _qid, oldest = next(iter(self._entries.items()))
                age_ms = (now - (oldest.get("endedAt") or now)) * 1000.0
                if age_ms < min_expire_age_ms:
                    break  # too young to expire; retry on a later record
                self._entries.popitem(last=False)
                evicted += 1
        if evicted:
            M.QUERY_HISTORY_EVICTIONS.inc(evicted)

    def snapshot(self) -> List[dict]:
        """Newest-first record list."""
        with self._lock:
            return list(reversed(self._entries.values()))


class CoordinatorSystemTables(spi.LiveTableProvider):
    """The coordinator's LiveTableProvider: materializes system-table rows
    from live server state at scan time and serves the ``kill_query``
    procedure through the existing administrative kill path."""

    def __init__(self, server):
        self._server = server

    # ------------------------------------------------------------- tables
    def snapshot_rows(self, schema: str, table: str) -> List[tuple]:
        if (schema, table) == ("runtime", "queries"):
            return self._queries_rows()
        if (schema, table) == ("runtime", "tasks"):
            return self._tasks_rows()
        if (schema, table) == ("runtime", "nodes"):
            return self._nodes_rows()
        if (schema, table) == ("runtime", "prepared_statements"):
            return self._prepared_rows()
        if (schema, table) == ("runtime", "serving"):
            return self._server.dispatcher.serving_rows()
        if (schema, table) == ("runtime", "resource_groups"):
            return self._resource_group_rows()
        if (schema, table) == ("runtime", "device_cache"):
            from trino_tpu.connector.system.connector import device_cache_rows

            return device_cache_rows()
        if (schema, table) == ("runtime", "memory"):
            return self._memory_rows()
        if (schema, table) == ("runtime", "kernels"):
            return self._kernels_rows()
        if (schema, table) == ("runtime", "compiles"):
            return self._compiles_rows()
        if (schema, table) == ("runtime", "transfers"):
            return self._transfers_rows()
        if (schema, table) == ("runtime", "stragglers"):
            return self._stragglers_rows()
        if (schema, table) == ("metadata", "materialized_views"):
            return self._matview_rows()
        if (schema, table) == ("metrics", "metrics"):
            return self._metrics_rows()
        raise KeyError(f"system.{schema}.{table} does not exist")

    def _live_executions(self) -> List:
        # COPY under the registry lock, compute outside it (the deadlock /
        # torn-state contract in the module docstring)
        with self._server._qlock:
            return list(self._server.queries.values())

    def _queries_rows(self) -> List[tuple]:
        live = self._live_executions()
        rows = [_query_row(query_record(q)) for q in live]
        seen = {q.query_id for q in live}
        # completed queries whose executions were pruned from the registry
        # survive in the history ring (live records win: fresher stats)
        rows.extend(_query_row(rec) for rec in self._server.history.snapshot()
                    if rec["queryId"] not in seen)
        return rows

    def _tasks_rows(self) -> List[tuple]:
        rows = []
        for q in self._live_executions():
            for rec in q.task_records():
                s = rec.get("stats") or {}
                ops = s.get("operatorStats") or ()
                rows.append((
                    q.query_id, rec["taskId"], int(rec["fragment"]),
                    rec["state"], rec.get("workerUri"),
                    int(s.get("totalSplits", 0)),
                    int(s.get("completedSplits", 0)),
                    int(s.get("inputRows", 0)), int(s.get("outputRows", 0)),
                    int(s.get("outputBytes", 0)), int(s.get("peakBytes", 0)),
                    float(s.get("elapsedS", 0.0)),
                    float(s.get("deviceS", 0.0)), len(ops),
                ))
        return rows

    def _nodes_rows(self) -> List[tuple]:
        rows = []
        for n in self._server.registry.snapshot():
            info = n.get("info") or {}
            mem_limit = info.get("memoryLimit")
            dev_mem = info.get("deviceMemoryBytes")
            rows.append((
                n["nodeId"], n["url"], "active" if n["alive"] else "dead",
                info.get("version"), int(info.get("tasks", 0)),
                int(info.get("memoryBytes", 0)),
                int(mem_limit) if mem_limit is not None else None,
                int(dev_mem) if dev_mem is not None else None,
                int(info.get("deviceCacheBytes") or 0),
                int(n["ageS"] * 1000.0),
                int(info.get("hostCacheBytes") or 0),
                int(info.get("hostCacheHits") or 0),
                int(info.get("netBytesSent") or 0),
                int(info.get("netBytesReceived") or 0),
            ))
        return rows

    def _memory_rows(self) -> List[tuple]:
        """``system.runtime.memory``: the cluster memory ledger — one row
        per (node, pool, owner). Worker rows come from each node's newest
        announce payload (cluster_memory.memory_rows); the coordinator
        contributes its own process ledger directly (it never announces
        to itself). A worker ledger sharing this process (in-process test
        clusters stamp the global ledger with the worker's node id) is
        NOT double-reported: announce rows win for that node id."""
        from trino_tpu.obs.memledger import MEMORY_LEDGER

        rows = []
        announced = set()
        for nid, row in self._server.cluster_memory.memory_rows():
            announced.add(nid)
            rows.append((
                nid, str(row.get("pool", "")), str(row.get("owner", "")),
                int(row.get("bytes", 0)), int(row.get("peakBytes", 0)),
                int(row.get("events", 0)),
            ))
        nid = MEMORY_LEDGER.node_id or "coordinator"
        if nid not in announced:
            rows.extend(
                (nid, r["pool"], r["owner"], int(r["bytes"]),
                 int(r["peakBytes"]), int(r["events"]))
                for r in MEMORY_LEDGER.owner_rows())
        return rows

    def _kernels_rows(self) -> List[tuple]:
        """``system.runtime.kernels``: the kernel ledger — one row per
        (query, plan node, operator, tier, node). Terminal queries read
        from the folded device-profiler store; RUNNING queries merge
        their live task rollups so the table never lags the engine."""
        from trino_tpu.obs.devprofiler import DEVICE_PROFILER

        rows = []
        seen = set()
        for q in self._live_executions():
            if getattr(q, "_kernels_folded", False):
                continue  # folded rows below are fresher-complete
            for r in q.kernel_rows_live():
                seen.add(q.query_id)
                rows.append(self._kernel_row(r))
        rows.extend(self._kernel_row(r) for r in DEVICE_PROFILER.kernel_rows()
                    if r["queryId"] not in seen)
        return rows

    @staticmethod
    def _kernel_row(r: dict) -> tuple:
        return (
            str(r.get("queryId", "")), str(r.get("nodeId", "")),
            str(r.get("planNodeId", "")), str(r.get("operator", "")),
            str(r.get("tier", "")), int(r.get("launches", 0)),
            float(r.get("wallS", 0.0)), float(r.get("deviceS", 0.0)),
            float(r.get("dispatchOverheadS",
                        max(0.0, float(r.get("wallS", 0.0))
                            - float(r.get("deviceS", 0.0))))),
            int(r.get("inputBytes", 0)), int(r.get("outputBytes", 0)),
            bool(r.get("estimated", False)),
        )

    def _compiles_rows(self) -> List[tuple]:
        """``system.runtime.compiles``: the compile ledger — one row per
        jit/Pallas compile event, cluster-wide. Worker rows ride the
        announce payload (``compileEvents``); the coordinator
        contributes its own process ring directly. A worker profiler
        sharing this process (in-process test clusters) is NOT
        double-reported: announce rows win for that node id."""
        from trino_tpu.obs.devprofiler import DEVICE_PROFILER

        rows = []
        announced = set()
        for n in self._server.registry.snapshot():
            info = n.get("info") or {}
            events = info.get("compileEvents")
            if events is None:
                continue
            announced.add(n["nodeId"])
            rows.extend(self._compile_row(n["nodeId"], e) for e in events)
        nid = DEVICE_PROFILER.node_id or "coordinator"
        if nid not in announced:
            rows.extend(self._compile_row(nid, e)
                        for e in DEVICE_PROFILER.compile_rows())
        return rows

    @staticmethod
    def _compile_row(nid: str, e: dict) -> tuple:
        return (
            str(e.get("nodeId") or nid), str(e.get("queryId", "")),
            str(e.get("tier", "")), str(e.get("fingerprint", "")),
            str(e.get("shapeSig", "")), float(e.get("compileS", 0.0)),
            str(e.get("cache", "")), float(e.get("ts", 0.0)),
        )

    def _transfers_rows(self) -> List[tuple]:
        """``system.runtime.transfers``: the flow ledger — one row per
        (node, link, owner) transfer rollup, cluster-wide. Worker rows
        ride the announce payload (``flows``); the coordinator
        contributes its own process ledger directly. A worker ledger
        sharing this process (in-process test clusters) is NOT
        double-reported: announce rows win for that node id."""
        from trino_tpu.obs.flowledger import FLOW_LEDGER

        rows = []
        announced = set()
        for n in self._server.registry.snapshot():
            flows = (n.get("info") or {}).get("flows")
            if flows is None:
                continue
            announced.add(n["nodeId"])
            rows.extend(self._transfer_row(n["nodeId"], r) for r in flows)
        nid = FLOW_LEDGER.node_id or "coordinator"
        if nid not in announced:
            rows.extend(self._transfer_row(nid, r)
                        for r in FLOW_LEDGER.transfer_rows())
        return rows

    @staticmethod
    def _transfer_row(nid: str, r: dict) -> tuple:
        return (
            nid, str(r.get("link", "")), str(r.get("owner", "")),
            int(r.get("bytes", 0)), int(r.get("pages", 0)),
            int(r.get("transfers", 0)), float(r.get("seconds", 0.0)),
            (float(r["mbPerS"]) if r.get("mbPerS") is not None else None),
            int(r.get("retries", 0)),
            (str(r["lastStatus"]) if r.get("lastStatus") is not None
             else None),
        )

    def _stragglers_rows(self) -> List[tuple]:
        """``system.runtime.stragglers``: one row per flagged task across
        the live query registry — frozen verdicts for terminal queries,
        live detection for RUNNING ones (QueryExecution.straggler_rows
        makes that split)."""
        rows = []
        for q in self._live_executions():
            for f in q.straggler_rows():
                stage = f.get("stageId")
                rows.append((
                    q.query_id,
                    int(stage) if stage is not None else None,
                    f.get("taskId"), f.get("workerUri"),
                    float(f.get("elapsedS", 0.0)),
                    float(f.get("stageMedianS", 0.0)),
                    float(f.get("ratio", 0.0)),
                    float(f.get("multiple", 0.0)),
                    str(f.get("cause", "")),
                    int(f.get("completedSplits", 0)),
                ))
        return rows

    def _resource_group_rows(self) -> List[tuple]:
        """``system.runtime.resource_groups``: one row per live group
        node of the admission tree (empty under a legacy injected flat
        gate — the table only describes group-aware admission)."""
        groups = getattr(self._server, "resource_groups", None)
        if groups is None:
            return []
        return groups.table_rows()

    def _prepared_rows(self) -> List[tuple]:
        return [
            (e.user, e.name, e.sql, int(e.param_count),
             float(e.created_at), int(e.executions),
             float(e.last_executed_at)
             if e.last_executed_at is not None else None)
            for e in self._server.prepared.snapshot()
        ]

    def _matview_rows(self) -> List[tuple]:
        """``system.metadata.materialized_views``: every registered view
        with its freshness recomputed against the connectors' CURRENT
        data versions at scan time — the table never shows a cached
        verdict."""
        from trino_tpu.matview.substitute import staleness_reason

        rows = []
        for mv in self._server.matviews.snapshot():
            reason = staleness_reason(self._server.catalogs, mv)
            base = ", ".join(
                f"{c}.{s}.{t}@{v}" for (c, s, t), v in
                (mv.base_versions or ()))
            rows.append((
                mv.catalog, mv.schema, mv.name, mv.owner,
                mv.definition_sql, mv.storage_qualified,
                reason is None, reason,
                float(mv.last_refresh) if mv.last_refresh else None,
                base or None, int(mv.hits), int(mv.refreshes),
            ))
        return rows

    def _metrics_rows(self) -> List[tuple]:
        from trino_tpu.connector.system.connector import metric_sample_rows
        from trino_tpu.server.events import refreshed_server_gauges

        with refreshed_server_gauges(self._server):
            return metric_sample_rows()

    # --------------------------------------------------------- procedures
    def procedure(self, schema: str, name: str):
        if (schema, name) == ("runtime", "kill_query"):
            return self._kill_query
        if (schema, name) == ("runtime", "sync_materialized_view"):
            return self._sync_materialized_view
        return None

    def _sync_materialized_view(self, session, payload_b64,
                                signature=None) -> str:
        """CALL system.runtime.sync_materialized_view(b64_json, hmac):
        apply one materialized-view registry replication payload — how
        the dispatch process keeps executor-process replicas in step
        with its authoritative registry after CREATE/REFRESH/DROP (the
        prepared-statement broadcast analog, carried as data instead of
        replayed SQL so children never re-execute a refresh). The
        payload must be HMAC-signed with the cluster-internal secret
        (server/wire.py — the same trust root every internal endpoint
        verifies): an ordinary client cannot inject registry entries,
        which would otherwise launder access control through a forged
        storage-table pointer."""
        import base64
        import json

        from trino_tpu.matview.lifecycle import sync_from_payload
        from trino_tpu.server import wire

        blob = str(payload_b64)
        if not wire.verify(blob.encode(), str(signature)
                           if signature is not None else None):
            from trino_tpu.server.security import AccessDeniedError

            raise AccessDeniedError(
                "sync_materialized_view: bad internal signature — this "
                "procedure is the executor-plane replication channel, "
                "not a user surface")
        payload = json.loads(base64.b64decode(blob))
        return sync_from_payload(self._server.matviews, payload)

    def _kill_query(self, session, query_id, reason=None) -> str:
        """CALL system.runtime.kill_query(query_id, reason): FAIL the named
        query with the supplied reason through the administrative kill
        path (reference: KillQueryProcedure -> DispatchManager.failQuery).
        Refuses self-kill (the calling query's own id) and — when end-user
        authentication is enforced — killing another user's query."""
        query_id = str(query_id)
        if query_id == getattr(session, "query_id", None):
            raise ValueError(
                "kill_query cannot kill the query that invoked it")
        q = self._server.get_query(query_id)
        if q is None:
            raise ValueError(f"kill_query: query not found: {query_id}")
        auth = getattr(self._server, "authenticator", None)
        if auth is not None and auth.required:
            from trino_tpu.server.security import AccessDeniedError

            user = getattr(getattr(session, "identity", None), "user", None)
            if q.user != user:
                raise AccessDeniedError(
                    "Access Denied: query belongs to another user")
        if q.state.is_terminal():
            return f"query {query_id} is already {q.state.get()}"
        q.kill(str(reason) if reason is not None
               else "Killed via system.runtime.kill_query")
        return f"killed {query_id}"
