"""Public-API authentication: password file + JWT.

Reference: ``server/security/PasswordAuthenticatorManager`` + the
password-file plugin (``plugin/trino-password-authenticators``) and
``server/security/jwt/JwtAuthenticator`` — the coordinator's HTTP surface
authenticates end users (Basic or Bearer) BEFORE dispatch; the internal
control plane keeps its separate HMAC (server/wire.py). Stdlib-only
implementations: PBKDF2-SHA256 password hashes and HS256 JWTs.
"""
from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json
import time
from typing import Dict, Optional

from trino_tpu.server.security import Identity


class AuthenticationError(Exception):
    pass


# ------------------------------------------------------------ password file

PBKDF2_ITERATIONS = 100_000


def hash_password(password: str, salt: Optional[bytes] = None,
                  iterations: int = PBKDF2_ITERATIONS) -> str:
    """'pbkdf2_sha256$<iters>$<salt_hex>$<hash_hex>' — the storage format
    of the password file (role of the reference's bcrypt/PBKDF2 htpasswd
    entries)."""
    import os

    salt = salt if salt is not None else os.urandom(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, iterations)
    return f"pbkdf2_sha256${iterations}${salt.hex()}${dk.hex()}"


def verify_password(password: str, stored: str) -> bool:
    try:
        scheme, iters, salt_hex, hash_hex = stored.split("$")
        if scheme != "pbkdf2_sha256":
            return False
        dk = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), bytes.fromhex(salt_hex), int(iters))
        return hmac.compare_digest(dk.hex(), hash_hex)
    except (ValueError, binascii.Error):
        return False


class PasswordFileAuthenticator:
    """user:pbkdf2-hash lines (reference: file password authenticator)."""

    def __init__(self, entries: Dict[str, str]):
        self._entries = dict(entries)

    @classmethod
    def from_file(cls, path: str) -> "PasswordFileAuthenticator":
        entries: Dict[str, str] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                user, _, stored = line.partition(":")
                entries[user] = stored
        return cls(entries)

    def authenticate(self, user: str, password: str) -> Identity:
        stored = self._entries.get(user)
        if stored is None or not verify_password(password, stored):
            raise AuthenticationError("Invalid credentials")
        return Identity(user)


# --------------------------------------------------------------------- jwt


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def make_jwt(claims: dict, secret: bytes) -> str:
    """Mint an HS256 JWT (test/ops helper; real deployments bring their
    own issuer)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing = f"{header}.{payload}".encode()
    sig = _b64url(hmac.new(secret, signing, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


class JwtAuthenticator:
    """HS256 bearer-token validation: signature + exp + principal claim
    (reference: server/security/jwt — RS256/JWKS in the reference; the
    validation contract is the same)."""

    def __init__(self, secret: bytes, principal_claim: str = "sub"):
        self._secret = secret
        self._claim = principal_claim

    def authenticate(self, token: str) -> Identity:
        try:
            header_s, payload_s, sig_s = token.split(".")
            header = json.loads(_unb64url(header_s))
            if header.get("alg") != "HS256":
                raise AuthenticationError("unsupported JWT alg")
            signing = f"{header_s}.{payload_s}".encode()
            want = hmac.new(self._secret, signing, hashlib.sha256).digest()
            if not hmac.compare_digest(want, _unb64url(sig_s)):
                raise AuthenticationError("bad JWT signature")
            claims = json.loads(_unb64url(payload_s))
        except (ValueError, binascii.Error, json.JSONDecodeError) as e:
            raise AuthenticationError(f"malformed JWT: {e}") from e
        exp = claims.get("exp")
        if exp is not None and time.time() > float(exp):
            raise AuthenticationError("JWT expired")
        user = claims.get(self._claim)
        if not user:
            raise AuthenticationError(f"JWT missing {self._claim} claim")
        return Identity(str(user))


# ------------------------------------------------------------- http surface


class Authenticator:
    """The coordinator's request authenticator: Basic -> password file,
    Bearer -> JWT; absence of either configured scheme = open cluster
    (the reference's insecure-authentication default)."""

    def __init__(self, password: Optional[PasswordFileAuthenticator] = None,
                 jwt: Optional[JwtAuthenticator] = None):
        self.password = password
        self.jwt = jwt

    @property
    def required(self) -> bool:
        return self.password is not None or self.jwt is not None

    def authenticate_header(self, authorization: Optional[str]) -> Identity:
        """Authorization header -> Identity, or AuthenticationError."""
        if not self.required:
            raise AuthenticationError("no authenticator configured")
        if not authorization:
            raise AuthenticationError("Authorization header required")
        scheme, _, rest = authorization.partition(" ")
        scheme = scheme.lower()
        if scheme == "basic" and self.password is not None:
            try:
                user, _, pw = base64.b64decode(rest).decode().partition(":")
            except (ValueError, binascii.Error) as e:
                raise AuthenticationError("malformed Basic credentials") from e
            return self.password.authenticate(user, pw)
        if scheme == "bearer" and self.jwt is not None:
            return self.jwt.authenticate(rest.strip())
        raise AuthenticationError(f"unsupported authorization scheme {scheme}")
