"""Event listener SPI + metrics collection.

Reference: ``core/trino-spi/.../spi/eventlistener/`` — ``EventListener``
(queryCreated / queryCompleted), ``QueryCompletedEvent`` (metadata, stats,
failure info), registered via EventListenerFactory plugins and dispatched by
``eventlistener/EventListenerManager`` with per-listener exception isolation.
Here the same shape: listeners attach to a Session or a CoordinatorServer,
events are plain dataclasses, and a failing listener never fails the query.

The metrics side (``render_metrics``) exposes the coordinator's counters in
the Prometheus text format — the role of the reference's JMX-to-/metrics
bridge (``trino-jmx`` + airlift's MetricsResource). Since the observability
PR it is a thin bridge: server-derived gauges refresh from the server's
PUBLIC accessors into the typed registry (``trino_tpu/obs/metrics.py``)
and the registry renders the page — seed metric names unchanged, engine
counters and histograms ride along.
"""
from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import time
from typing import List, Mapping, Optional, Tuple

logger = logging.getLogger("trino_tpu.events")


@dataclasses.dataclass(frozen=True)
class QueryCreatedEvent:
    """Reference: spi/eventlistener/QueryCreatedEvent.java."""

    query_id: str
    user: str
    sql: str
    create_time: float  # epoch seconds


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    """Reference: spi/eventlistener/QueryCompletedEvent.java (metadata +
    statistics + failureInfo, flattened to the fields the engine tracks)."""

    query_id: str
    user: str
    sql: str
    state: str  # FINISHED | FAILED | CANCELED
    create_time: float
    end_time: float
    wall_seconds: float
    output_rows: int
    error: Optional[str] = None
    # the query's trace, exported span records (obs/trace.py Span.to_dict)
    # — the reference attaches QueryStats/operator summaries; here the span
    # tree carries the same where-did-time-go data (SlowQueryLogListener
    # is the first consumer)
    spans: Tuple[dict, ...] = ()
    # the session-property view the query ran with (reference:
    # QueryContext.sessionProperties on the completed event)
    session_properties: Mapping[str, object] = dataclasses.field(
        default_factory=dict)
    # completion-time phase ledger (obs/timeline.py QueryTimeline.to_dict)
    # — wall attribution per phase + unattributed residual; None when the
    # ledger could not be computed
    timeline: Optional[Mapping[str, object]] = None
    # flight-recorder postmortem (obs/flightrecorder.py): merged
    # coordinator + worker rings, captured for FAILED queries only
    postmortem: Optional[Mapping[str, object]] = None


class EventListener:
    """Subclass and override either hook (reference: EventListener's
    default methods are no-ops, so listeners implement only what they use)."""

    def query_created(self, event: QueryCreatedEvent) -> None:  # noqa: B027
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:  # noqa: B027
        pass


class EventListenerManager:
    """Dispatch with per-listener exception isolation (reference:
    eventlistener/EventListenerManager catches and logs per listener)."""

    def __init__(self):
        self._listeners: List[EventListener] = []
        self._lock = threading.Lock()

    def add(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def _snapshot(self) -> List[EventListener]:
        with self._lock:
            return list(self._listeners)

    def fire_created(self, event: QueryCreatedEvent) -> None:
        for lsn in self._snapshot():
            try:
                lsn.query_created(event)
            except Exception:  # noqa: BLE001 — listener faults never fail
                # queries, but a silently-broken listener is undiagnosable:
                # log it (reference: EventListenerManager catches AND logs)
                logger.exception(
                    "event listener %s failed in query_created for %s",
                    type(lsn).__name__, event.query_id)

    def fire_completed(self, event: QueryCompletedEvent) -> None:
        for lsn in self._snapshot():
            try:
                lsn.query_completed(event)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "event listener %s failed in query_completed for %s",
                    type(lsn).__name__, event.query_id)


@contextlib.contextmanager
def refreshed_server_gauges(server):
    """Refresh the server-derived gauges from the server's PUBLIC
    accessors (``query_state_counts`` — no reaching into ``_qlock``/
    ``queries`` privates) for the duration of the block, then clear them.
    RENDER_LOCK (shared with render_registry, reentrant) makes refresh-
    read-clear one atomic unit: concurrent scrapes — of this server,
    another coordinator, or a same-process worker — never observe a
    half-refreshed gauge. Shared by the Prometheus page
    (``render_metrics``) and the ``system.metrics`` table snapshot
    (server/system_tables.py)."""
    from trino_tpu.obs import metrics as M

    gauges = (M.QUERIES, M.RESULT_ROWS, M.QUERIES_TOTAL, M.WORKERS,
              M.UPTIME_SECONDS, M.QUERY_HISTORY_SIZE)
    with M.RENDER_LOCK:
        by_state, rows = server.query_state_counts()
        M.QUERIES.clear()
        for st, n in by_state.items():
            M.QUERIES.set(n, st)
        M.RESULT_ROWS.set(rows)
        M.QUERIES_TOTAL.clear()
        M.QUERIES_TOTAL.inc(getattr(server, "queries_submitted", 0))
        alive = server.registry.alive() if hasattr(server, "registry") else []
        M.WORKERS.set(len(alive))
        M.UPTIME_SECONDS.set(round(
            time.time() - getattr(server, "start_time", time.time()), 1))
        history = getattr(server, "history", None)
        if history is not None:
            M.QUERY_HISTORY_SIZE.set(len(history))
        dispatcher = getattr(server, "dispatcher", None)
        if dispatcher is not None:
            dispatcher.refresh_gauges()
        try:
            yield
        finally:
            for metric in gauges:
                # clear afterwards: the process-global registry must not
                # keep a stopped server's numbers, and a same-process
                # worker's render must not re-export this coordinator's
                # gauge values as its own
                metric.clear()


def render_metrics(server) -> str:
    """Coordinator metrics page: server-derived gauges refreshed, then the
    typed registry renders, which also carries the process-global engine
    counters and histograms."""
    from trino_tpu.obs import metrics as M

    with refreshed_server_gauges(server):
        return M.render_registry()
