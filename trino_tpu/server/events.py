"""Event listener SPI + metrics collection.

Reference: ``core/trino-spi/.../spi/eventlistener/`` — ``EventListener``
(queryCreated / queryCompleted), ``QueryCompletedEvent`` (metadata, stats,
failure info), registered via EventListenerFactory plugins and dispatched by
``eventlistener/EventListenerManager`` with per-listener exception isolation.
Here the same shape: listeners attach to a Session or a CoordinatorServer,
events are plain dataclasses, and a failing listener never fails the query.

The metrics side (``render_metrics``) exposes the coordinator's counters in
the Prometheus text format — the role of the reference's JMX-to-/metrics
bridge (``trino-jmx`` + airlift's MetricsResource).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class QueryCreatedEvent:
    """Reference: spi/eventlistener/QueryCreatedEvent.java."""

    query_id: str
    user: str
    sql: str
    create_time: float  # epoch seconds


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    """Reference: spi/eventlistener/QueryCompletedEvent.java (metadata +
    statistics + failureInfo, flattened to the fields the engine tracks)."""

    query_id: str
    user: str
    sql: str
    state: str  # FINISHED | FAILED | CANCELED
    create_time: float
    end_time: float
    wall_seconds: float
    output_rows: int
    error: Optional[str] = None


class EventListener:
    """Subclass and override either hook (reference: EventListener's
    default methods are no-ops, so listeners implement only what they use)."""

    def query_created(self, event: QueryCreatedEvent) -> None:  # noqa: B027
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:  # noqa: B027
        pass


class EventListenerManager:
    """Dispatch with per-listener exception isolation (reference:
    eventlistener/EventListenerManager catches and logs per listener)."""

    def __init__(self):
        self._listeners: List[EventListener] = []
        self._lock = threading.Lock()

    def add(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def fire_created(self, event: QueryCreatedEvent) -> None:
        for lsn in list(self._listeners):
            try:
                lsn.query_created(event)
            except Exception:  # noqa: BLE001 — listener faults never fail queries
                pass

    def fire_completed(self, event: QueryCompletedEvent) -> None:
        for lsn in list(self._listeners):
            try:
                lsn.query_completed(event)
            except Exception:  # noqa: BLE001
                pass


def render_metrics(server) -> str:
    """Coordinator counters in the Prometheus text exposition format."""
    by_state: Dict[str, int] = {}
    total_rows = 0
    with server._qlock:
        queries = list(server.queries.values())
    for q in queries:
        st = q.state.get()
        by_state[st] = by_state.get(st, 0) + 1
        if st == "FINISHED":
            total_rows += len(q.rows)
    lines = [
        "# TYPE trino_tpu_queries gauge",
    ]
    for st in sorted(by_state):
        lines.append(f'trino_tpu_queries{{state="{st}"}} {by_state[st]}')
    lines.append("# TYPE trino_tpu_queries_total counter")
    lines.append(f"trino_tpu_queries_total {getattr(server, 'queries_submitted', 0)}")
    lines.append("# TYPE trino_tpu_result_rows gauge")
    lines.append(f"trino_tpu_result_rows {total_rows}")
    workers = server.registry.alive() if hasattr(server, "registry") else []
    lines.append("# TYPE trino_tpu_workers gauge")
    lines.append(f"trino_tpu_workers {len(workers)}")
    lines.append("# TYPE trino_tpu_uptime_seconds gauge")
    lines.append(
        f"trino_tpu_uptime_seconds {time.time() - getattr(server, 'start_time', time.time()):.1f}"
    )
    return "\n".join(lines) + "\n"
