"""Short-query fast path: run single-stage plans coordinator-local.

Reference role: the dispatch/execution split of
``dispatcher/QueuedStatementResource`` exists because the per-query
control-plane work — fragment, create tasks over HTTP, poll status, pull
pages through the exchange — dominates short queries. A point lookup that
executes in ~1 ms pays tens of milliseconds of task round-trips on the
distributed path. When the optimized plan would fragment into at most ONE
distributed stage (point lookups, small scans, single-step aggregations)
and its scans are small, the coordinator can run the WHOLE plan on its own
engine: same admission (``cluster_memory`` gates dispatch before ``_run``
starts), same caches (plan/result lookups happen before execution), same
stats rollups and spans — minus every task HTTP round-trip.

The eligibility predictor mirrors ``fragmenter.cut``'s decisions without
building fragments (no deepcopy, no fragment ids): it walks the optimized
plan and counts the stage cuts fragmentation WOULD make. Drift between
the two is caught by a test that compares the predictor against
``fragment_plan`` across the TPC-H suite (tests/test_fast_path.py).

Gated by the ``short_query_fast_path`` session property (opt-in, like the
other serving knobs) plus a scan-size guard (``fast_path_max_scan_rows``):
big scans keep the cluster's parallelism.
"""
from __future__ import annotations

from typing import Tuple

from trino_tpu.sql.planner import plan as P


def predicted_stage_count(session, root: P.PlanNode) -> int:
    """Number of non-single fragments ``fragment_plan`` would produce for
    this optimized plan (the root single fragment is not counted)."""
    n, rep = _cuts(session, root.source if isinstance(root, P.OutputNode)
                   else root)
    return n + (0 if rep else 1)


def _cuts(session, node: P.PlanNode) -> Tuple[int, bool]:
    """Mirror of ``fragmenter.cut``: returns (fragments the subtree would
    create, is_replicated). Unknown node kinds count as many stages so the
    fast path never claims a plan the fragmenter itself would reject."""
    from trino_tpu.sql.planner.fragmenter import (
        _colocated_join, _hash_distributed_final)

    if isinstance(node, P.TableScanNode):
        return 0, False
    if isinstance(node, (P.FilterNode, P.ProjectNode, P.LimitNode,
                         P.CompactNode)):
        return _cuts(session, node.source)
    if isinstance(node, P.AggregationNode):
        n, rep = _cuts(session, node.source)
        if rep:
            return n, True
        if not P.can_split_aggs(node.aggregates):
            return n + 1, True
        if _hash_distributed_final(session, node):
            return n + 2, True
        return n + 1, True
    if isinstance(node, P.JoinNode):
        ln, lrep = _cuts(session, node.left)
        rn, rrep = _cuts(session, node.right)
        n = ln + rn
        if (session is not None and not lrep and not rrep
                and _colocated_join(session, node, node.left, node.right)):
            return n, False
        if (session is not None and not lrep and not rrep
                and node.left_keys and node.join_type in ("inner", "semi",
                                                          "anti", "left")):
            from trino_tpu.sql.planner import stats

            if stats.join_repartitions(session, node, 1):
                return n + 3, True
        if not rrep:
            n += 1  # broadcast build fragment
        return n, lrep
    if isinstance(node, (P.SortNode, P.TopNNode, P.WindowNode,
                         P.MatchRecognizeNode)):
        n, rep = _cuts(session, node.source)
        return (n if rep else n + 1), True
    if isinstance(node, (P.UnionNode, P.SetOpNode)):
        n = 0
        for kid in node.sources:
            kn, krep = _cuts(session, kid)
            n += kn + (0 if krep else 1)
        return n, True
    if isinstance(node, P.ValuesNode):
        return 0, True
    # fragmenter would raise NotImplementedError: never fast-path it
    return 1 << 10, True


def scan_rows_estimate(session, root: P.PlanNode) -> int:
    """Total estimated rows across the plan's table scans — the work the
    coordinator would absorb without worker parallelism."""
    from trino_tpu.sql.planner import stats

    total = 0
    for node in P.walk_plan(root):
        if isinstance(node, P.TableScanNode):
            total += int(stats.estimate_rows(session, node))
    return total


def fast_path_decision(session, root: P.PlanNode) -> Tuple[bool, str]:
    """(take_fast_path, reason). The reason string rides the
    ``fastpath/execute`` span and EXPLAIN ANALYZE so the decision is
    always inspectable."""
    props = getattr(session, "properties", None) or {}
    if not bool(props.get("short_query_fast_path", False)):
        return False, "short_query_fast_path disabled"
    try:
        stages = predicted_stage_count(session, root)
    except Exception as e:  # noqa: BLE001 — prediction is best-effort
        return False, f"stage prediction failed: {e}"
    if stages > 1:
        return False, f"plan needs {stages} distributed stages"
    max_rows = int(props.get("fast_path_max_scan_rows", 4_000_000))
    rows = scan_rows_estimate(session, root)
    if rows > max_rows:
        return False, (f"~{rows} estimated scan rows exceed "
                       f"fast_path_max_scan_rows={max_rows}")
    return True, f"single-stage plan, ~{rows} estimated scan rows"
