"""Coordinator: dispatch, discovery, scheduling, and the client protocol.

Reference: ``dispatcher/QueuedStatementResource.java:103`` +
``dispatcher/DispatchManager.java:173`` (statement submission),
``execution/SqlQueryExecution.java:393`` (analyze→plan→schedule),
``metadata/DiscoveryNodeManager.java:68`` +
``failuredetector/HeartbeatFailureDetector.java:76`` (membership/liveness),
``server/remotetask/HttpRemoteTask.java:132`` (task CRUD client),
``server/protocol/ExecutingStatementResource.java:69`` (paged results with
``nextUri`` chaining).

Scheduling model (walking skeleton of PipelinedQueryScheduler): every
*source* fragment gets one task per alive worker with splits round-robin
assigned (UniformNodeSelector analog); all stages are scheduled at once and
stream through long-polled output buffers (phased scheduling is a later
refinement); the root *single* fragment executes on the coordinator itself,
pulling upstream pages with the exchange client.
"""
from __future__ import annotations

import itertools
import json
import re
import threading
import time
import traceback
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from trino_tpu.obs import trace as tracing
from trino_tpu.server import wire
from trino_tpu.server.exchange_client import ExchangeClient, TaskLocation
from trino_tpu.server.statemachine import StateMachine, query_state_machine
from trino_tpu.server.task import TaskRequest
from trino_tpu.sql.planner import plan as P
from trino_tpu.sql.planner.fragmenter import RemoteSourceNode, fragment_plan

_ANNOUNCE_RE = re.compile(r"^/v1/announce/([^/]+)$")
_RESULT_RE = re.compile(r"^/v1/statement/executing/([^/]+)/(\d+)$")
_QUERY_RE = re.compile(r"^/v1/query/([^/]+)$")
_TRACE_RE = re.compile(r"^/v1/query/([^/]+)/trace$")
_PROFILE_RE = re.compile(r"^/v1/query/([^/]+)/profile$")
_FLOWS_RE = re.compile(r"^/v1/query/([^/]+)/flows$")
_SEGMENT_RE = re.compile(r"^/v1/segment/([^/]+)$")

RESULT_PAGE_ROWS = 10_000

# sentinel returned by QueryExecution._consult_result_cache when the query
# was answered from the result cache (columns/rows already populated)
_SERVED_FROM_CACHE = "__served_from_cache__"


class NodeRegistry:
    """Worker membership with announce-age liveness (discovery + failure
    detection collapsed: an entry not re-announced within ``max_age`` is
    dead — the push analog of heartbeat ping + decayed failure ratio)."""

    def __init__(self, max_age: float = 10.0):
        self._nodes: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self.max_age = max_age

    def announce(self, node_id: str, url: str,
                 info: Optional[dict] = None) -> None:
        with self._lock:
            self._nodes[node_id] = {"url": url, "last_seen": time.monotonic(),
                                    "info": dict(info or {})}

    def alive(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            return [
                {"nodeId": nid, **info}
                for nid, info in sorted(self._nodes.items())
                if now - info["last_seen"] <= self.max_age
            ]

    def snapshot(self) -> List[dict]:
        """Every known node with its last announce payload and heartbeat
        age — including DEAD entries (announce aged out), which the
        ``system.runtime.nodes`` table surfaces instead of hiding."""
        now = time.monotonic()
        with self._lock:
            return [
                {"nodeId": nid, "url": info["url"],
                 "info": dict(info.get("info") or {}),
                 "ageS": now - info["last_seen"],
                 "alive": now - info["last_seen"] <= self.max_age}
                for nid, info in sorted(self._nodes.items())
            ]

    def wait_for_workers(self, count: int, timeout: float = 30.0) -> bool:
        """ClusterSizeMonitor analog: block dispatch until enough workers."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.alive()) >= count:
                return True
            time.sleep(0.1)
        return False


class QueryExecution:
    """One query's lifecycle on the coordinator."""

    def __init__(self, query_id: str, sql: str, session_properties: dict,
                 registry: NodeRegistry, session_factory, user: str = "anonymous",
                 query_cache=None, prepared_registry=None):
        self.query_id = query_id
        self.sql = sql
        self.user = user
        self.session_properties = dict(session_properties)
        self.state: StateMachine[str] = query_state_machine()
        self.registry = registry
        self.session_factory = session_factory
        # server-wide QueryCache (trino_tpu/cache/) or None (caching off)
        self.query_cache = query_cache
        # server-wide PreparedStatementRegistry (server/prepared.py) or
        # None: PREPARE registers, EXECUTE binds + runs, DEALLOCATE drops
        self.prepared_registry = prepared_registry
        # which control-plane path executed the SELECT: "fast-path"
        # (single-stage plan run coordinator-local), "distributed",
        # "local-catalog" (process-local catalog forced local), or None
        # (non-SELECT / served from the result cache)
        self.fast_path: Optional[str] = None
        # PREPARE/DEALLOCATE round-trip to the client (the
        # X-Trino-Added-Prepare / X-Trino-Deallocated-Prepare analog,
        # carried in the result payload like set/reset session)
        self.add_prepared: Dict[str, str] = {}
        self.deallocated_prepared: List[str] = []
        # result-cache disposition, surfaced as X-Trino-Tpu-Cache:
        # HIT (served from cache / a concurrent leader), MISS (executed,
        # filled the cache), BYPASS (ineligible or cache disabled)
        self.cache_status: Optional[str] = None
        self.failure: Optional[str] = None
        self.columns: List[str] = []
        self.rows: List[tuple] = []
        # SET/RESET SESSION results: the protocol carries them back to the
        # client, which applies them to its subsequent requests (reference:
        # the X-Trino-Set-Session / X-Trino-Clear-Session headers) — the
        # coordinator itself is stateless per query.
        self.set_session: Dict[str, object] = {}
        self.reset_session: List[str] = []
        # FTE bookkeeping: successful attempt index per task + retried ids
        self.task_attempts: Dict[str, int] = {}
        self.retried_tasks: List[str] = []
        # IN-FLIGHT duplicate straggler attempts: entries are pruned when
        # their slot resolves (the speculated task or its original
        # completes), so long queries can't grow this without bound
        self.speculative_tasks: List[str] = []
        # bounded record of every speculation launched (observability/tests)
        from collections import deque

        self.speculation_history = deque(maxlen=64)
        self.fragment_tasks: Dict[int, List[TaskLocation]] = {}
        # distributed stats pipeline (reference: QueryStats/StageStats fed
        # by TaskStatus updates): worker-reported task stats keyed by task
        # SLOT (query.fragment.worker — retried attempts replace their
        # slot), folded into per-stage and per-query rollups on read.
        # Populated by the status-polling loop + the task-create response;
        # a FINISHED attempt's record is never downgraded, so stats freeze
        # naturally once the query reaches a terminal state.
        self.task_stats: Dict[str, dict] = {}
        self._tstats_lock = threading.Lock()
        # fragments of the last distributed execution (EXPLAIN ANALYZE
        # rendering + stage count); None for coordinator-local queries
        self.fragments = None
        # versioned plan changes applied by the adaptive re-planner
        # (trino_tpu/adaptive/), surfaced via GET /v1/query/{id} and the
        # EXPLAIN ANALYZE [adapted: ...] annotations
        self.plan_versions: List[dict] = []
        self.created_at = time.time()
        self.ended_at: Optional[float] = None
        # one trace per query; the trace id doubles as the propagation key
        # stamped on worker/exchange requests (reference: the otel Tracer
        # injected into DispatchManager + the traceparent headers of the
        # internal HTTP clients)
        self.tracer = tracing.Tracer()
        # the coordinator's flight recorder (obs/flightrecorder.py), set
        # by CoordinatorServer.submit — the tracer mirrors closed spans
        # into it, and the FAILED postmortem snapshots it
        self.recorder = None
        # merged coordinator+worker flight-recorder postmortem, captured
        # at FAILED (GET /v1/query/{id}/trace?recorder=1 + the query log)
        self.postmortem: Optional[dict] = None
        # completion-time phase ledger (obs/timeline.QueryTimeline),
        # computed once from the merged span tree and cached
        self._timeline = None
        # when the client last fetched a FINISHED result page — feeds the
        # ledger's client-drain phase (outside the query wall)
        self.last_drain_at: Optional[float] = None
        # dispatch/executor split (server/dispatch.py): which plane ran
        # this query ("dispatch-lane" inline, "executor-process:N" when
        # forwarded), the queue-residency span the lane closes on
        # dequeue, and spans pulled from an executor process's trace
        # (merged into the ledger and the trace endpoint)
        self.plane: str = "dispatch-lane"
        self._dispatch_queue_span = None
        self.extra_spans: List[dict] = []
        # resource-group admission (server/resource_groups.py): the full
        # dotted group path this query was classified into by the
        # selector chain (None under an injected legacy gate), and the
        # client-reported source the selectors may route on
        # (X-Trino-Source); queued-ahead count captured at enqueue
        self.resource_group: Optional[str] = None
        self.source: str = ""
        self.queued_ahead: Optional[int] = None
        # set by the server at submit: the shared IO thread pool for
        # parallel worker pulls (span dumps, flight-recorder rings) and
        # the dispatcher completion hook
        self.io_pool = None
        self.dispatcher = None
        # serving-index learning (dispatch.ServingIndex): whether the
        # statement was a plain SELECT shape, and the result-cache key +
        # captured data versions of a led flight
        self.is_plain_select = False
        self.result_cache_key: Optional[str] = None
        self.result_cache_versions = None
        # materialized-view substitutions applied to this query's plan
        # (qualified view names, in decision order) + the full decision
        # notes — queryStats.mvHits/mvNames and EXPLAIN ANALYZE headers
        self.mv_substitutions: List[str] = []
        self.mv_notes: List[dict] = []
        # spooled result protocol (server/segments.py): when the query's
        # results went to segments, the statement response carries this
        # MANIFEST ({uri, ackUri, id, rows, bytes, codec} per segment)
        # instead of inline rows; ``spooled`` records which producer
        # wrote them ("worker-direct" — root-fragment tasks, the
        # coordinator never touched the data — or "coordinator")
        self.result_segments: Optional[List[dict]] = None
        self.spooled: Optional[str] = None
        # segment id -> owning worker base url (ack forwarding + early
        # discard); empty for coordinator-spooled queries
        self._segment_workers: Dict[str, str] = {}
        # set by CoordinatorServer.submit: this coordinator's segment
        # store + public base url (None for bare embedded executions,
        # which then never spool)
        self.segment_store = None
        self.segment_base_url: Optional[str] = None
        # when a client last fetched/acked a result segment through this
        # coordinator — feeds the ledger's segment-fetch phase (outside
        # the query wall, beside client-drain)
        self.last_segment_fetch_at: Optional[float] = None

    def start(self) -> None:
        """Run the lifecycle on a fresh thread (legacy surface — the
        server's executor lanes call ``run()`` inline instead)."""
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def cancel(self) -> None:
        self.ended_at = self.ended_at or time.time()
        if self.state.set("CANCELED"):
            self._cancel_tasks()

    def kill(self, reason: str) -> None:
        """Administrative kill (low-memory killer): FAILED with the given
        reason; running tasks are canceled (reference:
        QueryExecution.fail from ClusterMemoryManager's killer)."""
        self.failure = reason
        self.ended_at = self.ended_at or time.time()
        if self.state.set("FAILED"):
            self._cancel_tasks()

    # ------------------------------------------------------------ lifecycle
    def run(self) -> None:
        root_span = self.tracer.start_span(
            "query", query_id=self.query_id, user=self.user)
        # the dispatch-queue span opened before this root existed (the
        # HTTP thread enqueued, a lane dequeued): adopt it so the trace
        # tree stays single-rooted under the query span
        qs = getattr(self, "_dispatch_queue_span", None)
        if qs is not None:
            qs.parent_id = root_span.span_id
        try:
            with tracing.activate(self.tracer, root_span.span_id):
                self._run_lifecycle()
            # close the trace BEFORE the terminal transition: the state
            # machine's listeners (QueryCompletedEvent) snapshot the spans,
            # and a query that completes on THIS thread must carry its
            # duration by then (a cancel/kill from another thread still
            # fires with whatever was recorded at that instant)
            self.tracer.end_span(root_span)
            # stop the stats clock BEFORE the terminal transition so a poll
            # racing the state change never reads a live elapsed time on a
            # terminal query
            self.ended_at = time.time()
            # warm the phase ledger on THIS thread before the terminal
            # transition: the compute pulls worker span dumps over HTTP,
            # and the state listeners (history recording, events) must
            # stay fast — they read the cached result
            self._warm_timeline()
            self.state.set("FINISHED")
        except Exception as e:  # noqa: BLE001 — reported through query info
            self.ended_at = self.ended_at or time.time()
            if self.failure is None:
                # an administrative kill() may already have set the real
                # reason; the task-cancellation fallout must not clobber it
                self.failure = f"{e}\n{traceback.format_exc()}"
            root_span.set("error", str(e).split("\n")[0][:300])
            self._cancel_tasks()
            self.tracer.end_span(root_span)
            self._warm_timeline()
            # capture the flight-recorder postmortem BEFORE FAILED is
            # visible (same fast-listener contract as the ledger): the
            # workers' rings still hold the context around the failure
            try:
                self.capture_postmortem(
                    timeout=self.COMPLETION_PULL_TIMEOUT)
            except Exception:  # noqa: BLE001 — best-effort forensics
                pass
            self.state.set("FAILED")
        finally:
            self.ended_at = self.ended_at or time.time()
            self.tracer.end_span(root_span)  # idempotent safety net
            # the latch decides: a kill()/cancel() racing this thread may
            # already have set CANCELED/FAILED — record what actually stuck
            root_span.set("state", self.state.get())
            self._cleanup_spool()

    def _run_lifecycle(self) -> None:
        """The coordinator half of the query, span-per-phase (reference:
        SqlQueryExecution.start's analyze -> plan -> schedule with otel
        spans around each)."""
        self.state.set("PLANNING")
        session = self.session_factory(self.session_properties)
        from trino_tpu.server.security import Identity

        session.identity = Identity(self.user)
        # procedures (CALL) resolve the calling query through the session:
        # system.runtime.kill_query refuses to kill its own query
        session.query_id = self.query_id
        from trino_tpu.exec.query import run_query
        from trino_tpu.sql.parser import ast
        from trino_tpu.sql.parser.parser import parse_statement

        # statement-kind probe, unspanned: plan_sql re-parses under its own
        # "parse" span, and two parse spans would double-attribute the time
        stmt = parse_statement(self.sql)
        if (isinstance(stmt, ast.Explain) and stmt.analyze
                and isinstance(stmt.statement, ast.Query)):
            # distributed EXPLAIN ANALYZE: run the statement through the
            # real fragment/schedule/execute path, then print the fragments
            # annotated with the workers' rolled-up OperatorStats — no
            # coordinator-local re-execution (reference:
            # ExplainAnalyzeOperator consuming the stage stats it ran under)
            self.cache_status = "BYPASS"
            text = self._explain_analyze(session, stmt)
            # the deliverable is the annotated plan, not the inner
            # query's rows: release any segments the execution spooled
            self._discard_spooled_result()
            self.columns = ["Query Plan"]
            self.rows = [(line,) for line in text.split("\n")]
            return
        if isinstance(stmt, (ast.Prepare, ast.ExecutePrepared,
                             ast.Deallocate)) \
                and self.prepared_registry is not None:
            # the serving surface (server/prepared.py): PREPARE registers
            # against the server-wide registry (per-user), EXECUTE binds
            # into the cached parameterized plan, DEALLOCATE drops — none
            # of this can run on the throwaway per-query session, whose
            # state dies with this statement
            self._run_prepared_statement(session, stmt)
            return
        if isinstance(stmt, (ast.CreateMaterializedView,
                             ast.RefreshMaterializedView,
                             ast.DropMaterializedView)):
            # materialized views (trino_tpu/matview/): the REFRESH's
            # defining query executes through the NORMAL path
            # (_execute_query: fast-path / local-catalog / distributed),
            # then the rows swap into the storage table and the registry
            # change replicates to the executor-process plane
            self._run_mv_statement(session, stmt, self.sql)
            return
        if not isinstance(stmt, ast.Query):
            # metadata statements (SHOW …, EXPLAIN), CALL, and DML/DDL run
            # coordinator-local and always bypass the result cache — the
            # mutation itself is what bumps the connector data versions
            # that invalidate cached SELECTs over the touched tables
            self.cache_status = "BYPASS"
            self.state.set("RUNNING")
            with self.tracer.span("execute/coordinator-local"):
                result = run_query(session, self.sql)
            self.columns, self.rows = result.column_names, result.rows
            if isinstance(stmt, ast.SetSession):
                # run_query validated+coerced it on the throwaway session
                self.set_session[stmt.name] = session.properties[stmt.name]
            elif isinstance(stmt, ast.ResetSession):
                self.reset_session.append(stmt.name)
            return
        self.is_plain_select = True
        root, versions = self._plan_query(session, stmt)
        root, versions = self._substitute_matviews(session, root, versions)
        key = self._consult_result_cache(session, stmt, root, versions)
        self._finish_with_result_cache(session, root, key)

    # ------------------------------------------------- materialized views
    def _run_mv_statement(self, session, stmt, sql) -> None:
        """CREATE / REFRESH / DROP MATERIALIZED VIEW on the coordinator.
        The refresh's defining query runs through ``_execute_query`` so
        big definitions fragment and schedule across workers exactly like
        a user SELECT; the materialized rows then swap into the storage
        table (matview/lifecycle.py owns the version bookkeeping).
        ``sql`` is the CREATE statement's own text (the prepared path
        passes the registered inner text, or None when bound parameters
        made the stored text no longer describe the bound AST)."""
        from trino_tpu.matview import lifecycle as mv_lifecycle

        self.cache_status = "BYPASS"

        def execute_fn(root):
            # the refresh consumes materialized rows: spooled manifests
            # would leave them in segments nobody decodes server-side
            session.properties["spooled_results_enabled"] = False
            self._execute_query(session, root)
            rows, self.rows = self.rows, []
            return rows

        # under the executor-process plane, substituted SELECTs run in
        # the children — warming THIS process's device cache on refresh
        # would stage a table no query here ever scans
        warm = getattr(self.dispatcher, "process_plane", None) is None
        columns, rows = mv_lifecycle.dispatch_mv_statement(
            session, stmt, sql=sql, execute_fn=execute_fn, warm=warm)
        if self.state.get() in ("QUEUED", "PLANNING", "STARTING"):
            self.state.set("RUNNING")
        self.columns, self.rows = columns, rows
        self._replicate_mv_change(session, stmt)

    def _replicate_mv_change(self, session, stmt) -> None:
        """Process plane only: ship the registry mutation to every booted
        executor process (``CALL system.runtime.sync_materialized_view``
        with a base64 payload), so sticky-routed SELECTs substitute — or
        stop substituting — there too. Best-effort, like the prepared-
        registry broadcast."""
        pp = getattr(self.dispatcher, "process_plane", None)
        if pp is None:
            return
        import base64
        import json as _json

        from trino_tpu.matview import registry as mv_registry
        from trino_tpu.matview.lifecycle import resolve_mv_name
        from trino_tpu.sql.parser import ast

        catalog, schema, name = resolve_mv_name(session, stmt.name)
        if isinstance(stmt, ast.DropMaterializedView):
            payload = mv_registry.drop_payload(catalog, schema, name)
        else:
            mv = session.matviews.get(catalog, schema, name)
            if mv is None or mv.definition_sql is None:
                return
            payload = mv_registry.to_payload(mv)
        blob = base64.b64encode(
            _json.dumps(payload).encode()).decode()
        # signed with the cluster-internal secret (children inherit it
        # via their spawn env): the receiving procedure rejects anything
        # an ordinary client could forge
        sig = wire.sign(blob.encode())
        pp.broadcast(
            f"CALL system.runtime.sync_materialized_view('{blob}', "
            f"'{sig}')",
            self.user, self.session_properties)

    def _substitute_matviews(self, session, root, versions):
        """The MV substitution pass, applied AFTER the plan cache (a
        cached plan must stay substitution-free — freshness varies per
        execution; the pass copies-on-write, never mutating the cached
        tree) with the captured versions recomputed for the result-cache
        key: the substituted plan's own scans (storage + any remaining
        base scans) UNION the views' recorded base versions, so a
        REFRESH and a base-table DML both invalidate cached results."""
        from trino_tpu.matview.substitute import (
            substitute_plan, substitution_versions)

        new_root, notes = substitute_plan(session, root)
        self.mv_notes = notes
        self.mv_substitutions = [
            n["view"] for n in notes if n["result"] == "substituted"]
        if not self.mv_substitutions:
            return root, versions
        return new_root, substitution_versions(session, new_root, notes)

    def _finish_with_result_cache(self, session, root, key) -> None:
        """Shared tail of the SELECT lifecycle: serve/lead/bypass against
        the result cache, executing through ``_execute_query`` otherwise.
        A leader that fails abandons its flight (waiters re-execute)."""
        if key == _SERVED_FROM_CACHE:
            self.state.set("FINISHING")
            return
        if key is None:
            self._execute_query(session, root)
            return
        try:
            self._execute_query(session, root)
        except BaseException:
            self.query_cache.results.abandon(key)
            raise
        if self.result_segments is not None:
            # spooled results never enter the result cache: the rows were
            # deliberately never materialized on this coordinator —
            # abandon the flight so single-flight waiters re-execute
            # instead of inheriting an empty payload
            self.query_cache.results.abandon(key)
            return
        self.query_cache.results.complete(
            key, self.columns, self.rows,
            ttl_ms=session.properties.get("result_cache_ttl_ms", 60_000),
            max_bytes=session.properties.get("result_cache_max_bytes"))

    # ------------------------------------------------- prepared statements
    def _run_prepared_statement(self, session, stmt) -> None:
        """PREPARE / EXECUTE / DEALLOCATE against the server-wide registry
        (reference: PrepareTask/DeallocateTask + the EXECUTE rewrite of
        QueuedStatementResource, collapsed onto the query thread)."""
        from trino_tpu.sql.parser import ast

        reg = self.prepared_registry
        if isinstance(stmt, ast.Prepare):
            self.cache_status = "BYPASS"
            self.state.set("RUNNING")
            inner = stmt.statement
            if isinstance(inner, (ast.Prepare, ast.ExecutePrepared,
                                  ast.Deallocate)):
                raise ValueError(
                    "cannot PREPARE another prepared-statement control "
                    "statement")
            # the inner statement's text, for display surfaces: the PREPARE
            # grammar is rigid, so stripping the one fixed prefix is exact
            m = re.match(r"(?is)^\s*prepare\s+\S+\s+from\s+(.*)$",
                          self.sql.strip())
            sql_text = (m.group(1) if m else self.sql).strip()
            reg.put(self.user, stmt.name, inner, sql_text)
            self.add_prepared[stmt.name] = sql_text
            self.columns, self.rows = ["result"], [("PREPARE",)]
            self._replicate_registry_change()
            return
        if isinstance(stmt, ast.Deallocate):
            self.cache_status = "BYPASS"
            self.state.set("RUNNING")
            if not reg.remove(self.user, stmt.name):
                raise ValueError(
                    f"prepared statement not found: {stmt.name}")
            self.deallocated_prepared.append(stmt.name)
            self.columns, self.rows = ["result"], [("DEALLOCATE",)]
            self._replicate_registry_change()
            return
        self._run_execute_prepared(session, stmt)

    def _replicate_registry_change(self) -> None:
        """Process plane only: replay this PREPARE/DEALLOCATE on every
        executor process so their replica registries track the dispatch
        process's authoritative one (the owner of the structure)."""
        pp = getattr(self.dispatcher, "process_plane", None)
        if pp is not None:
            pp.broadcast(self.sql, self.user, self.session_properties)

    def _run_execute_prepared(self, session, stmt) -> None:
        """EXECUTE name [USING ...]: constant-fold the bindings, reuse (or
        create) the ONE cached parameterized plan for this statement+type
        signature, substitute the bound constants into a copy, and run it
        through the normal result-cache + execution pipeline. The second
        EXECUTE of a statement does zero parse/analyze/plan/optimize work
        — only the bind pass (microseconds) and execution."""
        from trino_tpu.obs import metrics as M
        from trino_tpu.server import prepared as prep
        from trino_tpu.sql.parser import ast

        ps = self.prepared_registry.get(self.user, stmt.name)
        if ps is None:
            raise ValueError(f"prepared statement not found: {stmt.name}")
        inner = ps.statement
        # bind step 1 — fold + arity: USING arguments must be constant
        # expressions whatever the inner statement kind, and the
        # executions counter only moves once the binding is valid
        t0 = time.perf_counter()
        with self.tracer.span("prepare/bind") as sp:
            sp.set("statement", stmt.name)
            sp.set("step", "fold")
            values = prep.fold_execute_args(stmt.params)
            prep.check_arity(ps, values)
            sp.set("parameters", len(values))
        fold_s = time.perf_counter() - t0
        self.prepared_registry.touch(self.user, stmt.name)
        if not isinstance(inner, ast.Query):
            # prepared DML/DDL/metadata: bind at the AST level (the raw
            # USING exprs, proven constant above) and run coordinator-
            # local — the mutation bumps data versions exactly like the
            # unprepared spelling
            from trino_tpu.exec.query import bind_parameters
            from trino_tpu.exec.query import dispatch_statement

            self.cache_status = "BYPASS"
            bound = bind_parameters(inner, stmt.params)
            M.EXECUTE_BIND_SECONDS.observe(fold_s)
            if isinstance(bound, (ast.CreateMaterializedView,
                                  ast.RefreshMaterializedView,
                                  ast.DropMaterializedView)):
                # prepared MV DDL takes the SAME path as the unprepared
                # spelling: distributed refresh + executor-plane registry
                # replication. The registered inner text serves as the
                # definition SQL; with bound parameters the stored text no
                # longer describes the bound AST, so replication (which
                # ships definitions as SQL) degrades to local-only
                self._run_mv_statement(
                    session, bound,
                    ps.sql if not stmt.params else None)
                return
            self.state.set("RUNNING")
            with self.tracer.span("execute/coordinator-local"):
                result = dispatch_statement(session, bound)
            self.columns, self.rows = result.column_names, result.rows
            return
        self.is_plain_select = True
        ptypes = tuple(c.type for c in values)
        # planning (plan-cache miss only) stays OUTSIDE the bind timer and
        # span: trino_tpu_execute_bind_seconds measures exactly the
        # per-request work a warm EXECUTE pays (fold + substitute)
        root, versions = self._plan_prepared(session, ps, ptypes)
        t1 = time.perf_counter()
        with self.tracer.span("prepare/bind") as sp:
            sp.set("step", "substitute")
            bound_root = prep.bind_plan_parameters(root, values)
        M.EXECUTE_BIND_SECONDS.observe(
            fold_s + (time.perf_counter() - t1))
        # per-binding consult metadata, computed ONCE per parameterized
        # plan OBJECT (a replanned/evicted plan is a new object, so this
        # can never serve a stale canonical): the determinism verdict and
        # the canonical plan string are binding-independent — only the
        # bound values (in `extra`) and data versions vary per request
        meta = getattr(root, "_consult_meta", None)
        if meta is None:
            from trino_tpu.cache.determinism import uncachable_reason
            from trino_tpu.cache.plan_key import canonicalize_plan

            reason = uncachable_reason(inner, root)
            meta = (reason,
                    canonicalize_plan(root) if reason is None else None)
            root._consult_meta = meta
        binding = "params=" + repr(
            [(str(c.type), repr(c.value)) for c in values])
        # MV substitution on the BOUND plan (outside the bind timer): the
        # result-cache key stays the parameterized canonical — still
        # correct because the merged versions (storage + base) move on
        # both REFRESH and base DML
        bound_root, versions = self._substitute_matviews(
            session, bound_root, versions)
        key = self._consult_result_cache(session, inner, bound_root,
                                         versions, prepared_meta=meta,
                                         binding=binding)
        self._finish_with_result_cache(session, bound_root, key)

    def _plan_prepared(self, session, ps, ptypes):
        """The parameterized plan for one prepared statement + binding
        type signature, through the server's logical-plan cache: ONE cache
        entry serves every binding of that signature (the plan keeps
        symbolic ``ir.Parameter`` placeholders — values never bake in).
        Returns ``(root, versions)`` like ``_plan_query``."""
        from trino_tpu.sql.analyzer.expr_analyzer import parameter_types

        def plan_fn():
            from trino_tpu.sql.planner.optimizer import optimize
            from trino_tpu.sql.planner.planner import Planner

            inner = ps.statement
            udfs = getattr(session, "udfs", None)
            if udfs:
                from trino_tpu.sql.routines import expand_udfs

                inner = expand_udfs(inner, udfs)
            with parameter_types(ptypes):
                with tracing.span("analyze/plan"):
                    root = Planner(session).plan(inner)
                with tracing.span("optimize"):
                    return optimize(root, session)

        return self._through_plan_cache(
            session, ps.statement, ps.plan_cache_sql(ptypes), plan_fn)

    def _through_plan_cache(self, session, stmt, key_sql, plan_fn):
        """Plan-cache choreography shared by plain SELECTs and prepared
        EXECUTEs: serve a still-valid entry (hit metric + span), else plan
        via ``plan_fn`` and admit. Table-function statements never cache
        (their rows freeze into the plan at plan time). Returns
        ``(root, versions)`` — versions None when the cache is off."""
        from trino_tpu.cache.determinism import contains_table_function
        from trino_tpu.cache.plan_key import capture_versions
        from trino_tpu.obs import metrics as M

        cache = self.query_cache
        use_plan_cache = (cache is not None and bool(
            session.properties.get("logical_plan_cache_enabled", True))
            and not contains_table_function(stmt))
        if use_plan_cache:
            hit = cache.plans.get(session, key_sql)
            if hit is not None:
                M.PLAN_CACHE_HITS.inc()
                with self.tracer.span("plan-cache/hit"):
                    pass
                return hit
            M.PLAN_CACHE_MISSES.inc()
        root = plan_fn()
        versions = None
        if use_plan_cache:
            versions = capture_versions(session, root)
            cache.plans.put(session, key_sql, root, versions)
        return root, versions

    def _plan_query(self, session, stmt):
        """Optimized plan for this SELECT, through the server's logical-
        plan cache when enabled (skipping parse/analyze/plan/optimize on
        canonical-SQL repeat; entries revalidate against connector data
        versions inside PlanCache.get). Table-function statements never
        plan-cache: their rows materialize into the plan at plan time.

        Returns ``(root, versions)`` — the data versions captured while
        planning/revalidating (None when not computed), handed onward so
        the result-cache lookup doesn't re-stat every table."""
        from trino_tpu.exec.query import plan_sql

        # plan_sql emits nested parse + analyze/plan + optimize spans
        return self._through_plan_cache(
            session, stmt, self.sql, lambda: plan_sql(session, self.sql))

    def _consult_result_cache(self, session, stmt, root, versions=None,
                              prepared_meta=None, binding=None):
        """One admission pass against the server result cache. Returns
        ``_SERVED_FROM_CACHE`` (columns/rows already populated), a cache
        key string (this query leads the flight and must complete/abandon
        it), or None (bypass / follower fallback: execute, don't store).
        ``prepared_meta`` = (reason, canonical-of-parameterized-plan) from
        the EXECUTE hot path — skips the per-request determinism walk and
        plan re-serialization; ``binding`` discriminates the key per bound
        values."""
        from trino_tpu.cache.determinism import uncachable_reason
        from trino_tpu.cache.plan_key import (
            capture_versions, fingerprint_from_canonical, plan_fingerprint)
        from trino_tpu.obs import metrics as M

        cache = self.query_cache
        if cache is None or not bool(
                session.properties.get("result_cache_enabled", False)):
            self.cache_status = "BYPASS"
            return None
        canonical = None
        if prepared_meta is not None:
            reason, canonical = prepared_meta
        else:
            reason = uncachable_reason(stmt, root)
        if reason is None:
            # captured at plan time (threaded through from _plan_query
            # when it already did the capture): a later mutation bumps the
            # version, the next identical query fingerprints differently,
            # and the stale entry misses naturally
            if versions is None:
                versions = capture_versions(session, root)
            if versions is None:
                reason = "unversioned table"
        with self.tracer.span("cache/lookup") as sp:
            if reason is not None:
                self.cache_status = "BYPASS"
                M.RESULT_CACHE_BYPASSES.inc()
                sp.set("disposition", "BYPASS")
                sp.set("reason", reason)
                return None
            # the user partitions the key: plan-time access control must
            # re-fire per principal, never be laundered through a cache hit
            from trino_tpu.cache.result_cache import session_user

            extra = (f"user={session_user(session)}",) + (
                (binding,) if binding else ())
            key = (fingerprint_from_canonical(canonical, versions, extra)
                   if canonical is not None
                   else plan_fingerprint(root, versions, extra=extra))
            sp.set("key", key[:16])
            # serving-index learning (server/dispatch.py): on FINISHED
            # MISS, the dispatcher maps (user, SQL) -> this key so a
            # repeat serves on the dispatch plane without planning
            self.result_cache_key = key
            self.result_cache_versions = versions
            kind, payload = cache.results.begin(key)
            if kind == "wait":
                # single-flight: a concurrent identical query is already
                # executing — park on its flight instead of duplicating
                sp.set("single_flight", True)
                M.RESULT_CACHE_SINGLE_FLIGHT_WAITS.inc()
                done = payload.wait(timeout=600.0)
                if done and payload.ok:
                    kind, payload = "hit", payload.value
                else:
                    # the leader failed or timed out: execute ourselves,
                    # uncached (no flight ownership to publish through)
                    self.cache_status = "MISS"
                    M.RESULT_CACHE_MISSES.inc()
                    sp.set("disposition", "MISS")
                    return None
            if kind == "hit":
                columns, rows = payload
                self.cache_status = "HIT"
                M.RESULT_CACHE_HITS.inc()
                sp.set("disposition", "HIT")
                sp.set("rows", len(rows))
                self.columns, self.rows = list(columns), list(rows)
                return _SERVED_FROM_CACHE
            self.cache_status = "MISS"
            M.RESULT_CACHE_MISSES.inc()
            sp.set("disposition", "MISS")
            return key

    def _execute_query(self, session, root) -> None:
        """Run an already-optimized SELECT plan: coordinator-local for
        process-local catalogs and fast-path-eligible short queries, else
        fragment + schedule + root fragment."""
        from trino_tpu.obs import metrics as M

        if any(
            isinstance(n, P.TableScanNode)
            and session.catalogs[n.catalog].coordinator_only
            for n in P.walk_plan(root)
        ):
            # scans over process-local catalogs (memory, system) cannot be
            # shipped to workers — execute on the coordinator's own
            # engine (its embedded worker role). RUNNING is set so the
            # query observes ITSELF truthfully through
            # system.runtime.queries while its scan materializes.
            self._run_local(session, root, path="local-catalog",
                            span_name="execute/coordinator-local")
            return
        from trino_tpu.server import fastpath

        take, reason = fastpath.fast_path_decision(session, root)
        if take:
            # short-query fast path (server/fastpath.py): the plan would
            # fragment into at most one distributed stage, so the task
            # round-trips buy nothing — run it on the coordinator's own
            # engine, with the decision on the span/query info/EXPLAIN
            self.fast_path_reason = reason
            self._run_local(session, root, path="fast-path",
                            span_name="fastpath/execute", reason=reason)
            return
        self.fast_path = "distributed"
        M.FAST_PATH_QUERIES.inc(1, "distributed")
        with self.tracer.span("fragment") as sp:
            fragments = fragment_plan(root, session)
            sp.set("fragments", len(fragments))
        self.fragments = fragments
        # spooled-results decision for the export shape, made BEFORE
        # scheduling: the producing fragment's tasks then write result
        # segments directly and the coordinator never pulls the data
        spool_fid = self._mark_worker_direct_spool(session, root, fragments)
        # the schedule span covers the whole dispatch tail — worker
        # selection, task creation, the RUNNING transition (whose state
        # listeners run inline), and the stats-poller spawn — so the
        # phase ledger attributes all of it to `schedule` instead of
        # leaving sub-millisecond gaps around the task POSTs
        with self.tracer.span("schedule") as sp:
            self.state.set("STARTING")
            workers = self.registry.alive()
            if not workers:
                raise RuntimeError("no alive workers")
            sp.set("workers", len(workers))
            self._schedule(session, fragments, workers)
            self.state.set("RUNNING")
            self._start_stats_poller()
        result_page = None
        if spool_fid is not None:
            # worker-direct spooled results: wait for the producers to
            # finish writing their segments, assemble the manifest from
            # their status payloads — metadata only, no page ever crosses
            # this process (the coordinator is off the data path)
            with self.tracer.span("segments/collect") as sp:
                self._collect_result_segments(spool_fid)
                sp.set("segments", len(self.result_segments or ()))
        else:
            with self.tracer.span("execute/root-fragment"):
                result_page = self._run_root_fragment(session, fragments)
        # freeze the rollup on the workers' terminal numbers before the
        # query leaves RUNNING (tasks are at least FLUSHING once the root
        # fragment has drained their buffers); spanned so the ledger can
        # attribute this control-plane wall instead of leaving a gap
        with self.tracer.span("stats/sweep") as sp:
            sp.set("polled", self._sweep_task_stats())
        self.state.set("FINISHING")
        self.columns = fragments[-1].root.column_names
        if result_page is not None:
            self._materialize_result(session, result_page)

    def _cleanup_spool(self) -> None:
        """Drop this query's spooled task outputs (reference: exchange
        lifecycle — sink files are deleted when the query completes)."""
        import glob
        import os

        from trino_tpu.server.task import spool_directory

        spool_dir = spool_directory()
        if not spool_dir:
            return
        for path in glob.glob(os.path.join(spool_dir, f"{self.query_id}.*.pages")):
            try:
                os.remove(path)
            except OSError:
                pass

    # ------------------------------------------------- spooled results
    def result_rows(self) -> int:
        """Result cardinality across both protocols: materialized rows
        inline, summed manifest rows when spooled."""
        if self.result_segments is not None:
            return sum(int(e.get("rows", 0)) for e in self.result_segments)
        return len(self.rows)

    def _spool_config(self, session) -> Optional[dict]:
        """The spooled-results knobs, or None when the protocol is off
        for this query (disabled, or no segment store — bare embedded
        executions)."""
        props = session.properties
        if self.segment_store is None or not bool(
                props.get("spooled_results_enabled", False)):
            return None
        return {
            "threshold": int(
                props.get("spooled_results_threshold_bytes", 8 << 20)),
            "segment_bytes": int(
                props.get("spooled_results_segment_bytes", 8 << 20)),
            "ttl_s": int(props.get("result_segment_ttl_ms",
                                   300_000)) / 1e3,
        }

    def _materialize_result(self, session, page) -> None:
        """The result tail every SELECT path funnels through: serve the
        page inline (result/serialize -> Python rows) or — when the
        ACTUAL bytes cross the spool threshold — encode it into this
        coordinator's segment store and publish a manifest instead. The
        inline-result memory guard lives here too: over
        ``inline_result_max_bytes`` the query auto-spools (protocol
        enabled) or FAILS loudly — one export query must never OOM the
        dispatch plane by silently materializing in process memory."""
        from trino_tpu.obs import metrics as M

        est = int(page.live_count()) * int(page.row_byte_estimate())
        cfg = self._spool_config(session)
        cap = int(session.properties.get("inline_result_max_bytes",
                                         256 << 20))
        if cfg is not None and est >= min(cfg["threshold"], cap):
            self._spool_result_page(session, page, cfg)
            return
        if est > cap:
            M.INLINE_RESULT_REJECTIONS.inc()
            raise RuntimeError(
                f"result is ~{est} serialized bytes, over "
                f"inline_result_max_bytes={cap}: the coordinator refuses "
                "to materialize it in process memory "
                "(INLINE_RESULT_TOO_LARGE) — enable "
                "spooled_results_enabled to serve it as a spooled "
                "segment manifest, or narrow the query")
        with self.tracer.span("result/serialize") as sp:
            self.rows = page.to_pylist()
            sp.set("rows", len(self.rows))

    def _spool_result_page(self, session, page, cfg) -> None:
        """Coordinator-side spool: chunk + serde-encode the result page
        into size-bounded segments in this coordinator's own store
        (coordinator-local, fast-path, and non-trivial-root distributed
        queries — the decision is plan-shape-independent; only the
        worker-direct shape also skips this process's encode)."""
        from trino_tpu.data.serde import serialize_page
        from trino_tpu.obs import metrics as M
        from trino_tpu.server.task import _chunk_pages

        page = page.compact()
        chunk_target = int(session.properties.get(
            "task_output_chunk_bytes", 4 << 20))
        chunk_rows = (max(1, chunk_target // page.row_byte_estimate())
                      if page.num_rows else 1)
        writer = self.segment_store.writer(
            self.query_id, target_bytes=cfg["segment_bytes"],
            ttl_s=cfg["ttl_s"])
        with self.tracer.span("result/spool") as sp:
            for c in _chunk_pages(page, chunk_rows):
                writer.add(serialize_page(c), int(c.num_rows))
            metas = writer.finish()
            sp.set("segments", len(metas))
            sp.set("rows", int(page.num_rows))
        base = self.segment_base_url or ""
        self.result_segments = [
            {**m.manifest_entry(),
             "uri": f"{base}/v1/segment/{m.segment_id}",
             "ackUri": f"{base}/v1/segment/{m.segment_id}"}
            for m in metas]
        self.spooled = "coordinator"
        self.rows = []
        M.SPOOLED_RESULT_QUERIES.inc(1, "coordinator")

    def _mark_worker_direct_spool(self, session, root, fragments):
        """Worker-direct spooling decision, made BEFORE scheduling: when
        the root single fragment is a pure gather pass-through
        (OutputNode over one RemoteSourceNode — the export shape) and
        the ESTIMATED result crosses the spool threshold, the producing
        fragment's tasks write result segments directly and the
        coordinator never runs the root fragment at all. Returns the
        producing fragment id, or None — in which case the actual-bytes
        decision in ``_materialize_result`` still applies, so the
        protocol choice stays plan-shape-independent."""
        cfg = self._spool_config(session)
        if cfg is None:
            return None
        if str(self.session_properties.get(
                "retry_policy", "NONE")).upper() == "TASK":
            # FTE may run duplicate attempts whose losing segments would
            # outlive the manifest; large FTE results still spool through
            # the coordinator path
            return None
        src = self._gather_passthrough(fragments[-1])
        if src is None:
            return None
        frag = next((f for f in fragments if f.id == src.fragment_id),
                    None)
        if frag is None or getattr(frag, "output_partition_channels",
                                   None):
            return None
        out = fragments[-1].root
        from trino_tpu.server import fastpath

        est_rows = fastpath.scan_rows_estimate(session, root)
        est_bytes = est_rows * 8 * max(1, len(out.column_names or ()))
        if est_bytes < cfg["threshold"]:
            return None
        frag.spool_results = True
        return frag.id

    @staticmethod
    def _gather_passthrough(root_frag):
        """The gather RemoteSourceNode when the root single fragment is
        a pure pass-through (OutputNode over one gather source — the
        export shape, where gathered bytes == result bytes), else
        None."""
        out = root_frag.root
        src = out.source if isinstance(out, P.OutputNode) else out
        if (isinstance(src, RemoteSourceNode)
                and src.exchange_type == "gather"):
            return src
        return None

    SEGMENT_COLLECT_TIMEOUT = 600.0

    def _collect_result_segments(self, fid: int) -> None:
        """Wait for the result-producing tasks to FINISH (their segments
        are durable by then) and assemble the statement manifest from
        their status payloads, in task order — the coordinator handles
        only metadata. Data fetches go straight to the owning worker;
        ACKs route through the coordinator (a tiny control-plane DELETE)
        so segment-fetch activity is attributable per query."""
        from trino_tpu.obs import metrics as M

        deadline = time.monotonic() + self.SEGMENT_COLLECT_TIMEOUT
        entries: List[dict] = []
        base = self.segment_base_url or ""
        for loc in self.fragment_tasks.get(fid, ()):
            info = None
            while True:
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"result task {loc.task_id} did not finish "
                        f"within {self.SEGMENT_COLLECT_TIMEOUT:g}s")
                if self.state.is_terminal():
                    raise RuntimeError("query was canceled")
                try:
                    status, body, _ = wire.http_request(
                        "GET",
                        f"{loc.base_url}/v1/task/{loc.task_id}/status",
                        timeout=10.0)
                except Exception:  # noqa: BLE001 — retry until deadline
                    time.sleep(0.1)
                    continue
                if status >= 400:
                    raise RuntimeError(
                        f"result task {loc.task_id} unreachable: "
                        f"{status}")
                info = json.loads(body)
                self._note_task_status(loc.task_id, info)
                state = info.get("state")
                if state == "FINISHED":
                    break
                if state in ("FAILED", "CANCELED"):
                    raise RuntimeError(
                        f"result task {loc.task_id} {state}: "
                        f"{info.get('failure')}")
                time.sleep(0.05)
            for seg in info.get("resultSegments", ()):
                self._segment_workers[seg["id"]] = loc.base_url
                entries.append({
                    **seg,
                    "uri": f"{loc.base_url}/v1/segment/{seg['id']}",
                    "ackUri": f"{base}/v1/segment/{seg['id']}",
                })
        self.result_segments = entries
        self.spooled = "worker-direct"
        self.rows = []
        M.SPOOLED_RESULT_QUERIES.inc(1, "worker-direct")

    def _discard_spooled_result(self) -> None:
        """A statement whose deliverable is NOT the inner query's rows
        (EXPLAIN ANALYZE) ran a query that spooled: release the segments
        now — no manifest will ever reach a client."""
        if self.result_segments is None:
            return
        for e in self.result_segments:
            worker = self._segment_workers.get(e["id"])
            if worker is not None:
                try:
                    wire.http_request(
                        "DELETE", f"{worker}/v1/segment/{e['id']}",
                        timeout=5.0)
                except Exception:  # noqa: BLE001 — TTL is the backstop
                    pass
            elif self.segment_store is not None:
                self.segment_store.discard(e["id"])
        self.result_segments = None
        self.spooled = None

    # ------------------------------------------------------ stats pipeline
    def _note_task_status(self, task_id: str, info: dict) -> None:
        """Record one task-status payload (state + worker-reported stats)
        into the slot map the stage/query rollups read."""
        parts = task_id.split(".")
        try:
            frag = int(parts[-3])
        except (ValueError, IndexError):
            return
        slot = task_id.rsplit(".a", 1)[0]
        entry = {
            "fragment": frag,
            "taskId": task_id,
            "state": info.get("state") or "RUNNING",
            "stats": info.get("stats") or {},
        }

        def progress(e):
            s = e.get("stats") or {}
            return (int(s.get("completedSplits", 0)),
                    int(s.get("inputRows", 0)),
                    int(s.get("outputRows", 0)))

        with self._tstats_lock:
            have = self.task_stats.get(slot)
            # a FINISHED attempt's stats are authoritative for its slot —
            # a late poll of a canceled speculative twin must not clobber
            if (have is not None and have["state"] == "FINISHED"
                    and entry["state"] != "FINISHED"):
                return
            # concurrent attempts (speculation / a retry's create response)
            # share the slot: while neither is FINISHED, keep whichever has
            # made MORE progress, so live numbers never regress or flicker.
            # Dead (FAILED/CANCELED) records never win in either direction:
            # a dead twin must not displace a live attempt's record, and a
            # dead existing record never blocks the live retry — a stage
            # must not read FAILED while an attempt is still running.
            dead = ("FAILED", "CANCELED")
            if (have is not None and have["taskId"] != task_id
                    and entry["state"] in dead
                    and have["state"] not in dead):
                return
            if (have is not None and entry["state"] != "FINISHED"
                    and have["state"] not in dead
                    and have["taskId"] != task_id
                    and progress(entry) < progress(have)):
                return
            self.task_stats[slot] = entry

    def _run_local(self, session, root, path: str, span_name: str,
                   reason: Optional[str] = None) -> None:
        """The coordinator-local execution tail shared by the forced
        local-catalog path and the short-query fast path: run the whole
        plan on this process's engine, record the path, and feed the
        stats rollups through the synthetic local task slot."""
        from trino_tpu.exec.executor import Executor
        from trino_tpu.obs import metrics as M

        self.fast_path = path
        M.FAST_PATH_QUERIES.inc(1, path)
        self.state.set("RUNNING")
        t0 = time.perf_counter()
        with self.tracer.span(span_name) as sp:
            if reason is not None:
                sp.set("reason", reason)
            ex = Executor(session)
            # memory-ledger attribution: the coordinator-local path runs
            # ONE executor per query, so owner mode is exact here (the
            # worker tier attributes at the task level instead)
            ex.memory.owner = f"query:{self.query_id}"
            page = ex.execute_checked(root)
            if reason is not None:
                sp.set("rows", page.live_count())
        self._local_executor = ex  # EXPLAIN ANALYZE annotation source
        self.columns = list(root.column_names)
        # same spool/inline decision as the distributed tail: the
        # protocol choice is plan-shape-independent — a fast-path or
        # local-catalog export spools from the coordinator's own store
        self._materialize_result(session, page)
        self._note_local_stats(ex, time.perf_counter() - t0)
        ex.memory.release()

    def _note_local_stats(self, ex, elapsed_s: float) -> None:
        """Fold a coordinator-local execution's stats into the task-stats
        map so the stage/query rollups, the protocol stats block, and
        ``system.runtime.queries``/``tasks`` cover fast-path queries
        exactly like distributed ones (one synthetic task slot in
        fragment 0 — the coordinator IS that task's worker)."""
        scan_rows = sum(getattr(ex, "scan_stats", {}).values())
        scan_cache = getattr(ex, "scan_cache", {})
        stats = {
            "elapsedS": round(elapsed_s, 6),
            "deviceS": round(sum(
                st.device_s for st in ex.node_stats.values()), 6),
            "completedSplits": max(1, len(getattr(ex, "scan_stats", {}))),
            "totalSplits": max(1, len(getattr(ex, "scan_stats", {}))),
            "inputRows": int(scan_rows),
            "outputRows": self.result_rows(),
            "outputBytes": sum(
                st.output_bytes for st in ex.node_stats.values()),
            "peakBytes": int(ex.memory.peak),
            "spills": len(ex.memory.spills),
            "shedBytes": int(ex.memory.shed_bytes),
            "yieldEvents": int(ex.memory.yields),
            "deviceCacheHits": sum(
                1 for d in scan_cache.values() if d == "hit"),
            "deviceCacheMisses": sum(
                1 for d in scan_cache.values() if d == "miss"),
            "operatorStats": [st.to_dict()
                              for st in ex.node_stats.values()],
        }
        self._note_task_status(f"{self.query_id}.0.local.a0",
                               {"state": "FINISHED", "stats": stats})

    def _sweep_task_stats(self) -> int:
        """One status sweep over every scheduled task (the coordinator's
        status-polling loop body; also the terminal freeze). Tasks whose
        record is already terminal — FINISHED, or FAILED/CANCELED (e.g.
        producers the adaptive re-planner superseded) — are skipped, and
        the timeout is sub-second so one unreachable worker cannot stall
        the live-stats cadence. Returns the number of tasks actually
        polled (the poller's backoff signal)."""
        with self._tstats_lock:
            done = {e["taskId"] for e in self.task_stats.values()
                    if e["state"] in ("FINISHED", "FAILED", "CANCELED")}
        locations = [loc for locs in list(self.fragment_tasks.values())
                     for loc in list(locs)
                     if loc is not None and loc.task_id not in done]
        for loc in locations:
            try:
                status, body, _ = wire.http_request(
                    "GET", f"{loc.base_url}/v1/task/{loc.task_id}/status",
                    timeout=0.8)
                if status < 400:
                    self._note_task_status(loc.task_id, json.loads(body))
            except Exception:  # noqa: BLE001 — a gone worker loses its stats
                pass
        return len(locations)

    STATS_POLL_INTERVAL = 0.25
    STATS_POLL_MAX_BACKOFF = 16.0  # x the base interval

    def _start_stats_poller(self) -> None:
        """Background status poll while the query RUNs, so
        ``GET /v1/query/{id}`` serves LIVE stage/query stats (reference:
        ContinuousTaskStatusFetcher feeding the coordinator's stage state
        machines). Each sleep is JITTERED so many concurrent RUNNING
        queries de-phase instead of hitting every worker in lockstep, and
        a sweep that found nothing left to poll (every slot frozen
        FINISHED — e.g. the root fragment is still draining results)
        backs off exponentially instead of hammering workers with no-op
        status rounds."""

        def poll():
            import random

            backoff = 1.0
            while not self.state.is_terminal():
                polled = self._sweep_task_stats()
                backoff = (min(backoff * 2.0, self.STATS_POLL_MAX_BACKOFF)
                           if polled == 0 else 1.0)
                time.sleep(self.STATS_POLL_INTERVAL * backoff
                           * random.uniform(0.75, 1.25))

        self._stats_poller = threading.Thread(target=poll, daemon=True)
        self._stats_poller.start()

    def stage_stats(self, include_operators: bool = True) -> List[dict]:
        """Per-stage rollups of the latest worker-reported task stats.
        ``include_operators=False`` skips the per-node OperatorStats merge
        for callers that only read the scalar summary (protocol polls,
        UI) — O(tasks) instead of O(tasks × plan nodes)."""
        from trino_tpu.exec.operator_stats import rollup_tasks_to_stage

        with self._tstats_lock:
            entries = [dict(e) for e in self.task_stats.values()]
        by_frag: Dict[int, List[dict]] = {}
        for e in entries:
            by_frag.setdefault(e["fragment"], []).append(e)
        return [rollup_tasks_to_stage(fid, es,
                                      include_operators=include_operators)
                for fid, es in sorted(by_frag.items())]

    def task_records(self) -> List[dict]:
        """Per-slot task records with the assigned worker uri attached —
        the public read surface ``system.runtime.tasks`` materializes from
        (no caller reaches into ``task_stats``/``_tstats_lock``)."""
        url_by_task = {
            loc.task_id: loc.base_url
            for locs in list(self.fragment_tasks.values())
            for loc in list(locs) if loc is not None
        }
        with self._tstats_lock:
            entries = [dict(e) for e in self.task_stats.values()]
        for e in entries:
            e["workerUri"] = url_by_task.get(e["taskId"])
        return entries

    # ------------------------------------------------------- phase ledger
    def worker_spans(self, timeout: float = 3.0) -> List[dict]:
        """Every scheduled task's span dump, fetched in parallel with a
        short timeout (a gone/partitioned worker loses its spans, never
        the whole read). Shared by the trace endpoint and the ledger —
        the completion-path caller passes a tighter timeout because it
        runs BEFORE the terminal state publishes."""
        locations = [loc for locs in list(self.fragment_tasks.values())
                     for loc in list(locs) if loc is not None]
        if not locations:
            return []

        def fetch(loc):
            try:
                status, body, _ = wire.http_request(
                    "GET", f"{loc.base_url}/v1/task/{loc.task_id}/spans",
                    timeout=timeout)
                if status < 400:
                    return json.loads(body).get("spans", ())
            except Exception:  # noqa: BLE001
                pass
            return ()

        spans: List[dict] = []
        pool = self.io_pool
        if pool is not None:
            try:
                for dump in pool.map(fetch, locations):
                    spans.extend(dump)
                return spans
            except RuntimeError:  # pool shut down mid-stop: inline below
                pass
        # no shared pool (bare QueryExecution use): fetch serially — the
        # per-call ThreadPoolExecutor churn this replaced cost more than
        # the fan-in it bought on the hot path
        for loc in locations:
            spans.extend(fetch(loc))
        return spans

    # pre-publication pulls (ledger warm + postmortem capture) run on the
    # query thread BEFORE the terminal state is visible — a blackholed
    # worker must cost ~a second of failure-reporting latency, not the
    # trace endpoint's full on-demand timeout
    COMPLETION_PULL_TIMEOUT = 1.5

    def _warm_timeline(self) -> None:
        """Compute + cache the ledger (requires ``ended_at``); called on
        the query thread right before the terminal transition so state
        listeners — and every later read — get the cached result."""
        if self._timeline is not None or self.ended_at is None:
            return
        try:
            from trino_tpu.obs.timeline import compute_timeline

            spans = (self.tracer.to_dicts() + list(self.extra_spans)
                     + self.worker_spans(
                         timeout=self.COMPLETION_PULL_TIMEOUT))
            self._timeline = compute_timeline(
                spans, self.created_at, self.ended_at)
        except Exception:  # noqa: BLE001 — the ledger is observability,
            pass  # never a reason to fail the terminal transition

    def timeline_dict(self) -> Optional[dict]:
        """The query's phase ledger: None while running, computed ONCE
        from the merged coordinator+worker span tree at terminal and
        cached (normally warmed by the query thread just before the
        terminal transition; a kill/cancel from another thread computes
        here on first read). ``client-drain`` refreshes on every read —
        result pages keep draining after the wall ends."""
        if not self.state.is_terminal() or self.ended_at is None:
            return None
        if self._timeline is None:
            self._warm_timeline()
        tl = self._timeline
        if tl is None:
            return None
        if self.last_drain_at is not None:
            tl.client_drain_s = max(0.0, self.last_drain_at - self.ended_at)
        if self.last_segment_fetch_at is not None:
            # segment fetch/ack activity seen by this coordinator —
            # refreshed per read, like client-drain (outside the wall)
            tl.segment_fetch_s = max(
                0.0, self.last_segment_fetch_at - self.ended_at)
        return tl.to_dict()

    def _timeline_now(self) -> dict:
        """A ledger over the spans recorded SO FAR (EXPLAIN ANALYZE's
        header renders mid-query, before the wall closes)."""
        from trino_tpu.obs.timeline import compute_timeline

        spans = (self.tracer.to_dicts() + list(self.extra_spans)
                 + self.worker_spans())
        return compute_timeline(spans, self.created_at,
                                time.time()).to_dict()

    # ---------------------------------------------------- flight recorder
    def capture_postmortem(self, store: bool = True,
                           timeout: float = 3.0) -> dict:
        """Merge this process's flight-recorder ring with every involved
        worker's (pulled via ``GET /v1/task/{id}/recorder``) into one
        postmortem. Called on FAILED (stored on the execution + shipped
        on QueryCompletedEvent) and on demand by
        ``GET /v1/query/{id}/trace?recorder=1``."""
        from trino_tpu.obs.flightrecorder import pull_worker_rings
        from trino_tpu.obs.memledger import MEMORY_LEDGER

        locations = [loc for locs in list(self.fragment_tasks.values())
                     for loc in list(locs) if loc is not None]
        # the failure-path capture runs BEFORE the FAILED transition is
        # published (fast-listener contract) — a set failure reason means
        # the query IS failing, and the record must say so
        state = self.state.get()
        if self.failure is not None and not self.state.is_terminal():
            state = "FAILED"
        pm = {
            "queryId": self.query_id,
            "state": state,
            "failure": (self.failure or "").split("\n")[0] or None,
            "capturedAt": time.time(),
            "coordinator": {
                "nodeId": getattr(self.recorder, "node_id", "coordinator"),
                "records": (self.recorder.snapshot()
                            if self.recorder is not None else []),
                # memory-ledger snapshot: per-pool live/peak bytes, top
                # consumers by owner, and the last shed events — names
                # WHO was holding memory when the query died
                "memory": MEMORY_LEDGER.memory_snapshot(),
                # device-profiler snapshot: the newest compile-ledger
                # events + utilization counters — a recompile storm
                # preceding the failure is visible right here
                "profiler": _profiler_snapshot(),
                # flow-ledger snapshot: per-link rollups + the last
                # transfers + stall rollups — what was moving (and who
                # was blocked on whom) when the query died
                "flows": _flows_snapshot(),
            },
            "workers": pull_worker_rings(locations, timeout=timeout,
                                         pool=self.io_pool),
        }
        if store:
            self.postmortem = pm
        return pm

    def query_stats(self, stages: Optional[List[dict]] = None) -> dict:
        """Query-level rollup: live while RUNNING, frozen at terminal.
        Pass precomputed ``stages`` to avoid re-rolling the task map when
        the caller already has them (info(), the UI page)."""
        from trino_tpu.exec.operator_stats import rollup_stages_to_query

        qs = rollup_stages_to_query(
            self.stage_stats() if stages is None else stages)
        end = (self.ended_at
               if self.state.is_terminal() and self.ended_at else time.time())
        qs["elapsedMs"] = int((end - self.created_at) * 1000)
        qs["state"] = self.state.get()
        qs["cacheStatus"] = self.cache_status
        # which resource group admitted this query (None under a legacy
        # injected gate) — clients (CLI summary tag) and system tables
        qs["resourceGroup"] = self.resource_group
        # which control-plane path served the SELECT (fast-path /
        # distributed / local-catalog), for clients and system tables
        qs["fastPath"] = self.fast_path
        qs["resultRows"] = self.result_rows()
        # spooled result protocol: which producer wrote the segments
        # (None = inline rows) + the manifest's footprint, for clients
        # (CLI summary) and system tables
        qs["spooled"] = self.spooled
        if self.result_segments is not None:
            qs["resultSegments"] = len(self.result_segments)
            qs["resultSegmentBytes"] = sum(
                int(e.get("bytes", 0)) for e in self.result_segments)
        # adaptive plan changes applied so far — rides every statement
        # response so clients can render "[adapted: N]" live
        qs["adaptations"] = len(self.plan_versions)
        # materialized-view substitutions in this query's plan (CLI
        # prints "mv: <name>"; 0/absent when nothing matched fresh)
        qs["mvHits"] = len(self.mv_substitutions)
        if self.mv_substitutions:
            qs["mvNames"] = list(self.mv_substitutions)
        # the phase ledger (obs/timeline.py): per-phase exclusive wall +
        # unattributed residual, None until the query is terminal
        qs["timeline"] = self.timeline_dict()
        # the memory block: peak by pool plus what was shed/yielded on
        # this query's behalf (cluster memory ledger read surface — the
        # CLI summary tag and system.runtime.queries columns feed here)
        qs["memory"] = {
            "peakBytes": int(qs.get("peakBytes") or 0),
            "shedBytes": int(qs.get("shedBytes") or 0),
            "yieldEvents": int(qs.get("yieldEvents") or 0),
            "spills": int(qs.get("spills") or 0),
        }
        # the data-plane block (flow ledger): drain throughput for the
        # CLI summary tag + the straggler count, absent on any ledger
        # hiccup rather than failing a stats poll
        try:
            qs["flows"] = self.flow_stats_block()
        except Exception:  # noqa: BLE001 — observability only
            pass
        return qs

    def flow_stats_block(self) -> dict:
        """The ``stats.flows`` block of the statement protocol: this
        query's client-drain rollup (bytes + effective MB/s) and the
        straggler count. Re-read by ``_drain_body`` on the final result
        page so the CLI summary includes that response's own bytes."""
        from trino_tpu.obs.flowledger import FLOW_LEDGER

        owner = f"drain:{self.query_id}"
        drain_bytes = 0
        drain_s = 0.0
        for r in FLOW_LEDGER.transfer_rows():
            if r["owner"] == owner:
                drain_bytes += r["bytes"]
                drain_s += r["seconds"]
        return {
            "drainBytes": drain_bytes,
            "drainMbPerS": (round(drain_bytes / drain_s / 1e6, 3)
                            if drain_s > 0 else None),
            "stragglers": len(self.straggler_rows()),
        }

    # ---------------------------------------------------- device profiler
    def kernel_rows_live(self) -> List[dict]:
        """This query's merged kernel-ledger rows (obs/devprofiler.py):
        worker rows from the task records (stamped with the assigned
        worker uri), coordinator rows from the local/root executors.
        Live while RUNNING — the same merge the terminal fold persists."""
        from trino_tpu.obs.devprofiler import merge_kernel_rows

        merged: Dict[tuple, dict] = {}
        # adaptive re-planner: superseded fragments re-ran as copies with
        # the same plan-node ids — keep them out, exactly like the
        # EXPLAIN ANALYZE operator merge
        superseded = {fid for ch in self.plan_versions
                      for fid in ch.get("supersedes", ())}
        for rec in self.task_records():
            if rec.get("fragment") in superseded:
                continue
            node = rec.get("workerUri") or "coordinator"
            rows = (rec.get("stats") or {}).get("kernelStats") or []
            merge_kernel_rows(merged, [
                dict(r, nodeId=r.get("nodeId") or node) for r in rows])
        for ex in (getattr(self, "_local_executor", None),
                   getattr(self, "_root_executor", None)):
            if ex is None:
                continue
            merge_kernel_rows(merged, [
                dict(r, nodeId="coordinator")
                for r in getattr(ex, "kernel_stats", {}).values()])
        rows = []
        for k in sorted(merged):
            row = dict(merged[k])
            row["queryId"] = self.query_id
            row["dispatchOverheadS"] = round(
                max(0.0, row["wallS"] - row["deviceS"]), 6)
            rows.append(row)
        return rows

    def fold_kernel_profile(self) -> None:
        """Persist the merged kernel rows into the process device
        profiler ONCE at terminal (the ``system.runtime.kernels`` store;
        per-operator launch/overhead metrics bump here, never
        per-dispatch)."""
        if getattr(self, "_kernels_folded", False):
            return
        self._kernels_folded = True
        from trino_tpu.obs.devprofiler import DEVICE_PROFILER

        rows = self.kernel_rows_live()
        if rows:
            DEVICE_PROFILER.record_query_kernels(self.query_id, rows)

    def profile_dict(self) -> dict:
        """The ``GET /v1/query/{id}/profile`` payload: merged kernel
        rows, this query's compile-ledger events, the phase ledger, and
        recent utilization samples from the coordinator's profiler."""
        from trino_tpu.obs.devprofiler import DEVICE_PROFILER

        folded = getattr(self, "_kernels_folded", False)
        kernels = (DEVICE_PROFILER.kernel_rows(self.query_id)
                   if folded else self.kernel_rows_live())
        return {
            "queryId": self.query_id,
            "state": self.state.get(),
            "kernels": kernels,
            "compiles": DEVICE_PROFILER.compile_rows(
                query_id=self.query_id),
            "utilization": DEVICE_PROFILER.utilization_rows(limit=8),
            "counters": DEVICE_PROFILER.counters(),
            "timeline": self.timeline_dict(),
        }

    # ------------------------------------------------------- flow ledger
    def _owns_flow(self, owner: str) -> bool:
        """Does a flow-ledger rollup owner belong to this query? Owners
        are ``task:{qid}.{frag}.{slot}.a{n}``, ``query:{qid}`` (spool
        writes / segment fetches) and ``drain:{qid}`` (client drain)."""
        return (owner == f"query:{self.query_id}"
                or owner == f"drain:{self.query_id}"
                or owner.startswith(f"task:{self.query_id}."))

    def _straggler_multiple(self) -> float:
        """The ``straggler_multiple`` session property (elapsed must
        exceed this multiple of the stage median to flag); malformed
        values fall back to the ledger default."""
        from trino_tpu.obs.flowledger import DEFAULT_STRAGGLER_MULTIPLE

        try:
            return float(self.session_properties.get(
                "straggler_multiple", DEFAULT_STRAGGLER_MULTIPLE))
        except (TypeError, ValueError):
            return DEFAULT_STRAGGLER_MULTIPLE

    def flow_rows_live(self) -> List[dict]:
        """This query's per-link transfer rollups, merged cluster-wide:
        worker rows ride the announce payload (``flows``), the
        coordinator contributes its own process ledger directly. A
        worker ledger sharing this process (in-process test clusters
        stamp the global ledger with the first server's id) is NOT
        double-reported: announce rows win for that node id — the
        kernel/memory ledger fold pattern."""
        from trino_tpu.obs.flowledger import FLOW_LEDGER

        rows = []
        announced = set()
        for n in self.registry.snapshot():
            flows = (n.get("info") or {}).get("flows")
            if flows is None:
                continue
            announced.add(n["nodeId"])
            rows.extend(dict(r, nodeId=n["nodeId"]) for r in flows
                        if self._owns_flow(str(r.get("owner", ""))))
        nid = FLOW_LEDGER.node_id or "coordinator"
        if nid not in announced:
            rows.extend(dict(r, nodeId=nid)
                        for r in FLOW_LEDGER.transfer_rows()
                        if self._owns_flow(r["owner"]))
        return rows

    def straggler_rows(self) -> List[dict]:
        """Straggler verdicts over this query's task records: frozen at
        terminal by :meth:`fold_flow_profile`, detected live while
        RUNNING (same live/folded split as the kernel rows)."""
        folded = getattr(self, "_stragglers", None)
        if folded is not None:
            return folded
        from trino_tpu.obs.flowledger import detect_stragglers

        return detect_stragglers(self.task_records(),
                                 multiple=self._straggler_multiple())

    def fold_flow_profile(self) -> None:
        """Freeze the straggler verdicts ONCE at terminal and bump the
        per-cause straggler counter (metrics fire at query end, never
        per stats poll)."""
        if getattr(self, "_flows_folded", False):
            return
        self._flows_folded = True
        self._stragglers = self.straggler_rows()
        if self._stragglers:
            from trino_tpu.obs import metrics as M

            for f in self._stragglers:
                M.STRAGGLER_TASKS.inc(1, f["cause"])

    def flows_dict(self) -> dict:
        """The ``GET /v1/query/{id}/flows`` payload: this query's
        cluster-merged per-link rows, the straggler verdicts, and the
        process backpressure stall rollups (stage-labelled; the stall
        series is process-scoped like the metrics registry)."""
        from trino_tpu.obs.flowledger import FLOW_LEDGER

        return {
            "queryId": self.query_id,
            "state": self.state.get(),
            "transfers": self.flow_rows_live(),
            "stragglers": self.straggler_rows(),
            "stalls": FLOW_LEDGER.stall_rows(),
            "net": FLOW_LEDGER.net_totals(),
        }

    def _explain_analyze(self, session, stmt) -> str:
        """Distributed EXPLAIN ANALYZE: plan, execute through the real
        fragment/schedule path, then render the fragments with the
        coordinator's rolled-up per-node worker stats injected (reference:
        PlanPrinter.textDistributedPlan with stats)."""
        import time as _time

        from trino_tpu.exec.operator_stats import (
            merge_operator_dicts, wall_time_header)
        from trino_tpu.sql.planner.fragmenter import format_fragments
        from trino_tpu.sql.planner.optimizer import optimize
        from trino_tpu.sql.planner.planner import Planner

        inner = stmt.statement
        udfs = getattr(session, "udfs", None)
        if udfs:
            from trino_tpu.sql.routines import expand_udfs

            inner = expand_udfs(inner, udfs)
        t_plan = _time.perf_counter()
        with tracing.span("analyze/plan"):
            root = Planner(session).plan(inner)
        with tracing.span("optimize"):
            root = optimize(root, session)
        root, _versions = self._substitute_matviews(session, root, None)
        plan_s = _time.perf_counter() - t_plan
        t_exec = _time.perf_counter()
        self._execute_query(session, root)
        exec_s = _time.perf_counter() - t_exec
        header = [wall_time_header(plan_s, exec_s)]
        from trino_tpu.exec.query import mv_notes_header

        mv_lines = mv_notes_header(self.mv_notes)
        if mv_lines:
            header.extend(mv_lines.rstrip("\n").split("\n"))
        # the phase ledger over the spans recorded so far (the EXPLAIN
        # query itself is still running while this renders)
        from trino_tpu.obs.timeline import summarize as summarize_timeline

        ledger = summarize_timeline(self._timeline_now())
        if ledger:
            header.append(f"Phase ledger: {ledger}")
        if self.fragments is None:
            # process-local catalogs / fast-path queries executed on the
            # coordinator's own engine: annotate from that executor,
            # exactly the local path — with the path decision on display
            from trino_tpu.sql.planner.plan import format_plan

            ex = getattr(self, "_local_executor", None)
            if self.fast_path == "fast-path":
                header.append(
                    "Fast path: coordinator-local ("
                    + getattr(self, "fast_path_reason", "short query") + ")")
            header.append(
                f"Peak working set: "
                f"{(ex.memory.peak if ex else 0) // 1024}KiB (coordinator)")
            return "\n".join(header) + "\n" + format_plan(
                root, executor=ex, verbose=stmt.verbose)
        # _execute_query already swept terminal task stats before FINISHING
        stages = self.stage_stats()
        stage_by_id = {s["stageId"]: s for s in stages}
        # fragments the adaptive re-planner superseded re-ran as COPIES
        # with the same plan-node ids — merging both runs would double
        # every per-node annotation, so the superseded stage's operators
        # stay out of the merge (its own [adapted: superseded] fragment
        # header still shows its stage totals)
        superseded = {fid for ch in self.plan_versions
                      for fid in ch.get("supersedes", ())}
        with self._tstats_lock:
            op_lists = [e["stats"].get("operatorStats")
                        for e in self.task_stats.values()
                        if e["fragment"] not in superseded]
        # the root single fragment ran on the coordinator itself — its
        # executor's stats complete the tree (that is its assigned worker,
        # not a re-execution)
        root_ex = getattr(self, "_root_executor", None)
        if root_ex is not None:
            op_lists.append(
                [st.to_dict() for st in root_ex.node_stats.values()])
        node_stats = merge_operator_dicts(op_lists)
        qs = self.query_stats(stages)
        header.append(
            f"Stages: {len(stages)} scheduled + 1 coordinator,"
            f" splits: {qs['completedSplits']}/{qs['totalSplits']},"
            f" input rows: {qs['totalRows']},"
            f" peak task memory: {qs['peakBytes'] // 1024}KiB,"
            f" spills: {qs['spills']}")
        if qs.get("shedBytes"):
            header.append(
                f"Memory pressure: {qs['shedBytes'] // 1024}KiB shed from "
                f"revocable caches across {qs.get('yieldEvents', 0)} "
                f"yield event(s)")
        # per-node peak annotation (memory ledger): the MAX task peak
        # each worker reached for this query — spots the skewed node a
        # cluster-wide rollup hides
        node_peaks: Dict[str, int] = {}
        for rec in self.task_records():
            node = rec.get("workerUri") or "coordinator"
            pb = int((rec.get("stats") or {}).get("peakBytes") or 0)
            if pb > node_peaks.get(node, 0):
                node_peaks[node] = pb
        if node_peaks:
            header.append("Peak task memory by node: " + ", ".join(
                f"{node} {pb // 1024}KiB"
                for node, pb in sorted(node_peaks.items())))
        # data-flow annotations (flow ledger): per-link bytes + effective
        # throughput for this query, then any straggler verdicts with
        # their dominant cause — the skewed node reads right here
        try:
            by_link: Dict[str, list] = {}
            for r in self.flow_rows_live():
                agg = by_link.setdefault(r["link"], [0, 0.0])
                agg[0] += int(r["bytes"])
                agg[1] += float(r["seconds"])
            if by_link:
                header.append("Data flow: " + ", ".join(
                    f"{link} {b / 1e6:.1f}MB"
                    + (f" @ {b / s / 1e6:.1f}MB/s" if s > 0 else "")
                    for link, (b, s) in sorted(by_link.items())))
            for f in self.straggler_rows():
                header.append(
                    f"Straggler: task {f['taskId']} {f['elapsedS']:.2f}s"
                    f" vs stage median {f['stageMedianS']:.2f}s"
                    f" ({f['ratio']:.1f}x, {f['cause']})")
        except Exception:  # noqa: BLE001 — annotations are observability
            pass
        # kernel-ledger annotations (device profiler): VERBOSE prints a
        # per-node launches=/dispatch_overhead= line from the merged rows
        kern = None
        if stmt.verbose:
            from trino_tpu.sql.planner.plan import kernel_annotations

            kern = kernel_annotations(self.kernel_rows_live())
        return "\n".join(header) + "\n" + format_fragments(
            self.fragments, stats=node_stats, stage_stats=stage_by_id,
            verbose=stmt.verbose, adapted=self._adapted_notes(),
            kernels=kern)

    def _schedule(self, session, fragments, workers) -> None:
        """Create one task per worker for each source fragment, splits
        round-robin across workers (SOURCE_DISTRIBUTION placement)."""
        # Declared consumer set per producing fragment (reference:
        # OutputBuffers): a fragment consumed by a source fragment is pulled
        # by every one of its tasks (broadcast — one buffer id per task); a
        # fragment consumed by the root single fragment has one consumer
        # (the coordinator's exchange client).
        consumer_counts: Dict[int, int] = {}
        for frag in fragments:
            for node in P.walk_plan(frag.root):
                if isinstance(node, RemoteSourceNode):
                    consumer_counts[node.fragment_id] = (
                        len(workers)
                        if frag.partitioning in ("source", "hash") else 1)
        fte = str(self.session_properties.get("retry_policy", "NONE")).upper() == "TASK"
        if fte:
            from trino_tpu.server.task import spool_directory

            if spool_directory() is None:
                # the retry contract needs durable outputs (reference: TASK
                # retry requires a configured exchange manager)
                raise RuntimeError(
                    "retry_policy=TASK requires the spooled exchange: set "
                    "TRINO_TPU_SPOOL_DIR to a cluster-shared directory")
        # Phased execution (reference: scheduler/policy/
        # PhasedExecutionSchedule): a fragment whose JOIN BUILD side is fed
        # by a leaf (scan-only) fragment does not schedule until that build
        # fragment's tasks finished executing (>= FLUSHING) — probe-side
        # tasks then never sit on workers holding memory while builds
        # compute. Leaf-only gating is deliberate: a build fragment that is
        # itself a consumer may park on its own output watermark before
        # FLUSHING, and gating on it could deadlock the pipeline.
        # wire-protocol values arrive as header STRINGS: normalize like the
        # typed property registry would ("false"/"0" disable)
        phased = str(self.session_properties.get(
            "phased_execution", True)).lower() not in ("false", "0", "no")
        by_id = {f.id: f for f in fragments}
        build_deps: Dict[int, List[int]] = {}
        for frag in fragments:
            deps = []
            for node in P.walk_plan(frag.root):
                if isinstance(node, P.JoinNode) and isinstance(
                        node.right, RemoteSourceNode):
                    dep = by_id.get(node.right.fragment_id)
                    if dep is not None and not any(
                            isinstance(n, RemoteSourceNode)
                            for n in P.walk_plan(dep.root)):
                        deps.append(dep.id)
            if deps:
                build_deps[frag.id] = deps
        self.phase_waits = []  # (fragment, [deps]) log for tests/EXPLAIN
        # adaptive execution (trino_tpu/adaptive/): between stage
        # completions, the re-planner may rewrite a fragment whose tasks
        # don't exist yet — this is the stage-boundary hook of the
        # reference's AdaptivePlanner, placed after the phased-execution
        # build waits so completed-build actuals are available
        adaptive = self._make_adaptive_planner(session, fragments, workers)
        for frag in list(fragments):
            if phased and not fte and frag.id in build_deps:
                self._await_build_fragments(build_deps[frag.id])
                self.phase_waits.append((frag.id, build_deps[frag.id]))
            if adaptive is not None and frag.partitioning != "single":
                for nf in self._adapt_fragment(
                        adaptive, frag, by_id, fragments, consumer_counts,
                        workers):
                    self._schedule_fragment(
                        session, nf, workers, consumer_counts, fte)
            self._schedule_fragment(session, frag, workers, consumer_counts,
                                    fte)

    def _schedule_fragment(self, session, frag, workers, consumer_counts,
                           fte) -> None:
        """Create the tasks of ONE fragment (source or hash partitioning;
        the root single fragment executes on the coordinator instead)."""
        if frag.partitioning == "hash":
            # one task per key partition (hash-distributed final
            # aggregations and co-partitioned joins): task i pulls
            # buffer/partition i from every upstream producer. Under
            # FTE these tasks retry like source tasks — their inputs
            # are durable per-partition spool files.
            if fte:
                self.fragment_tasks[frag.id] = self._run_fragment_fte(
                    frag, [dict() for _ in workers], workers,
                    consumer_counts)
            else:
                self.fragment_tasks[frag.id] = [
                    self._create_task(frag, wi, 0, {}, workers[wi],
                                      consumer_counts)
                    for wi in range(len(workers))
                ]
            return
        if frag.partitioning != "source":
            return
        # enumerate splits per scan node, interleave across workers
        from trino_tpu.exec import staging as _staging

        per_worker_splits: List[Dict[int, list]] = [dict() for _ in workers]
        scan_nodes = [n for n in P.walk_plan(frag.root)
                      if isinstance(n, P.TableScanNode)]
        for node in scan_nodes:
            conn = session.catalogs[node.catalog]
            floor = max(len(workers), 1)
            # adaptive split sizing (exec/staging.py): big tables fan out
            # finer than one-split-per-worker so task-side staging
            # pipelines over them — but ONLY for single-scan fragments:
            # a multi-scan fragment may be a co-located join whose
            # correctness depends on split i of both tables covering the
            # SAME key range (pushdown handles are guarded inside
            # target_split_count)
            target = floor
            if len(scan_nodes) == 1:
                target = _staging.target_split_count(
                    session, conn, node.schema, node.table, floor=floor,
                    handle=node.table_handle)
            splits = conn.get_splits(node.schema, node.table, target,
                                     constraint=node.constraint,
                                     handle=node.table_handle)
            for i, split in enumerate(splits):
                w = i % len(workers)
                per_worker_splits[w].setdefault(node.id, []).append(split)
        if fte:
            self.fragment_tasks[frag.id] = self._run_fragment_fte(
                frag, per_worker_splits, workers, consumer_counts)
        else:
            self.fragment_tasks[frag.id] = [
                self._create_task(
                    frag, wi, 0, per_worker_splits[wi], workers[wi],
                    consumer_counts)
                for wi in range(len(workers))
            ]

    # ------------------------------------------------- adaptive execution
    def _make_adaptive_planner(self, session, fragments, workers):
        """The per-query AdaptivePlanner, or None when adaptive execution
        is off (adaptive_execution_enabled session property)."""
        props = getattr(session, "properties", None) or {}
        if not bool(props.get("adaptive_execution_enabled", True)):
            return None
        from trino_tpu.adaptive import AdaptivePlanner, RuntimeStatsProvider
        from trino_tpu.sql.planner.fragmenter import fresh_fragment_ids

        def entries():
            with self._tstats_lock:
                return [dict(e) for e in self.task_stats.values()]

        provider = RuntimeStatsProvider(
            entries, sweep_fn=self._sweep_task_stats,
            expected_tasks_fn=lambda fid: len(
                self.fragment_tasks.get(fid, ())))
        return AdaptivePlanner(session, provider, len(workers),
                               fresh_fragment_ids(fragments))

    def _adapt_fragment(self, planner, frag, by_id, fragments,
                        consumer_counts, workers):
        """Run the adaptive rules against one not-yet-scheduled fragment;
        record every applied change as a versioned plan change (info(),
        EXPLAIN ANALYZE annotations, plan/adapt span, adaptive metrics),
        cancel superseded producer tasks, and return the new producer
        fragments to schedule first. Adaptation failures are recorded and
        swallowed — a stats-driven optimization must never fail a query
        that would have run fine unadapted — and rules are isolated from
        each other inside the planner, so a failing rule never discards an
        earlier rule's applied (and audited) change."""
        from trino_tpu.obs import metrics as M

        try:
            new_frags, changes, errors = planner.adapt_fragment(frag, by_id)
        except Exception as e:  # noqa: BLE001 — adaptivity is best-effort
            new_frags, changes, errors = [], [], [str(e)]
        for err in errors:
            with self.tracer.span("plan/adapt", fragment=frag.id) as sp:
                sp.set("error", str(err)[:300])
        for ch in changes:
            self.plan_versions.append(ch.to_dict())
            with self.tracer.span("plan/adapt", fragment=ch.fragment) as sp:
                sp.set("rule", ch.rule)
                sp.set("version", ch.version)
                sp.set("description", ch.description)
            M.ADAPTIVE_ADAPTATIONS.inc(1, ch.rule)
            if ch.rule == "join-distribution":
                direction = ("to_partitioned"
                             if ch.description.endswith("partitioned")
                             else "to_broadcast")
                M.ADAPTIVE_JOIN_FLIPS.inc(1, direction)
            elif ch.rule == "capacity-reseed":
                M.ADAPTIVE_RESEEDED_SOURCES.inc(
                    len(ch.detail.get("runtimeRows", {})))
            elif ch.rule == "skew-mitigation":
                M.ADAPTIVE_SKEW_HOT_PARTITIONS.inc(
                    len(ch.detail.get("hotPartitions", ())))
            # the rewrite re-runs superseded producers with a new output
            # shape; the originals' tasks only hold buffers nobody will
            # pull — cancel them (their frozen stats keep the record)
            for fid in ch.supersedes:
                for loc in self.fragment_tasks.get(fid, ()):
                    self._cancel_attempt(loc)
        for nf in new_frags:
            consumer_counts[nf.id] = len(workers)
            fragments.insert(fragments.index(frag), nf)
        return new_frags

    def _adapted_notes(self) -> Dict[int, str]:
        """fragment id -> change description, for the EXPLAIN ANALYZE
        ``[adapted: ...]`` annotations."""
        notes: Dict[int, str] = {}
        for ch in self.plan_versions:
            notes[ch["fragment"]] = ch["description"]
            for fid in ch.get("newFragments", ()):
                notes.setdefault(fid, ch["description"])
            for fid in ch.get("supersedes", ()):
                notes[fid] = "superseded"
        return notes

    MAX_TASK_ATTEMPTS = 3

    def _create_task(self, frag, wi: int, attempt: int, splits, worker,
                     consumer_counts) -> TaskLocation:
        task_id = f"{self.query_id}.{frag.id}.{wi}.a{attempt}"
        req = TaskRequest(
            task_id=task_id,
            query_id=self.query_id,
            fragment_root=frag.root,
            splits=splits,
            upstream=self._upstream_for(frag.root, consumer_index=wi),
            session_properties=self.session_properties,
            consumer_count=consumer_counts.get(frag.id, 1),
            output_partition_channels=getattr(
                frag, "output_partition_channels", None),
            skew_spread_partitions=getattr(
                frag, "skew_spread_partitions", None),
            skew_replicate_partitions=getattr(
                frag, "skew_replicate_partitions", None),
            spool_results=getattr(frag, "spool_results", False),
        )
        # trace-context propagation: the worker parents its task span under
        # the coordinator's current (schedule) span via this header
        status, resp, _ = wire.http_request(
            "POST", f"{worker['url']}/v1/task/{task_id}", req.to_bytes(),
            headers={tracing.TRACEPARENT_HEADER: self.tracer.traceparent()})
        if status >= 400:
            raise RuntimeError(
                f"task create failed on {worker['nodeId']}: "
                f"{resp[:300].decode(errors='replace')}")
        # the create response IS a task-info payload: seed the stats slot
        # immediately so totalSplits is known while the task still runs
        try:
            self._note_task_status(task_id, json.loads(resp))
        except Exception:  # noqa: BLE001 — stats seeding is best-effort
            pass
        return TaskLocation(worker["url"], task_id)

    TASK_ATTEMPT_TIMEOUT = 600.0

    def _run_fragment_fte(self, frag, per_worker_splits, workers,
                          consumer_counts) -> List[TaskLocation]:
        """Fault-tolerant stage execution (reference:
        EventDrivenFaultTolerantQueryScheduler.java:201): all of a stage's
        tasks run CONCURRENTLY; the stage barrier is that every task must
        FINISH (output spooled) before consumers schedule. A failed/
        unreachable/timed-out attempt is canceled (best effort) and retried
        on the next worker — upstreams are never recomputed because their
        outputs persist in the spool."""
        n = len(workers)
        locations: List[Optional[TaskLocation]] = [None] * n
        # per slot: LIST of concurrent attempts (attempt#, loc, deadline,
        # started) — normally one; a straggler gets a SPECULATIVE second
        # (reference: the event-driven FTE scheduler's speculative
        # execution — launch a duplicate of a slow task, first finish wins)
        slots: Dict[int, list] = {}
        top_attempt: Dict[int, int] = {}
        for wi in range(n):
            slots[wi] = [self._start_attempt(
                frag, wi, 0, per_worker_splits, workers, consumer_counts)]
            top_attempt[wi] = 0
        finished_durations: List[float] = []

        def fail_all(msg):
            for atts in slots.values():
                for _a, other, _dl, _t in atts:
                    self._cancel_attempt(other)
                    self._prune_speculative(other)
            raise RuntimeError(msg)

        while slots:
            if self.state.get() == "CANCELED":
                fail_all("query was canceled")
            for wi in list(slots):
                for att in list(slots[wi]):
                    attempt, loc, deadline, started = att
                    state, failure = self._poll_task(loc, deadline)
                    if state is None:
                        continue  # still running
                    if state == "FINISHED":
                        locations[wi] = loc
                        self.task_attempts[loc.task_id] = attempt
                        finished_durations.append(time.monotonic() - started)
                        for _a, other, _dl, _t in slots[wi]:
                            if other is not loc:
                                self._cancel_attempt(other)  # losers
                            self._prune_speculative(other)
                        del slots[wi]
                        break
                    # failed / unreachable / timed out / canceled remotely
                    self._cancel_attempt(loc)
                    self._prune_speculative(loc)
                    if loc is not None:
                        self.retried_tasks.append(loc.task_id)
                    slots[wi].remove(att)
                    if not slots[wi]:
                        if top_attempt[wi] + 1 >= self.MAX_TASK_ATTEMPTS:
                            fail_all(
                                f"task {frag.id}.{wi} failed after "
                                f"{self.MAX_TASK_ATTEMPTS} attempts: {failure}")
                        top_attempt[wi] += 1
                        slots[wi] = [self._start_attempt(
                            frag, wi, top_attempt[wi], per_worker_splits,
                            workers, consumer_counts)]
            # speculation: once siblings establish a duration baseline, a
            # slot still on its FIRST running attempt past factor x median
            # gets a duplicate on a different worker
            if finished_durations and slots:
                med = sorted(finished_durations)[len(finished_durations) // 2]
                threshold = max(self.SPECULATION_MIN_S,
                                self.SPECULATION_FACTOR * med)
                now = time.monotonic()
                for wi, atts in slots.items():
                    if len(atts) != 1:
                        continue  # already speculating (or mid-restart)
                    attempt, loc, _dl, started = atts[0]
                    if attempt != 0:
                        continue  # retried slots keep their attempt budget
                    if loc is None or now - started < threshold:
                        continue
                    if top_attempt[wi] + 1 >= self.MAX_TASK_ATTEMPTS:
                        continue
                    top_attempt[wi] += 1
                    spec = self._start_attempt(
                        frag, wi, top_attempt[wi], per_worker_splits,
                        workers, consumer_counts)
                    atts.append(spec)
                    if spec[1] is not None:
                        self.speculative_tasks.append(spec[1].task_id)
                        self.speculation_history.append(spec[1].task_id)
            time.sleep(0.05)
        return list(locations)

    # speculative-execution policy: duplicate a slot's first attempt when
    # it has run SPECULATION_FACTOR x the median sibling duration (and at
    # least SPECULATION_MIN_S)
    SPECULATION_MIN_S = 2.0
    SPECULATION_FACTOR = 2.0

    def _start_attempt(self, frag, wi, attempt, per_worker_splits, workers,
                       consumer_counts):
        """Create one attempt; creation failure (dead worker at POST) is a
        normal retryable outcome, represented as a slot with loc=None."""
        worker = workers[(wi + attempt) % len(workers)]
        deadline = time.monotonic() + self.TASK_ATTEMPT_TIMEOUT
        try:
            loc = self._create_task(
                frag, wi, attempt, per_worker_splits[wi], worker,
                consumer_counts)
        except Exception:  # noqa: BLE001 — retried like a task failure
            loc = None
        return (attempt, loc, deadline, time.monotonic())

    def _poll_task(self, loc: Optional[TaskLocation], deadline: float):
        """One non-blocking status check: (None, None) while running, else
        (terminal_state, failure)."""
        if loc is None:
            return "FAILED", "task creation failed (worker unreachable)"
        if time.monotonic() > deadline:
            return "FAILED", "task attempt timeout"
        try:
            status, body, _ = wire.http_request(
                "GET", f"{loc.base_url}/v1/task/{loc.task_id}/status",
                timeout=10.0)
        except Exception as e:  # noqa: BLE001 — worker gone counts as failed
            return "FAILED", f"status poll failed: {e}"
        if status >= 400:
            return "FAILED", f"status {status}"
        info = json.loads(body)
        self._note_task_status(loc.task_id, info)
        if info["state"] in ("FINISHED", "FAILED", "CANCELED"):
            return info["state"], info.get("failure")
        return None, None

    def _prune_speculative(self, loc: Optional[TaskLocation]) -> None:
        """Drop a resolved attempt from the in-flight speculation list (the
        speculated task — or the original it duplicated — completed); the
        bounded ``speculation_history`` keeps the record."""
        if loc is not None and loc.task_id in self.speculative_tasks:
            self.speculative_tasks.remove(loc.task_id)

    @staticmethod
    def _cancel_attempt(loc: Optional[TaskLocation]) -> None:
        """Best-effort cancel of a superseded/orphaned attempt so it stops
        consuming worker resources alongside its replacement."""
        if loc is None:
            return
        try:
            wire.http_request(
                "DELETE", f"{loc.base_url}/v1/task/{loc.task_id}", timeout=5.0)
        except Exception:  # noqa: BLE001
            pass

    def _upstream_for(self, root, consumer_index: int = 0) -> Dict[int, list]:
        up: Dict[int, list] = {}
        for node in P.walk_plan(root):
            if isinstance(node, RemoteSourceNode):
                locs = self.fragment_tasks.get(node.fragment_id, [])
                up[node.fragment_id] = [
                    (l.base_url, l.task_id, consumer_index) for l in locs]
        return up

    def _run_root_fragment(self, session, fragments):
        from trino_tpu.exec.memory import page_bytes
        from trino_tpu.obs import metrics as M
        from trino_tpu.server.task import FragmentExecutor

        root_frag = fragments[-1]
        assert root_frag.partitioning == "single"
        # inline-result memory guard, applied DURING the gather: with
        # spooling unavailable, a result past inline_result_max_bytes
        # fails while pulling — before the coordinator has accumulated
        # the whole columnar result in process memory (the post-gather
        # check in _materialize_result only bounds the Python-row
        # blowup). Scoped to the pass-through root shape, where gather
        # bytes == result bytes exactly — a reducing root (single-step
        # aggregation over gathered raw rows) may legitimately gather
        # far more than it outputs. With spooling enabled there is no
        # gather cap: the page is spooled from here, holding
        # ~wire-sized arrays once.
        budget = None
        if (self._spool_config(session) is None
                and self._gather_passthrough(root_frag) is not None):
            budget = int(session.properties.get(
                "inline_result_max_bytes", 256 << 20))
        remote_pages: Dict[int, list] = {}
        for node in P.walk_plan(root_frag.root):
            if isinstance(node, RemoteSourceNode):
                # flow-ledger attribution: the coordinator's root gather
                # is this query's exchange pull (the "task:{qid}." owner
                # prefix groups it with the workers' task pulls)
                client = ExchangeClient(self.fragment_tasks[node.fragment_id],
                                        tracer=self.tracer,
                                        owner=f"task:{self.query_id}.root",
                                        stall_key=(root_frag.id, None))
                client.start()
                if budget is None:
                    remote_pages[node.fragment_id] = client.pages()
                    continue
                pages, gathered = [], 0
                for p in client.iter_pages():
                    gathered += page_bytes(p)
                    if gathered > budget:
                        M.INLINE_RESULT_REJECTIONS.inc()
                        raise RuntimeError(
                            f"gathered result exceeds "
                            f"inline_result_max_bytes={budget} while "
                            "pulling the root fragment's input "
                            "(INLINE_RESULT_TOO_LARGE) — enable "
                            "spooled_results_enabled to serve it as a "
                            "spooled segment manifest, or narrow the "
                            "query")
                    pages.append(p)
                remote_pages[node.fragment_id] = pages
        ex = FragmentExecutor(session, {}, remote_pages)
        self._root_executor = ex  # EXPLAIN ANALYZE: the root stage's stats
        return ex.execute_checked(root_frag.root)

    PHASE_WAIT_TIMEOUT = 300.0

    def _await_build_fragments(self, dep_ids) -> None:
        """Block until every task of the given (already-scheduled) build
        fragments reports FLUSHING or later — its body is done and its
        output is buffered/spooled, so probe tasks can start pulling
        immediately (reference: PhasedExecutionSchedule's stage phases)."""
        deadline = time.monotonic() + self.PHASE_WAIT_TIMEOUT
        for fid in dep_ids:
            for loc in self.fragment_tasks.get(fid, ()):
                while time.monotonic() < deadline:
                    try:
                        status, body, _ = wire.http_request(
                            "GET",
                            f"{loc.base_url}/v1/task/{loc.task_id}/status",
                            timeout=10.0)
                        if status < 400:
                            info = json.loads(body)
                            self._note_task_status(loc.task_id, info)
                            state = info.get("state")
                            if state in ("FLUSHING", "FINISHED", "FAILED",
                                         "CANCELED"):
                                break
                    except Exception:  # noqa: BLE001 — retry until deadline
                        pass
                    if self.state.is_terminal():
                        return
                    time.sleep(0.05)

    def _cancel_tasks(self) -> None:
        for locations in self.fragment_tasks.values():
            for loc in locations:
                try:
                    wire.http_request(
                        "DELETE", f"{loc.base_url}/v1/task/{loc.task_id}",
                        timeout=5.0)
                except Exception:  # noqa: BLE001
                    pass

    def info(self) -> dict:
        stages = self.stage_stats()
        return {
            "queryId": self.query_id,
            "state": self.state.get(),
            "user": self.user,
            "query": self.sql,
            "failure": (self.failure or "").split("\n")[0] or None,
            "cacheStatus": self.cache_status,
            "fastPath": self.fast_path,
            "fragments": {
                str(fid): [l.task_id for l in locs]
                for fid, locs in self.fragment_tasks.items()
            },
            "retriedTasks": list(self.retried_tasks),
            # versioned plan changes the adaptive re-planner applied
            # (rule, fragment, description, superseded/new fragments)
            "planVersions": list(self.plan_versions),
            # live task→stage→query rollup of worker-reported OperatorStats
            # (frozen once the query is terminal — polling stops and
            # FINISHED slots never downgrade)
            "queryStats": self.query_stats(stages),
            "stageStats": stages,
        }


class CoordinatorServer:
    """The coordinator process: discovery registry + dispatch + protocol."""

    def __init__(self, port: int = 0, session_factory=None, resource_group=None,
                 cluster_memory_limit_bytes=None, low_memory_killer=None,
                 authenticator=None, executor_lanes=None,
                 dispatch_queue_capacity=None, executor_plane=None,
                 executor_processes=None, resource_groups_config=None):
        from trino_tpu.server.resource_groups import (
            ResourceGroupTree, config_from_env, load_config_file,
            parse_config)
        from trino_tpu.connector.registry import default_catalogs
        from trino_tpu.server.cluster_memory import (
            ClusterMemoryManager, total_reservation_killer)

        self.registry = NodeRegistry()
        self.cluster_memory = ClusterMemoryManager(
            kill=self._kill_query,
            cluster_limit_bytes=cluster_memory_limit_bytes,
            policy=low_memory_killer or total_reservation_killer)
        # one shared catalog map for every query this server runs: DDL/DML
        # against stateful connectors (memory) must be visible to later
        # statements (reference: MetadataManager's catalog handles living at
        # server scope, not query scope)
        self.catalogs = default_catalogs()
        # system catalog (trino_tpu/connector/system/): bounded completed-
        # query history ring (QueryTracker's query.max-history analog) +
        # the live provider that feeds system.runtime.* and system.metrics
        # from THIS server's state at scan time
        from trino_tpu.server.system_tables import (
            CoordinatorSystemTables, QueryHistory)

        self.history = QueryHistory()
        if "system" in self.catalogs:
            self.catalogs["system"].attach_live_provider(
                CoordinatorSystemTables(self))
        # shared across statements, like catalogs: CREATE FUNCTION on one
        # query is callable from the next (reference: global function store)
        self.udfs: Dict[str, object] = {}

        def _shared_catalog_session(properties):
            from trino_tpu.client.session import Session

            return Session(properties, catalogs=self.catalogs,
                           udfs=self.udfs, matviews=self.matviews)

        self.session_factory = session_factory or _shared_catalog_session
        # query caching subsystem (trino_tpu/cache/): logical-plan cache +
        # result cache shared by every query this server runs; per-query
        # opt-in via the result_cache_enabled session property
        from trino_tpu.cache import QueryCache

        self.query_cache = QueryCache()
        # prepared statements (server/prepared.py): server-wide registry
        # keyed (user, name) so PREPARE survives across statements — our
        # per-query sessions are throwaway; the reference holds these in
        # the client session and replays them per request, which collapses
        # to this registry for a single coordinator
        from trino_tpu.server.prepared import PreparedStatementRegistry

        self.prepared = PreparedStatementRegistry()
        # materialized views (trino_tpu/matview/): server-wide registry
        # shared by every session this coordinator creates; replicated to
        # executor processes via the sync_materialized_view procedure
        from trino_tpu.matview.registry import MaterializedViewRegistry

        self.matviews = MaterializedViewRegistry()
        self.queries: Dict[str, QueryExecution] = {}
        self._qlock = threading.Lock()
        self._qid = itertools.count(1)
        # admission control (reference: resource groups / DispatchManager's
        # resource-group submission). Default: the hierarchical
        # ResourceGroupTree — selector-routed, weighted-fair, with
        # per-group concurrency/queue/memory limits, configured from
        # `resource_groups_config` (a dict or a JSON file path) or the
        # TRINO_TPU_RESOURCE_GROUPS_CONFIG file; config validation runs
        # HERE so a bad file fails server start, not the first query.
        # An explicitly injected `resource_group` gate keeps the legacy
        # flat blocking-submit admission path.
        if resource_group is not None:
            self.resource_groups = None
            self.resource_group = resource_group
        else:
            if resource_groups_config is None:
                roots, selectors = config_from_env()
            elif isinstance(resource_groups_config, str):
                roots, selectors = load_config_file(resource_groups_config)
            else:
                roots, selectors = parse_config(resource_groups_config)
            self.resource_groups = ResourceGroupTree(roots, selectors)
            # group memory limits read the cluster ledger's live
            # per-query bytes (the PR 16 attribution spine)
            self.resource_groups.set_memory_probe(
                self.cluster_memory.query_reservations)
            # the tree also serves the flat gate's read surface (info()
            # feeds /ui); submit()/finish() calls never reach it — the
            # tree path admits at dequeue time
            self.resource_group = self.resource_groups
        # end-user authentication on the public API (None = open cluster;
        # reference: PasswordAuthenticatorManager / jwt — server/auth.py)
        self.authenticator = authenticator
        # event listener SPI (server/events.py; reference:
        # eventlistener/EventListenerManager)
        from trino_tpu.server.events import EventListenerManager

        self.events = EventListenerManager()
        # first in-tree SPI consumer, on by default: slow queries log with
        # their span breakdown (threshold: slow_query_log_threshold_ms
        # session property > TRINO_TPU_SLOW_QUERY_MS env > 30 s default;
        # listeners are exception-isolated, so this can never fail a query)
        from trino_tpu.obs.listeners import SlowQueryLogListener

        self.events.add(SlowQueryLogListener())
        # durable JSONL query history (obs/listeners.QueryLogListener):
        # opt-in via env, exception-isolated like every listener
        import os as _os

        query_log_path = _os.environ.get("TRINO_TPU_QUERY_LOG")
        if query_log_path:
            from trino_tpu.obs.listeners import QueryLogListener

            self.events.add(QueryLogListener(query_log_path))
        self.queries_submitted = 0
        self.start_time = time.time()
        # failure flight recorder (obs/flightrecorder.py): this process's
        # bounded ring of recent span/event/admission records — what the
        # FAILED-query postmortem snapshots on the coordinator side
        from trino_tpu.obs.flightrecorder import FlightRecorder

        self.recorder = FlightRecorder(node_id="coordinator")
        # cluster memory ledger (obs/memledger.py): the process-global
        # ring takes this node's identity once (an in-process worker may
        # have stamped it first — tests run both in one interpreter) and
        # mirrors shed events into the flight recorder for postmortems
        from trino_tpu.obs.memledger import MEMORY_LEDGER

        if not MEMORY_LEDGER.node_id:
            MEMORY_LEDGER.node_id = "coordinator"
        MEMORY_LEDGER.attach_recorder(self.recorder)
        # device profiler (obs/devprofiler.py): same first-server-wins
        # identity stamp; compile-ledger events mirror into the flight
        # recorder so postmortems show recompile storms
        from trino_tpu.obs.devprofiler import DEVICE_PROFILER

        if not DEVICE_PROFILER.node_id:
            DEVICE_PROFILER.node_id = "coordinator"
        DEVICE_PROFILER.attach_recorder(self.recorder)
        # data-plane flow ledger (obs/flowledger.py): same
        # first-server-wins identity stamp; retried transfers mirror
        # into the flight recorder so postmortems show flaky links
        from trino_tpu.obs.flowledger import FLOW_LEDGER

        if not FLOW_LEDGER.node_id:
            FLOW_LEDGER.node_id = "coordinator"
        FLOW_LEDGER.attach_recorder(self.recorder)
        # spooled result segments (server/segments.py): the coordinator's
        # own store — coordinator-local/fast-path queries (and
        # non-trivial-root distributed ones) spool here, so the protocol
        # decision is plan-shape-independent
        from trino_tpu.server.segments import SegmentStore

        self.segments = SegmentStore(node_id="coordinator")
        # OTLP export (obs/otlp.py): on only when TRINO_TPU_OTLP_ENDPOINT
        # is set — completed queries' span trees ship to the collector
        # from a background batch exporter, never the query path
        from trino_tpu.obs import otlp as _otlp

        self.otlp = _otlp.exporter_from_env("trino-tpu-coordinator")
        # dispatch plane / executor plane split (server/dispatch.py): the
        # bounded dispatch queue, the fixed pool of executor lanes that
        # replaced per-query thread creation, the dispatch-plane serving
        # index, and (opt-in) the executor-process pool
        from trino_tpu.server.dispatch import Dispatcher

        self.dispatcher = Dispatcher(
            self, lanes=executor_lanes,
            queue_capacity=dispatch_queue_capacity, plane=executor_plane,
            processes=executor_processes, groups=self.resource_groups)
        # shared IO pool for parallel worker pulls (span dumps, flight-
        # recorder rings): lazily created, shut down with the server —
        # replaces the fresh ThreadPoolExecutor these calls built per
        # invocation on the hot path
        self._io_pool = None
        self._io_pool_lock = threading.Lock()
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.base_url = f"http://127.0.0.1:{self.port}"
        self._serve_thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)

    @property
    def io_pool(self):
        """The server-wide IO thread pool (created on first use)."""
        pool = self._io_pool
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._io_pool_lock:
                if self._io_pool is None:
                    self._io_pool = ThreadPoolExecutor(
                        max_workers=16, thread_name_prefix="coord-io")
                pool = self._io_pool
        return pool

    def start(self) -> None:
        self._serve_thread.start()
        self.dispatcher.ensure_lanes()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self.dispatcher.shutdown()
        self.segments.close()
        with self._io_pool_lock:
            pool, self._io_pool = self._io_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        if self.otlp is not None:
            # flush + stop the exporter thread: a stopped instance must
            # not keep reporting metrics under its service identity
            self.otlp.shutdown()

    # retained terminal queries (history for /v1/query) — oldest evicted
    # with their materialized result rows (reference: query.max-history)
    MAX_QUERY_HISTORY = 100

    def submit(self, sql: str, properties: Optional[dict] = None,
               user: str = "anonymous", source: str = "") -> QueryExecution:
        # resource-group classification runs FIRST (cheap: a regex chain
        # over user/source/session properties) so the overload turn-around
        # below can name the saturated group and its queue depth
        group = None
        if self.resource_groups is not None:
            group = self.resource_groups.select(user, source,
                                                properties or {})
        # typed overload turn-around BEFORE any per-query state is built:
        # a full dispatch queue raises DispatchRejected (the protocol
        # surface answers 429 + Retry-After), never a hang or a thread
        self.dispatcher.precheck(group)
        query_id = f"q{time.strftime('%Y%m%d')}_{next(self._qid):05d}_{uuid.uuid4().hex[:5]}"
        execution = QueryExecution(
            query_id, sql, properties or {}, self.registry, self.session_factory,
            user=user, query_cache=self.query_cache,
            prepared_registry=self.prepared)
        execution.resource_group = group
        execution.source = source
        # flight-recorder hookup: closed spans mirror into the process
        # ring, and the execution can snapshot it for its postmortem
        execution.recorder = self.recorder
        execution.tracer.recorder = self.recorder
        execution.io_pool = self.io_pool
        execution.dispatcher = self.dispatcher
        # spooled result protocol hookup + an opportunistic TTL sweep
        # (rate-limited in the store) on the submit cadence
        execution.segment_store = self.segments
        execution.segment_base_url = self.base_url
        self.segments.maybe_sweep()
        self.recorder.record("admission", "submitted", queryId=query_id,
                             user=user)
        with self._qlock:
            if len(self.queries) > self.MAX_QUERY_HISTORY:
                # scan for prunable terminals only once the registry can
                # actually be over budget — the per-submit full scan this
                # replaces was measurable on the serving hot path
                terminal = [qid for qid, q in self.queries.items()
                            if q.state.is_terminal()]
                for qid in terminal[: max(0, len(terminal)
                                          - self.MAX_QUERY_HISTORY)]:
                    del self.queries[qid]
            self.queries[query_id] = execution
            self.queries_submitted += 1
        from trino_tpu.server import events as ev

        created_at = time.time()
        self.events.fire_created(
            ev.QueryCreatedEvent(query_id, user, sql, created_at))
        def fire_terminal(state):
            if state not in ("FINISHED", "FAILED", "CANCELED"):
                return
            try:
                # serving-index maintenance (server/dispatch.py): learn
                # MISS-then-filled SELECTs, clear on non-SELECT statements
                self.dispatcher.note_completion(
                    execution, execution.is_plain_select)
            except Exception:  # noqa: BLE001 — index upkeep must never
                pass  # disturb the terminal transition
            now = time.time()
            wall = now - created_at
            from trino_tpu.obs import metrics as M

            M.QUERY_SECONDS.observe(wall, state)
            self.recorder.record("event", "query-completed",
                                 queryId=query_id, state=state,
                                 wallS=round(wall, 6))
            # query-peak histogram (memory ledger): one sample per
            # terminal query, from the task→stage→query rollup
            try:
                peak = int(execution.query_stats().get("peakBytes") or 0)
                if peak:
                    M.QUERY_PEAK_MEMORY_BYTES.observe(peak, state)
            except Exception:  # noqa: BLE001 — observability, never a
                pass  # reason to disturb the terminal transition
            # the phase ledger: computed ONCE here (the merged span tree
            # exists now) and fed into the per-phase histogram — this is
            # where every millisecond of the wall gets attributed
            timeline = None
            try:
                timeline = execution.timeline_dict()
                if timeline is not None:
                    from trino_tpu.obs.timeline import observe_phases

                    observe_phases(timeline)
            except Exception:  # noqa: BLE001 — the ledger is
                pass  # observability, never a reason to disturb terminal
            # kernel-ledger fold (device profiler): persist the merged
            # per-operator kernel rows ONCE — system.runtime.kernels and
            # the per-operator launch/overhead metrics read the folded
            # store, so nothing bumps per-dispatch on the serving path
            try:
                execution.fold_kernel_profile()
            except Exception:  # noqa: BLE001 — observability only
                pass
            # flow-ledger fold: freeze the straggler verdicts and bump
            # the per-cause counter ONCE — system.runtime.stragglers and
            # the /flows surface read the frozen verdicts after this
            try:
                execution.fold_flow_profile()
            except Exception:  # noqa: BLE001 — observability only
                pass
            # a FAILED/CANCELED query's result segments will never be
            # fetched — reclaim the coordinator-hosted ones now instead
            # of waiting out the TTL (worker-hosted ones TTL out; their
            # producing tasks normally abandoned them already)
            if state != "FINISHED":
                try:
                    self.segments.drop_query(query_id)
                except Exception:  # noqa: BLE001 — lifecycle best-effort
                    pass
            # FAILED queries carry the flight-recorder postmortem —
            # normally captured by the query thread before the terminal
            # transition; a kill() from another thread captures here
            if state == "FAILED" and execution.postmortem is None:
                try:
                    execution.capture_postmortem()
                except Exception:  # noqa: BLE001 — best-effort forensics
                    pass
            self.events.fire_completed(
                ev.QueryCompletedEvent(
                    query_id, user, sql, state, created_at, now,
                    wall, len(execution.rows), execution.failure,
                    spans=tuple(execution.tracer.to_dicts()),
                    session_properties=dict(execution.session_properties),
                    timeline=timeline,
                    postmortem=execution.postmortem,
                )
            )
            if self.otlp is not None:
                # ship the coordinator half of the trace (workers export
                # their own task spans at task completion) — with the
                # query's per-link flow totals + straggler count as
                # resource attributes, so the collector sees the data
                # plane without a second export path
                otlp_attrs = {"query_id": query_id, "query.user": user,
                              "query.state": state}
                try:
                    by_link: Dict[str, int] = {}
                    for r in execution.flow_rows_live():
                        by_link[r["link"]] = (by_link.get(r["link"], 0)
                                              + int(r["bytes"]))
                    for link, nbytes in sorted(by_link.items()):
                        otlp_attrs[f"flow.{link}.bytes"] = nbytes
                    otlp_attrs["flow.stragglers"] = len(
                        execution.straggler_rows())
                except Exception:  # noqa: BLE001 — observability only
                    pass
                self.otlp.export_spans(
                    execution.tracer.to_dicts(), execution.tracer.trace_id,
                    otlp_attrs)
            # completed-query history (system.runtime.queries coverage of
            # finished queries): retention knobs are session-property-
            # gated, read from THIS query's submitted properties — but the
            # ring is SHARED server state, so a session may only GROW
            # retention (clamped at the server defaults): otherwise any
            # session completing one query with query_max_history=1 would
            # wipe every other user's history
            from trino_tpu.server.system_tables import (
                DEFAULT_MAX_HISTORY, DEFAULT_MIN_EXPIRE_AGE_MS, query_record)

            try:
                self.history.record(
                    query_record(execution, state=state, ended_at=now),
                    max_history=max(DEFAULT_MAX_HISTORY, _int_property(
                        execution.session_properties, "query_max_history",
                        DEFAULT_MAX_HISTORY)),
                    min_expire_age_ms=max(
                        DEFAULT_MIN_EXPIRE_AGE_MS, _int_property(
                            execution.session_properties,
                            "query_min_expire_age_ms",
                            DEFAULT_MIN_EXPIRE_AGE_MS)))
            except Exception:  # noqa: BLE001 — history is observability,
                pass  # never a reason to disturb the terminal transition

        execution.state.add_listener(fire_terminal)
        # dispatch is ASYNC: the submit POST returns a QUEUED payload
        # and the client polls nextUri; the dispatcher either answers the
        # query on the dispatch plane (serving index), enqueues it for an
        # executor lane, or rejects it typed when the queue is full
        # (reference: QueuedStatementResource's queued/executing split)
        from trino_tpu.server.dispatch import DispatchRejected

        try:
            self.dispatcher.dispatch(execution)
        except DispatchRejected as e:
            # lost the capacity race after registration: unregister and
            # surface the same typed rejection the precheck gives. The
            # rejected statement executed NOTHING — it must not count as
            # a non-SELECT completion and wipe the serving index right
            # when overload needs it most
            execution.is_plain_select = True
            with self._qlock:
                self.queries.pop(query_id, None)
            execution.failure = str(e)
            execution.ended_at = time.time()
            execution.state.set("FAILED")
            self.recorder.record("admission", "dispatch-rejected",
                                 queryId=query_id, user=user)
            raise
        return execution

    def _admit(self, execution: QueryExecution) -> bool:
        """Admission, run on an executor lane after dequeue: the resource
        group (per-user fairness) then the cluster-memory gate. Returns
        False when the query failed admission or went terminal (canceled)
        while queued — the lane moves on."""
        user = execution.user
        if self.resource_groups is not None:
            # group-aware path: the tree ALREADY admitted this query at
            # dequeue time (weighted-fair pick under concurrency + memory
            # eligibility) — release its slot at terminal, or right now
            # if it went terminal (canceled) between dequeue and here
            qid = execution.query_id
            if execution.state.is_terminal():
                self.resource_groups.finish(qid)
                return False
            groups = self.resource_groups
            execution.state.add_listener(
                lambda s: groups.finish(qid)
                if s in ("FINISHED", "FAILED", "CANCELED") else None)
            self.recorder.record(
                "admission", "admitted", queryId=qid, user=user,
                group=execution.resource_group)
        else:
            if execution.state.is_terminal():  # canceled while queued
                return False
            if not self.resource_group.submit(timeout=600.0, user=user):
                execution.failure = (
                    "Query queue is full (resource group limit)")
                self.recorder.record("admission", "queue-full",
                                     queryId=execution.query_id, user=user)
                execution.state.set("FAILED")
                return False
            self.recorder.record("admission", "admitted",
                                 queryId=execution.query_id, user=user)
        # cluster-memory admission: dispatch blocks while the cluster
        # pool is over its limit (reference: ClusterMemoryManager's
        # query.max-memory gate) — the killer frees it if needed; a
        # cluster that stays saturated past the deadline FAILS the
        # query loudly (never silently dispatches over the limit)
        deadline = time.monotonic() + 600.0
        while (not self.cluster_memory.has_headroom()
               and not execution.state.is_terminal()
               and time.monotonic() < deadline):
            time.sleep(0.2)
        if (not execution.state.is_terminal()
                and not self.cluster_memory.has_headroom()):
            execution.failure = (
                "Cluster is out of memory and did not recover within the "
                "admission deadline (EXCEEDED_CLUSTER_MEMORY)")
            execution.state.set("FAILED")
        if execution.state.is_terminal():  # canceled/killed while queued
            # tree path: its terminal listener (registered above) already
            # released the group slot when the state flipped
            if self.resource_groups is None:
                self.resource_group.finish(user=user)
            return False
        if self.resource_groups is None:
            execution.state.add_listener(
                lambda s: self.resource_group.finish(user=user)
                if s in ("FINISHED", "FAILED", "CANCELED") else None)
        return True

    def get_query(self, query_id: str) -> Optional[QueryExecution]:
        with self._qlock:
            return self.queries.get(query_id)

    def query_state_counts(self):
        """Public metrics accessor: ``(queries-by-state counts, result rows
        held by FINISHED queries)`` — the exporter reads this instead of
        reaching into ``_qlock``/``queries`` privates."""
        by_state: Dict[str, int] = {}
        total_rows = 0
        with self._qlock:
            queries = list(self.queries.values())
        for q in queries:
            st = q.state.get()
            by_state[st] = by_state.get(st, 0) + 1
            if st == "FINISHED":
                total_rows += len(q.rows)
        return by_state, total_rows

    def query_trace(self, query_id: str,
                    include_recorder: bool = False) -> Optional[dict]:
        """Assemble the query's cross-process span tree: coordinator-side
        spans merge with each worker task's span dump (pulled on demand from
        ``GET /v1/task/{id}/spans`` — task-span collection is lazy, like the
        reference's trace export being independent of the query path).
        ``include_recorder`` attaches the flight-recorder postmortem: the
        one captured at FAILED, else a live merge of the rings
        (``?recorder=1``)."""
        q = self.get_query(query_id)
        if q is None:
            return None
        spans = (q.tracer.to_dicts() + list(q.extra_spans)
                 + q.worker_spans())
        from trino_tpu.obs.trace import build_tree

        trace = {
            "queryId": q.query_id,
            "traceId": q.tracer.trace_id,
            "state": q.state.get(),
            "spanCount": len(spans),
            # the phase ledger rides the trace payload once terminal —
            # the span tree is the evidence, the ledger the verdict
            "timeline": q.timeline_dict(),
            "root": build_tree(spans),
        }
        if include_recorder:
            # the stored postmortem exists only for FAILED queries (frozen
            # at failure time); any other state merges the LIVE rings on
            # every read — never cached, so repeated reads see fresh
            # process context
            trace["postmortem"] = (
                q.postmortem if q.postmortem is not None
                else q.capture_postmortem(store=False))
        return trace

    def _kill_query(self, query_id: str, reason: str) -> None:
        q = self.get_query(query_id)
        if q is not None and not q.state.is_terminal():
            q.kill(reason)


def _result_payload(server: CoordinatorServer, q: QueryExecution, token: int) -> dict:
    state = q.state.get()
    # summary stats ride EVERY statement response (reference: the
    # StatementStats block of the client protocol) so clients can render
    # live progress while polling nextUri
    payload: dict = {
        "id": q.query_id,
        "stats": {**q.query_stats(q.stage_stats(include_operators=False)),
                  "state": state},
    }
    if state == "FAILED":
        payload["error"] = {"message": q.failure or "query failed"}
        return payload
    if state == "CANCELED":
        payload["error"] = {"message": "query was canceled"}
        return payload
    if state != "FINISHED":
        payload["nextUri"] = f"{server.base_url}/v1/statement/executing/{q.query_id}/{token}"
        return payload
    if q.set_session:
        payload["setSessionProperties"] = {k: v for k, v in q.set_session.items()}
    if q.reset_session:
        payload["resetSessionProperties"] = list(q.reset_session)
    # PREPARE/DEALLOCATE round-trip (the X-Trino-Added-Prepare /
    # X-Trino-Deallocated-Prepare analog): clients track which names are
    # live so drivers (DBAPI) can skip re-PREPARE on reuse
    if q.add_prepared:
        payload["addedPreparedStatements"] = dict(q.add_prepared)
    if q.deallocated_prepared:
        payload["deallocatedPreparedStatements"] = list(q.deallocated_prepared)
    if q.result_segments is not None:
        # spooled protocol: the response carries the segment MANIFEST —
        # clients fetch the data from the producers' segment endpoints
        # in parallel; this coordinator never pages the rows
        q.last_drain_at = time.time()
        payload["columns"] = [{"name": c} for c in q.columns]
        payload["segments"] = [dict(e) for e in q.result_segments]
        payload["spooled"] = q.spooled
        return payload
    start = token * RESULT_PAGE_ROWS
    chunk = q.rows[start : start + RESULT_PAGE_ROWS]
    # client-drain bookkeeping for the phase ledger: the query's wall is
    # over, but the client is still fetching pages
    q.last_drain_at = time.time()
    payload["columns"] = [{"name": c} for c in q.columns]
    payload["data"] = [list(_jsonable(v) for v in row) for row in chunk]
    if start + RESULT_PAGE_ROWS < len(q.rows):
        payload["nextUri"] = f"{server.base_url}/v1/statement/executing/{q.query_id}/{token + 1}"
    return payload


def _drain_body(server: CoordinatorServer, q: QueryExecution,
                token: int) -> bytes:
    """Serialize one statement-protocol response and charge its bytes to
    the query's ``client-drain`` flow when it carries results (rows or a
    segment manifest). The serialize wall is the drain cost the
    coordinator can see — socket write time belongs to the client."""
    import time as _time

    t0 = _time.perf_counter()
    payload = _result_payload(server, q, token)
    body = json.dumps(payload).encode()
    if "data" in payload or "segments" in payload:
        try:
            from trino_tpu.obs.flowledger import FLOW_LEDGER

            FLOW_LEDGER.record_transfer(
                "client-drain", f"drain:{q.query_id}", len(body),
                _time.perf_counter() - t0,
                pages=len(payload.get("data") or payload.get("segments")
                          or ()),
                src=FLOW_LEDGER.node_id or "coordinator", dst="client",
                direction="send")
            if "nextUri" not in payload and "stats" in payload:
                # final page: refresh the stats flows block so the CLI
                # summary's drain tag counts THIS response's bytes (the
                # stats were built before the record above) — one extra
                # dumps of the last page buys a truthful summary
                payload["stats"]["flows"] = q.flow_stats_block()
                body = json.dumps(payload).encode()
        except Exception:  # noqa: BLE001 — accounting never fails serving
            pass
    return body


CACHE_HEADER = "X-Trino-Tpu-Cache"


def _cache_header(q: QueryExecution) -> Optional[dict]:
    """Result-cache disposition header (HIT|MISS|BYPASS), once the query
    has decided it (None while still queued/planning)."""
    return {CACHE_HEADER: q.cache_status} if q.cache_status else None


def _profiler_snapshot() -> dict:
    """The postmortem's device-profiler block: newest compile-ledger
    events + the monotonic utilization counters."""
    try:
        from trino_tpu.obs.devprofiler import DEVICE_PROFILER

        return {"compiles": DEVICE_PROFILER.compile_rows(limit=16),
                "counters": DEVICE_PROFILER.counters()}
    except Exception:  # noqa: BLE001 — best-effort forensics
        return {}


def _flows_snapshot() -> dict:
    """The postmortem's flow-ledger block: per-link rollups, net totals,
    the newest transfer records and the stall rollups."""
    try:
        from trino_tpu.obs.flowledger import FLOW_LEDGER

        return FLOW_LEDGER.flow_snapshot()
    except Exception:  # noqa: BLE001 — best-effort forensics
        return {}


def _int_property(properties: dict, name: str, default: int) -> int:
    """Integer session property from a raw (wire-string) property map —
    malformed values fall back like the typed registry's defaults."""
    try:
        return int(properties.get(name, default))
    except (TypeError, ValueError):
        return default


def _jsonable(v):
    import datetime
    import decimal

    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.isoformat()
    if isinstance(v, decimal.Decimal):
        return str(v)
    return v


def _render_ui(server: CoordinatorServer) -> str:
    """Minimal cluster status page (reference role: core/trino-web-ui's
    query list + worker view, server-rendered instead of a React SPA)."""
    import html

    rows = []
    with server._qlock:
        queries = sorted(server.queries.items(), reverse=True)
    for qid, q in queries[:50]:
        state = q.state.get()
        stage_list = q.stage_stats(include_operators=False)
        qs = q.query_stats(stage_list)
        stages = " ".join(
            f"f{s['stageId']}: {s['outputRows']} rows/"
            f"{s['wallS'] * 1e3:.0f}ms"
            for s in stage_list) or "—"
        progress = (f"{qs['completedSplits']}/{qs['totalSplits']} splits, "
                    f"{qs['elapsedMs'] / 1e3:.1f}s")
        rows.append(
            f"<tr><td>{html.escape(qid)}</td><td class='s {state}'>{state}</td>"
            f"<td>{html.escape(q.user)}</td>"
            f"<td><code>{html.escape(q.sql.strip()[:120])}</code></td>"
            f"<td>{html.escape(progress)}</td>"
            f"<td>{html.escape(stages)}</td>"
            f"<td>{len(q.retried_tasks)}</td></tr>")
    nodes = "".join(
        f"<tr><td>{html.escape(n['nodeId'])}</td>"
        f"<td>{html.escape(n['url'])}</td></tr>"
        for n in server.registry.alive())
    # recent queries from the completed-query history ring (the durable
    # record: survives the live registry's pruning)
    recent = []
    for rec in server.history.snapshot()[:50]:
        recent.append(
            f"<tr><td>{html.escape(rec['queryId'])}</td>"
            f"<td class='s {rec['state']}'>{rec['state']}</td>"
            f"<td>{rec['elapsedMs'] / 1e3:.1f}s</td>"
            f"<td>{rec['resultRows']}</td>"
            f"<td>{html.escape(rec['cacheStatus'] or '—')}</td>"
            f"<td>{rec['adaptations']}</td>"
            f"<td><code>{html.escape((rec['query'] or '').strip()[:100])}"
            f"</code></td></tr>")
    recent_html = "".join(recent) or (
        "<tr><td colspan='7'>no completed queries yet</td></tr>")
    rg = server.resource_group.info()
    group_rows = ""
    for gname, g in sorted(rg.get("groups", {}).items()):
        group_rows += (
            f"<tr><td>{html.escape(gname)}</td><td>{g['state']}</td>"
            f"<td>{g['running']}</td><td>{g['queued']}</td>"
            f"<td>{g['served']}</td><td>{g['weight']}</td></tr>")
    groups_html = (
        "<h2>resource groups <small>(<code>select * from "
        "system.runtime.resource_groups</code>)</small></h2><table>"
        "<tr><th>group</th><th>state</th><th>running</th><th>queued</th>"
        f"<th>served</th><th>weight</th></tr>{group_rows}</table>"
        if group_rows else "")
    return f"""<!doctype html><html><head><meta http-equiv="refresh" content="3">
<title>trino-tpu</title><style>
body{{font-family:monospace;margin:2em;background:#111;color:#ddd}}
table{{border-collapse:collapse;margin:1em 0;width:100%}}
td,th{{border:1px solid #333;padding:4px 10px;text-align:left}}
.s.FINISHED{{color:#6c6}}.s.FAILED{{color:#e66}}.s.RUNNING{{color:#6ae}}
h1,h2{{color:#fff}}</style></head><body>
<h1>trino-tpu coordinator</h1>
<p>resource group "{rg['name']}": {rg['running']} running, {rg['queued']} queued
(limit {rg['hardConcurrencyLimit']})</p>
{groups_html}
<h2>workers</h2><table><tr><th>node</th><th>url</th></tr>{nodes}</table>
<h2>queries <small>(<a href="#recent" style="color:#6ae">recent
queries</a> · <code>select * from system.runtime.queries</code>)</small></h2>
<table>
<tr><th>query id</th><th>state</th><th>user</th><th>query</th>
<th>progress</th><th>stages (rows/wall)</th><th>retries</th></tr>
{''.join(rows)}</table>
<h2 id="recent">recent queries</h2><table>
<tr><th>query id</th><th>state</th><th>elapsed</th><th>rows</th>
<th>cache</th><th>adaptations</th><th>query</th></tr>
{recent_html}</table></body></html>"""


def _make_handler(server: CoordinatorServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # close keep-alive connections idle past this (the client pool's
        # idle TTL is shorter, so the client normally closes first)
        timeout = 30
        # TCP_NODELAY: headers and body flush as separate writes — with
        # Nagle on, the second write stalls behind the delayed ACK
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):
            pass

        def _send(self, status: int, body: bytes = b"",
                  content_type: str = "application/json",
                  headers: Optional[dict] = None):
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _read_body(self) -> bytes:
            n = int(self.headers.get("Content-Length", "0"))
            return self.rfile.read(n)

        def do_PUT(self):
            m = _ANNOUNCE_RE.match(self.path)
            if m:
                body = self._read_body()
                if not wire.verify(body, self.headers.get(wire.H_INTERNAL_AUTH)):
                    self._send(401, b'{"error": "bad internal signature"}')
                    return
                info = json.loads(body)
                server.registry.announce(m.group(1), info["url"], info)
                server.cluster_memory.update(m.group(1), info)
                self._send(200, b"{}")
                return
            self._send(404)

        def do_POST(self):
            if self.path == "/v1/statement":
                sql = self._read_body().decode()
                props = {}
                for header, value in self.headers.items():
                    if header.lower().startswith("x-trino-session-"):
                        props[header[len("x-trino-session-"):].lower()] = value
                user = self.headers.get("X-Trino-User", "anonymous")
                if server.authenticator is not None and server.authenticator.required:
                    from trino_tpu.server.auth import AuthenticationError

                    try:
                        identity = server.authenticator.authenticate_header(
                            self.headers.get("Authorization"))
                    except AuthenticationError as e:
                        self._send(401, json.dumps(
                            {"error": {"message": f"Authentication failed: {e}"}}
                        ).encode(), headers={
                            "WWW-Authenticate": 'Basic realm="trino-tpu", Bearer'})
                        return
                    # the authenticated principal wins over the client's
                    # claimed user header (no impersonation by default)
                    user = identity.user
                # the client-reported source (X-Trino-Source): a
                # resource-group selector routing dimension, like user
                source = self.headers.get("X-Trino-Source", "")
                from trino_tpu.server.dispatch import DispatchRejected

                try:
                    q = server.submit(sql, props, user=user, source=source)
                except DispatchRejected as e:
                    # typed overload: 429 + Retry-After with structured
                    # retry guidance — the client backs off and retries
                    # instead of piling a thread onto a saturated server
                    self._send(429, json.dumps(e.payload()).encode(),
                               headers={"Retry-After":
                                        f"{e.retry_after_s:g}"})
                    return
                # brief long-poll: short queries finish inside this
                # window, collapsing the protocol to ONE round trip
                # (submit response already carries the result page)
                if not q.state.is_terminal():
                    q.state.wait_for_terminal(0.5)
                self._send(200, _drain_body(server, q, 0),
                           headers=_cache_header(q))
                return
            self._send(404)

        def _authenticated(self, query=None):
            """Gate for query-scoped routes when an authenticator is
            configured: results, query info, and cancel carry user data and
            control — they are NOT open even though submission already
            authenticated (predictable query ids must not leak results).
            With ``query``, the authenticated principal must also OWN it
            (reference: AccessControl.checkCanViewQueryOwnedBy /
            checkCanKillQueryOwnedBy)."""
            if server.authenticator is None or not server.authenticator.required:
                return True
            from trino_tpu.server.auth import AuthenticationError

            try:
                identity = server.authenticator.authenticate_header(
                    self.headers.get("Authorization"))
            except AuthenticationError as e:
                self._send(401, json.dumps(
                    {"error": {"message": f"Authentication failed: {e}"}}
                ).encode(), headers={
                    "WWW-Authenticate": 'Basic realm="trino-tpu", Bearer'})
                return False
            if query is not None and query.user != identity.user:
                self._send(403, json.dumps(
                    {"error": {"message":
                               "Access Denied: query belongs to another user"}}
                ).encode())
                return False
            return True

        def do_GET(self):
            m = _RESULT_RE.match(self.path)
            if m:
                q = server.get_query(m.group(1))
                if not self._authenticated(query=q):
                    return
                if q is None:
                    self._send(404, b'{"error": "no such query"}')
                    return
                # long-poll briefly so clients don't busy-spin
                if not q.state.is_terminal():
                    q.state.wait_for_terminal(0.5)
                self._send(200, _drain_body(server, q, int(m.group(2))),
                           headers=_cache_header(q))
                return
            # the trace route accepts a query string (?recorder=1 attaches
            # the flight-recorder postmortem); other routes stay exact
            from urllib.parse import parse_qs, urlsplit

            url_parts = urlsplit(self.path)
            m = _TRACE_RE.match(url_parts.path)
            if m:
                q = server.get_query(m.group(1))
                if not self._authenticated(query=q):
                    return
                params = parse_qs(url_parts.query)
                with_recorder = params.get("recorder", ["0"])[0] not in (
                    "0", "", "false")
                trace = (server.query_trace(
                            m.group(1), include_recorder=with_recorder)
                         if q is not None else None)
                if trace is None:
                    # covers eviction between the two lookups too: never
                    # answer 200 with a null body
                    self._send(404, b'{"error": "no such query"}')
                    return
                self._send(200, json.dumps(trace).encode())
                return
            m = _PROFILE_RE.match(url_parts.path)
            if m:
                # the device-profiler read surface (obs/devprofiler.py):
                # merged coordinator+worker kernel rows, this query's
                # compile events, utilization samples, phase ledger
                q = server.get_query(m.group(1))
                if not self._authenticated(query=q):
                    return
                if q is None:
                    self._send(404, b'{"error": "no such query"}')
                    return
                self._send(200, json.dumps(q.profile_dict()).encode())
                return
            m = _FLOWS_RE.match(url_parts.path)
            if m:
                # the flow-ledger read surface (obs/flowledger.py): this
                # query's cluster-merged per-link transfer rows, the
                # straggler verdicts, and the backpressure stall rollups
                q = server.get_query(m.group(1))
                if not self._authenticated(query=q):
                    return
                if q is None:
                    self._send(404, b'{"error": "no such query"}')
                    return
                self._send(200, json.dumps(q.flows_dict()).encode())
                return
            m = _QUERY_RE.match(self.path)
            if m:
                q = server.get_query(m.group(1))
                if not self._authenticated(query=q):
                    return
                if q is None:
                    self._send(404, b'{"error": "no such query"}')
                    return
                self._send(200, json.dumps(q.info()).encode())
                return
            m = _SEGMENT_RE.match(self.path)
            if m:
                # coordinator-hosted spooled result segments: the id is
                # an unguessable capability (the reference's pre-signed
                # segment URI model), so no further gate is applied —
                # range/ack semantics live in server/segments.py
                from trino_tpu.server.segments import segment_response

                sid = m.group(1)
                q = server.get_query(sid.split(".", 1)[0])
                if q is not None:
                    q.last_segment_fetch_at = time.time()
                status, body, seg_headers, ctype = segment_response(
                    server.segments, sid, self.headers.get("Range"))
                self._send(status, body, ctype, seg_headers)
                return
            if self.path == "/v1/node":
                self._send(200, json.dumps(server.registry.alive()).encode())
                return
            if self.path == "/v1/info":
                self._send(200, json.dumps(
                    {"coordinator": True, "state": "ACTIVE"}).encode())
                return
            if self.path == "/v1/metrics":
                from trino_tpu.server.events import render_metrics

                self._send(200, render_metrics(server).encode(),
                           "text/plain; version=0.0.4")
                return
            if self.path in ("/ui", "/ui/"):
                self._send(200, _render_ui(server).encode(), "text/html")
                return
            self._send(404)

        def do_DELETE(self):
            m = _RESULT_RE.match(self.path)
            if m:
                q = server.get_query(m.group(1))
                if not self._authenticated(query=q):
                    return
                if q is not None:
                    q.cancel()
                self._send(204)
                return
            m = _SEGMENT_RE.match(self.path)
            if m:
                # segment ACK: data fetches go straight to the owning
                # producer, but the tiny ack DELETE routes through the
                # coordinator — it forwards worker-hosted deletes and
                # stamps the query's segment-fetch clock either way
                sid = m.group(1)
                q = server.get_query(sid.split(".", 1)[0])
                if q is not None:
                    q.last_segment_fetch_at = time.time()
                    worker = q._segment_workers.get(sid)
                    if worker is not None:
                        try:
                            wire.http_request(
                                "DELETE", f"{worker}/v1/segment/{sid}",
                                timeout=10.0)
                        except Exception:  # noqa: BLE001 — TTL backstop
                            pass
                        self._send(204)
                        return
                server.segments.ack(sid)
                self._send(204)
                return
            self._send(404)

    return Handler


def main() -> None:
    """Entry point: ``python -m trino_tpu.server.coordinator --port N``."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    c = CoordinatorServer(args.port)
    c.start()
    print(json.dumps({"url": c.base_url}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        c.stop()


if __name__ == "__main__":
    main()
