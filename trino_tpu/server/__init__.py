"""Distributed control plane: coordinator + worker processes over HTTP.

Reference: the coordinator⇄worker tier of the reference engine —
``dispatcher/QueuedStatementResource.java:103`` (client protocol),
``server/remotetask/HttpRemoteTask.java:132`` (task CRUD),
``execution/SqlTaskManager.java:109`` (worker task engine),
``operator/DirectExchangeClient.java:56`` (streaming page pull).

TPU-first split (SURVEY.md §2.6): the *intra-slice* data plane never touches
this package — repartition/broadcast exchanges compile into the query program
as ICI collectives (parallel/spmd.py). This package is the *DCN tier*: the
host-side control plane (dispatch, task lifecycle, discovery, failure
detection) and the cross-host streaming page shuffle with the columnar wire
serde (data/serde.py).
"""
