"""Worker server: task CRUD + result streaming over HTTP.

Reference: ``server/TaskResource.java`` —
``POST /v1/task/{taskId}`` creates/updates a task (:140-145),
``GET /v1/task/{taskId}/results/{bufferId}/{token}`` streams pages
(:333-336), ``DELETE`` destroys; plus the worker side of discovery
(announce loop → coordinator, reference: airlift discovery announcer).

Built on the stdlib threading HTTP server — the control plane is
latency-bound, not throughput-bound (SURVEY.md §7.1 "control plane stays
host-side"); the data plane bodies are the serde's compressed columnar
pages.
"""
from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from trino_tpu.obs import trace as tracing
from trino_tpu.server import wire
from trino_tpu.server.task import TaskManager, TaskRequest

_RESULTS_RE = re.compile(r"^/v1/task/([^/]+)/results/(\d+)/(\d+)$")
_TASK_RE = re.compile(r"^/v1/task/([^/]+)$")
_STATUS_RE = re.compile(r"^/v1/task/([^/]+)/status$")
_SPANS_RE = re.compile(r"^/v1/task/([^/]+)/spans$")
_RECORDER_RE = re.compile(r"^/v1/task/([^/]+)/recorder$")
_SEGMENT_RE = re.compile(r"^/v1/segment/([^/]+)$")


def default_session_factory(properties):
    from trino_tpu.client.session import Session

    return Session(properties)


def shared_catalog_session_factory():
    """Session factory bound to ONE catalog map (and routine store) for the
    whole process, so stateful-connector writes and CREATE FUNCTION persist
    across tasks (see CoordinatorServer)."""
    from trino_tpu.connector.registry import default_catalogs

    catalogs = default_catalogs()
    udfs: dict = {}

    def factory(properties):
        from trino_tpu.client.session import Session

        return Session(properties, catalogs=catalogs, udfs=udfs)

    return factory


class WorkerServer:
    """One worker process: task manager + HTTP endpoint + announcer."""

    def __init__(self, port: int = 0, coordinator_url: Optional[str] = None,
                 node_id: Optional[str] = None, session_factory=None,
                 memory_limit_bytes: Optional[int] = None):
        import os

        self.node_id = node_id or f"worker-{time.time_ns() & 0xFFFFFF:x}"
        # this worker's failure flight recorder (obs/flightrecorder.py):
        # bounded ring of recent span/event records, pulled by the
        # coordinator into FAILED-query postmortems via
        # GET /v1/task/{id}/recorder
        from trino_tpu.obs.flightrecorder import FlightRecorder
        from trino_tpu.obs.memledger import MEMORY_LEDGER

        self.recorder = FlightRecorder(node_id=self.node_id)
        # the process memory ledger (obs/memledger.py): stamp this node's
        # identity (first server in the process wins — in-process test
        # clusters share one ledger exactly like they share the metrics
        # registry and the cache tiers) and mirror pressure sheds into
        # the flight recorder so OOM postmortems name the shed tier
        if not MEMORY_LEDGER.node_id:
            MEMORY_LEDGER.node_id = self.node_id
        MEMORY_LEDGER.attach_recorder(self.recorder)
        # the process device profiler (obs/devprofiler.py): same
        # first-server-wins identity stamp; compile-ledger events mirror
        # into the flight recorder so postmortems show recompile storms
        from trino_tpu.obs.devprofiler import DEVICE_PROFILER

        if not DEVICE_PROFILER.node_id:
            DEVICE_PROFILER.node_id = self.node_id
        DEVICE_PROFILER.attach_recorder(self.recorder)
        # the process flow ledger (obs/flowledger.py): same
        # first-server-wins identity stamp; retried transfers mirror into
        # the flight recorder so postmortems show flaky links
        from trino_tpu.obs.flowledger import FLOW_LEDGER

        if not FLOW_LEDGER.node_id:
            FLOW_LEDGER.node_id = self.node_id
        FLOW_LEDGER.attach_recorder(self.recorder)
        # OTLP export, on only when TRINO_TPU_OTLP_ENDPOINT is set: each
        # completed task ships its span dump under the query's PROPAGATED
        # trace id, so worker spans parent into the coordinator's trace
        # inside the collector too
        from trino_tpu.obs import otlp as _otlp

        self.otlp = _otlp.exporter_from_env(
            "trino-tpu-worker", instance_id=self.node_id)
        # spooled result segments (server/segments.py): result-producing
        # tasks write here; clients fetch via GET /v1/segment/{id} —
        # the worker IS the data plane, the coordinator never relays
        from trino_tpu.server.segments import SegmentStore

        self.segments = SegmentStore(node_id=self.node_id)
        self.tasks = TaskManager(
            session_factory or shared_catalog_session_factory(),
            recorder=self.recorder, otlp=self.otlp,
            segment_store=self.segments)
        self.coordinator_url = coordinator_url
        # per-worker memory pool size (reference: memory.heap-headroom /
        # query.max-memory-per-node config); None = unlimited
        env_limit = os.environ.get("TRINO_TPU_WORKER_MEMORY_BYTES")
        self.memory_limit_bytes = (
            memory_limit_bytes if memory_limit_bytes is not None
            else int(env_limit) if env_limit else None)
        # optional node host-RAM ceiling: process RSS over it sheds the
        # revocable cache tiers host-first (devcache.shed_revocable) on
        # the announce cadence; None = host RAM unmanaged
        env_host = os.environ.get("TRINO_TPU_HOST_MEMORY_LIMIT_BYTES")
        self.host_memory_limit_bytes = int(env_host) if env_host else None
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.base_url = f"http://127.0.0.1:{self.port}"
        self._serve_thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._announce_thread = threading.Thread(target=self._announce_loop, daemon=True)
        self._stop = threading.Event()

    def start(self) -> None:
        self._serve_thread.start()
        if self.coordinator_url:
            self._announce_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.segments.close()
        if self.otlp is not None:
            # flush + stop the exporter thread: a stopped instance must
            # not keep reporting metrics under its service.instance.id
            self.otlp.shutdown()

    def _announce_loop(self) -> None:
        """Periodic announce = discovery + liveness in one (reference:
        DiscoveryNodeManager polls announcements; HeartbeatFailureDetector
        pings — here the worker pushes, the coordinator ages entries out)."""
        while not self._stop.is_set():
            # piggyback the result-segment TTL sweep on the announce
            # cadence (rate-limited inside the store)
            try:
                self.segments.maybe_sweep()
            except Exception:  # noqa: BLE001 — lifecycle is best-effort
                pass
            try:
                from trino_tpu import __version__, devcache

                qmem = self.tasks.query_memory()
                if self.memory_limit_bytes is not None:
                    # the device table cache is this node's REVOCABLE
                    # tier: when queries + warm tables overflow the pool,
                    # shed cache FIRST — before the coordinator's
                    # low-memory killer would ever consider a query.
                    # DEVICE bytes only: the pool models query/device
                    # memory, and host-RAM cache bytes live in a
                    # different physical budget (counting them here
                    # would thrash the host tier on memory-tight
                    # workers while freeing nothing the pool needs).
                    # Scoped to the band where the cache IS the overflow
                    # (queries alone fit the pool): reservations are
                    # projected peaks, so a huge spilling join reports
                    # more than the pool while its partitioned passes
                    # stay under budget — eviction there cures nothing
                    # and the spill path's per-pass yield (exec/memory)
                    # already handles the real pressure.
                    q_total = sum(qmem.values())
                    over = (q_total
                            + devcache.DEVICE_CACHE.cached_bytes()
                            - self.memory_limit_bytes)
                    if over > 0 and q_total < self.memory_limit_bytes:
                        devcache.DEVICE_CACHE.yield_bytes(
                            over, reason="pool-overflow")
                # host-RAM pressure is the SEPARATE budget where the
                # two-tier shed order applies: when the process RSS
                # crosses the optional node limit, shed host pages
                # before warm-HBM entries (devcache.shed_revocable — a
                # lost host page costs one transfer to rebuild, a lost
                # HBM page costs the whole scan→decode→transfer path
                # once the host tier is gone too). CURRENT RSS only
                # (obs/metrics.current_rss_bytes): the gauge fallback
                # reports the lifetime PEAK on /proc-less platforms,
                # which would latch the shed on forever once crossed —
                # no reading, no shed.
                from trino_tpu.obs import metrics as M

                rss = M.current_rss_bytes()
                if self.host_memory_limit_bytes is not None and rss is not None:
                    over_host = rss - self.host_memory_limit_bytes
                    if over_host > 0:
                        devcache.shed_revocable(over_host)
                # sample the memory ledger on the announce cadence: live
                # per-owner bytes from ground-truth sources (the ledger's
                # event-driven live numbers never drift past one
                # heartbeat), per-pool watermarks + RSS + jax device
                # capacity into the per-node time series, and the
                # process gauges (RSS/fds/threads) so OTLP export and
                # system.metrics see LIVE values even when nobody
                # scrapes /v1/metrics
                mem_rows = self._sample_memory(qmem, rss)
                M.refresh_process_gauges()
                # device-profiler utilization tick (obs/devprofiler.py):
                # launches/sec + device-busy fraction since the last
                # heartbeat, and the newest compile-ledger events so
                # system.runtime.compiles merges cluster-wide
                from trino_tpu.obs.devprofiler import DEVICE_PROFILER

                util_sample = DEVICE_PROFILER.sample_utilization()
                compile_events = DEVICE_PROFILER.compile_rows(limit=64)
                # flow-ledger ride-alongs (obs/flowledger.py): per-link
                # rollups + stall timelines (system.runtime.transfers'
                # per-node source) and the NIC-level byte totals the
                # nodes table surfaces as net_bytes_sent/received
                from trino_tpu.obs.flowledger import FLOW_LEDGER

                flow_rows = FLOW_LEDGER.transfer_rows()
                flow_stalls = FLOW_LEDGER.stall_rows()
                net = FLOW_LEDGER.net_totals()
                wire.json_request(
                    "PUT",
                    f"{self.coordinator_url}/v1/announce/{self.node_id}",
                    {"url": self.base_url,
                     "tasks": len(self.tasks.list_info()),
                     # per-query live reservations + this worker's pool size:
                     # the coordinator's ClusterMemoryManager aggregates
                     # these (reference: node status -> ClusterMemoryPool)
                     "queryMemory": qmem,
                     "memoryBytes": sum(qmem.values()),
                     "memoryLimit": self.memory_limit_bytes,
                     # real accelerator capacity + warm-cache occupancy:
                     # admission sizes from hardware, the cache reads as
                     # revocable (server/cluster_memory.py)
                     "deviceMemoryBytes": devcache.device_memory_bytes(),
                     "deviceCacheBytes":
                         devcache.DEVICE_CACHE.cached_bytes(),
                     # host-RAM columnar tier occupancy + lifetime hits:
                     # the SECOND revocable tier (sheds first), surfaced
                     # by system.runtime.nodes (host_cache_* columns)
                     "hostCacheBytes":
                         devcache.HOST_CACHE.cached_bytes(),
                     "hostCacheHits": devcache.HOST_CACHE.hit_count(),
                     # per-pool, per-owner attribution rows (memory
                     # ledger): system.runtime.memory's per-node source
                     "memoryOwners": mem_rows,
                     # device-profiler ride-alongs: the latest utilization
                     # sample + newest compile-ledger events
                     # (system.runtime.compiles' per-node source)
                     "profiler": util_sample,
                     "compileEvents": compile_events,
                     # flow-ledger ride-alongs: per-link transfer rollups
                     # + backpressure stall rollups (the cluster-wide
                     # sources of system.runtime.transfers and the
                     # /flows surface) and NIC byte totals for the
                     # nodes table
                     "flows": flow_rows,
                     "flowStalls": flow_stalls,
                     "netBytesSent": net["sent"],
                     "netBytesReceived": net["received"],
                     "rssBytes": rss,
                     # surfaced by system.runtime.nodes (reference: the
                     # node version in NodeSystemTable rows)
                     "version": __version__},
                    timeout=5.0,
                )
            except Exception:  # noqa: BLE001 — coordinator may not be up yet
                pass
            self._stop.wait(0.5)

    def _sample_memory(self, qmem: dict, rss: Optional[int]) -> list:
        """One announce tick's memory-ledger sampling: sync live per-owner
        bytes from their ground-truth sources (task reservations, cache
        occupancy), sample the per-pool watermarks into the time-series
        ring, set the per-pool gauges, and return the per-owner rows the
        announce payload ships (``memoryOwners``)."""
        from trino_tpu import devcache
        from trino_tpu.obs import metrics as M
        from trino_tpu.obs.memledger import MEMORY_LEDGER, TOTAL_OWNER

        dev_owners = {f"query:{q}": int(b) for q, b in qmem.items()}
        dev_owners["device-cache"] = devcache.DEVICE_CACHE.cached_bytes()
        host_owners = {"host-cache": devcache.HOST_CACHE.cached_bytes()}
        # transient owners the sources above cannot see (staging scratch,
        # MV storage) ride in from the ledger's event-driven live bytes
        for row in MEMORY_LEDGER.owner_rows():
            owners = dev_owners if row["pool"] == "device" else host_owners
            if (row["owner"] != TOTAL_OWNER
                    and not row["owner"].startswith("query:")
                    and row["owner"] not in owners and row["bytes"] > 0):
                owners[row["owner"]] = row["bytes"]
        MEMORY_LEDGER.sync_pool("device", dev_owners, prefix="query:")
        MEMORY_LEDGER.sync_pool("host", host_owners)
        totals = {"device": sum(dev_owners.values()),
                  "host": sum(host_owners.values())}
        MEMORY_LEDGER.sample_watermarks(
            totals, rss_bytes=rss,
            device_total_bytes=devcache.device_memory_bytes())
        for pool, total in totals.items():
            M.MEMORY_POOL_BYTES.set(total, pool, self.node_id)
        ledger = {(r["pool"], r["owner"]): r
                  for r in MEMORY_LEDGER.owner_rows()}
        rows = []
        for pool, owners in (("device", dev_owners), ("host", host_owners)):
            for owner, nbytes in sorted(owners.items()):
                lr = ledger.get((pool, owner), {})
                rows.append({
                    "pool": pool, "owner": owner, "bytes": int(nbytes),
                    "peakBytes": max(int(lr.get("peakBytes", 0)),
                                     int(nbytes)),
                    "events": int(lr.get("events", 0)),
                })
            lr = ledger.get((pool, TOTAL_OWNER), {})
            rows.append({
                "pool": pool, "owner": TOTAL_OWNER,
                "bytes": int(totals[pool]),
                "peakBytes": max(int(lr.get("peakBytes", 0)),
                                 int(totals[pool])),
                "events": int(lr.get("events", 0)),
            })
        return rows


def _make_handler(server: WorkerServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # close keep-alive connections idle past this (the client pool's
        # idle TTL is shorter, so the client normally closes first)
        timeout = 30
        # TCP_NODELAY: headers and body flush as separate writes — with
        # Nagle on, the second write stalls behind the delayed ACK
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # quiet
            pass

        def _send(self, status: int, body: bytes = b"",
                  content_type: str = "application/json", headers: Optional[dict] = None):
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _read_body(self) -> bytes:
            n = int(self.headers.get("Content-Length", "0"))
            return self.rfile.read(n)

        def do_POST(self):
            m = _TASK_RE.match(self.path)
            if m:
                body = self._read_body()
                if not wire.verify(body, self.headers.get(wire.H_INTERNAL_AUTH)):
                    self._send(401, b'{"error": "bad internal signature"}')
                    return
                request = TaskRequest.from_bytes(body)
                # trace-context propagation: the coordinator's schedule span
                # rides in on the traceparent header so this task's spans
                # parent into the query's trace tree
                task = server.tasks.create_task(
                    request, traceparent=self.headers.get(
                        tracing.TRACEPARENT_HEADER))
                self._send(200, json.dumps(task.info()).encode())
                return
            self._send(404)

        def _authorized(self) -> bool:
            """Every /v1/task route carries the cluster's HMAC (wire.sign of
            the body — empty for GET/DELETE), not just task creation: result
            pages and cancellation are control-plane surface too."""
            if wire.verify(b"", self.headers.get(wire.H_INTERNAL_AUTH)):
                return True
            self._send(401, b'{"error": "bad internal signature"}')
            return False

        def do_GET(self):
            m = _SEGMENT_RE.match(self.path)
            if m:
                # spooled result segments: NO cluster HMAC — the id is an
                # unguessable capability and the caller is an external
                # protocol client (the reference's pre-signed segment
                # URI model); range/ack semantics live in segments.py
                from trino_tpu.server.segments import segment_response

                status, body, headers, ctype = segment_response(
                    server.segments, m.group(1),
                    self.headers.get("Range"))
                self._send(status, body, ctype, headers)
                return
            m = _RESULTS_RE.match(self.path)
            if m:
                if not self._authorized():
                    return
                task = server.tasks.get(m.group(1))
                if task is None:
                    self._send(404, b'{"error": "no such task"}')
                    return
                pages, next_token, complete, failure = task.output.poll(
                    int(m.group(3)), buffer_id=int(m.group(2)))
                headers = {
                    wire.H_PAGE_TOKEN: m.group(3),
                    wire.H_NEXT_TOKEN: str(next_token),
                    wire.H_BUFFER_COMPLETE: "true" if complete else "false",
                }
                if failure:
                    headers[wire.H_TASK_FAILED] = failure.replace("\n", " ")[:900]
                self._send(200, wire.frame_pages(pages), wire.MEDIA_PAGES, headers)
                return
            m = _STATUS_RE.match(self.path)
            if m:
                if not self._authorized():
                    return
                task = server.tasks.get(m.group(1))
                if task is None:
                    self._send(404, b'{"error": "no such task"}')
                    return
                self._send(200, json.dumps(task.info()).encode())
                return
            m = _SPANS_RE.match(self.path)
            if m:
                if not self._authorized():
                    return
                task = server.tasks.get(m.group(1))
                if task is None:
                    self._send(404, b'{"error": "no such task"}')
                    return
                self._send(200, json.dumps({
                    "taskId": task.request.task_id,
                    "traceId": task.tracer.trace_id,
                    "spans": task.tracer.to_dicts(),
                }).encode())
                return
            m = _RECORDER_RE.match(self.path)
            if m:
                if not self._authorized():
                    return
                # the PROCESS ring, not a per-task record: a postmortem
                # wants the context AROUND the failure (what else ran,
                # which spans closed last) — and it still answers after
                # the task itself was pruned from the manager
                from trino_tpu.obs.flowledger import FLOW_LEDGER
                from trino_tpu.obs.memledger import MEMORY_LEDGER

                self._send(200, json.dumps({
                    "nodeId": server.node_id,
                    "taskId": m.group(1),
                    "taskKnown": server.tasks.get(m.group(1)) is not None,
                    "records": server.recorder.snapshot(),
                    # merged memory snapshot for OOM postmortems: pool
                    # watermarks + top consumers + recent sheds
                    "memory": MEMORY_LEDGER.memory_snapshot(),
                    # data-plane snapshot: per-link rollups + last
                    # transfers + stall timeline, so a FAILED postmortem
                    # shows what was moving when the query died
                    "flows": FLOW_LEDGER.flow_snapshot(),
                }).encode())
                return
            if self.path == "/v1/metrics":
                from trino_tpu.obs.metrics import render_registry

                self._send(200, render_registry().encode(),
                           "text/plain; version=0.0.4")
                return
            if self.path == "/v1/info":
                self._send(200, json.dumps(
                    {"nodeId": server.node_id, "state": "ACTIVE",
                     "tasks": server.tasks.list_info()}).encode())
                return
            self._send(404)

        def do_DELETE(self):
            m = _SEGMENT_RE.match(self.path)
            if m:
                # client ack: the segment was fetched — delete it now
                # instead of waiting out the TTL (idempotent: a repeated
                # ack of a gone segment is still a 204)
                server.segments.ack(m.group(1))
                self._send(204)
                return
            m = _RESULTS_RE.match(self.path)
            if m:
                if not self._authorized():
                    return
                # final ack: this consumer is done with the buffer
                task = server.tasks.get(m.group(1))
                if task is not None:
                    task.output.destroy_consumer(int(m.group(2)))
                self._send(204)
                return
            m = _TASK_RE.match(self.path)
            if m:
                if not self._authorized():
                    return
                server.tasks.cancel(m.group(1))
                self._send(204)
                return
            self._send(404)

    return Handler


def main() -> None:
    """Entry point: ``python -m trino_tpu.server.worker --port N
    --coordinator URL``."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--node-id", default=None)
    args = ap.parse_args()
    w = WorkerServer(args.port, args.coordinator, args.node_id)
    w.start()
    print(json.dumps({"nodeId": w.node_id, "url": w.base_url}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        w.stop()


if __name__ == "__main__":
    main()
