"""Resource groups: admission control for query dispatch.

Reference: ``execution/resourcegroups/InternalResourceGroup.java:75`` + the
resource-group manager SPI — a TREE of groups with concurrency/queue
limits: a query queues when its group (or any ancestor) is at its hard
concurrency limit, and as running queries finish, freed slots dispatch
queued queries chosen by weighted scheduling across sibling subgroups
(``WeightedScheduler``'s role). ``ResourceGroup`` is the flat single-group
gate (kept as the default); ``ResourceGroupManager`` adds per-user
subgroup trees (the ``user.${USER}`` selector template of the reference's
resource-group configuration files).
"""
from __future__ import annotations

import collections
import threading
from typing import Deque, Dict, Optional


class ResourceGroup:
    """Bounded-concurrency admission gate with a FIFO queue."""

    def __init__(self, name: str = "global", hard_concurrency_limit: int = 16,
                 max_queued: int = 200):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self._lock = threading.Lock()
        self._running = 0
        self._queue: Deque[threading.Event] = collections.deque()

    def submit(self, timeout: Optional[float] = None,
               user: str = "anonymous") -> bool:
        """Block until admitted (True) or rejected/timed out (False).
        Rejection happens immediately when the queue is full (the
        reference's QUERY_QUEUE_FULL error). ``user`` is ignored by the
        flat group (one queue for everyone); ResourceGroupManager routes
        it to the per-user subgroup."""
        with self._lock:
            if self._running < self.hard_concurrency_limit and not self._queue:
                self._running += 1
                return True
            if len(self._queue) >= self.max_queued:
                return False
            gate = threading.Event()
            self._queue.append(gate)
        if not gate.wait(timeout):
            with self._lock:
                try:
                    self._queue.remove(gate)
                except ValueError:
                    return True  # raced with finish(): already admitted
            return False
        return True

    def finish(self, user: str = "anonymous") -> None:
        with self._lock:
            if self._queue:
                gate = self._queue.popleft()
                gate.set()  # hand the slot over; _running unchanged
            else:
                self._running = max(0, self._running - 1)

    def info(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "running": self._running,
                "queued": len(self._queue),
                "hardConcurrencyLimit": self.hard_concurrency_limit,
            }


class ResourceGroupManager:
    """Per-user subgroup tree under one root: global.user:<name>.

    Admission needs a slot in BOTH the user's subgroup and the root; when a
    query finishes, the freed root slot goes to the queued subgroup with
    the smallest running/weight ratio (weighted fair scheduling,
    reference: InternalResourceGroup.internalStartNext + the weighted
    scheduling policy). Subgroups are created on first use from a template
    (the ``user.${USER}`` expansion of resource-group config files)."""

    def __init__(self, root_concurrency_limit: int = 16,
                 per_user_concurrency_limit: int = 8,
                 per_user_max_queued: int = 100,
                 user_weights: Optional[Dict[str, int]] = None):
        self.root_limit = root_concurrency_limit
        self.user_limit = per_user_concurrency_limit
        self.user_max_queued = per_user_max_queued
        self.user_weights = dict(user_weights or {})
        self._lock = threading.Lock()
        self._root_running = 0
        # user -> state
        self._groups: Dict[str, dict] = {}

    # compatibility with the flat ResourceGroup surface (coordinator calls
    # submit()/finish() without a user for internal work)
    def submit(self, timeout: Optional[float] = None, user: str = "anonymous") -> bool:
        g = self._group(user)
        with self._lock:
            if self._can_start(g):
                self._start(g)
                return True
            if len(g["queue"]) >= self.user_max_queued:
                return False
            gate = threading.Event()
            g["queue"].append(gate)
        if not gate.wait(timeout):
            with self._lock:
                try:
                    g["queue"].remove(gate)
                except ValueError:
                    return True  # raced with a dispatch: already admitted
            return False
        return True

    def finish(self, user: str = "anonymous") -> None:
        with self._lock:
            g = self._groups.get(user)
            if g is not None:
                g["running"] = max(0, g["running"] - 1)
            self._root_running = max(0, self._root_running - 1)
            self._dispatch_next()

    def info(self) -> dict:
        with self._lock:
            return {
                "name": "global",
                "running": self._root_running,
                "queued": sum(len(g["queue"]) for g in self._groups.values()),
                "hardConcurrencyLimit": self.root_limit,
                "subgroups": {
                    u: {"running": g["running"], "queued": len(g["queue"]),
                        "weight": g["weight"]}
                    for u, g in sorted(self._groups.items())
                },
            }

    # ----------------------------------------------------------- internals
    def _group(self, user: str) -> dict:
        with self._lock:
            g = self._groups.get(user)
            if g is None:
                g = {"running": 0, "queue": collections.deque(),
                     "weight": max(1, int(self.user_weights.get(user, 1)))}
                self._groups[user] = g
            return g

    def _can_start(self, g: dict) -> bool:
        return (g["running"] < self.user_limit
                and self._root_running < self.root_limit)

    def _start(self, g: dict) -> None:
        g["running"] += 1
        self._root_running += 1

    def _dispatch_next(self) -> None:
        """Weighted fair pick among queued subgroups with capacity: the
        eligible group with the smallest running/weight starts next."""
        while self._root_running < self.root_limit:
            eligible = [
                g for g in self._groups.values()
                if g["queue"] and g["running"] < self.user_limit
            ]
            if not eligible:
                return
            g = min(eligible, key=lambda g: g["running"] / g["weight"])
            gate = g["queue"].popleft()
            self._start(g)
            gate.set()
