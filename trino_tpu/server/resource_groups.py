"""Resource groups: admission control for query dispatch.

Reference: ``execution/resourcegroups/InternalResourceGroup.java:75`` + the
resource-group manager SPI — a tree of groups with concurrency/queue
limits; queries QUEUE when their group is at its hard concurrency limit and
dispatch as running queries finish. This is the flat single-group core of
that design (per-user subgroup trees are configuration, not mechanism).
"""
from __future__ import annotations

import collections
import threading
from typing import Deque, Optional


class ResourceGroup:
    """Bounded-concurrency admission gate with a FIFO queue."""

    def __init__(self, name: str = "global", hard_concurrency_limit: int = 16,
                 max_queued: int = 200):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self._lock = threading.Lock()
        self._running = 0
        self._queue: Deque[threading.Event] = collections.deque()

    def submit(self, timeout: Optional[float] = None) -> bool:
        """Block until admitted (True) or rejected/timed out (False).
        Rejection happens immediately when the queue is full (the
        reference's QUERY_QUEUE_FULL error)."""
        with self._lock:
            if self._running < self.hard_concurrency_limit and not self._queue:
                self._running += 1
                return True
            if len(self._queue) >= self.max_queued:
                return False
            gate = threading.Event()
            self._queue.append(gate)
        if not gate.wait(timeout):
            with self._lock:
                try:
                    self._queue.remove(gate)
                except ValueError:
                    return True  # raced with finish(): already admitted
            return False
        return True

    def finish(self) -> None:
        with self._lock:
            if self._queue:
                gate = self._queue.popleft()
                gate.set()  # hand the slot over; _running unchanged
            else:
                self._running = max(0, self._running - 1)

    def info(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "running": self._running,
                "queued": len(self._queue),
                "hardConcurrencyLimit": self.hard_concurrency_limit,
            }
