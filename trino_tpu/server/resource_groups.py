"""Resource groups: hierarchical multi-tenant admission control.

Reference: ``execution/resourcegroups/InternalResourceGroup.java:75`` +
``FileResourceGroupConfigurationManager`` — a TREE of groups with
concurrency/queue/memory limits, queries mapped to a group by a
first-match SELECTOR chain over (user, source, session property), and
freed slots handed to queued sibling groups by WEIGHTED FAIR scheduling
(deficit counters proportional to group weight, never a global FIFO).

Three layers live here:

- the **config layer** — :class:`GroupSpec` / :class:`SelectorSpec`
  parsed and VALIDATED from a JSON document (``root_groups`` +
  ``selectors``), loadable from the file named by
  ``TRINO_TPU_RESOURCE_GROUPS_CONFIG`` (validation errors fail server
  start, not the first query). Group name segments may be the
  ``${USER}`` template: the node instantiates per user on first match
  (the reference's per-user expansion of ``user.${USER}``).

- the **runtime tree** — :class:`ResourceGroupTree`: per-group bounded
  queues, ``hard_concurrency_limit`` enforced along the whole ancestor
  chain, ``memory_limit_bytes`` checked against the memory ledger's
  live per-query bytes (a group over its memory limit QUEUES new work
  until the ledger shows headroom — never fails it), per-group
  ``queue_timeout_ms`` aging parked queries out as typed
  ``EXCEEDED_QUEUE_TIMEOUT`` failures, and weighted-fair dequeue among
  eligible sibling groups via weight-proportional deficit counters.

- the **cache carve-out registry** — :class:`CacheShares` +
  the current-group context: each group may reserve a ``cache_share``
  fraction of every cache tier's byte budget; the cache eviction loops
  (devcache/cache.py, devcache/hostcache.py, cache/result_cache.py)
  prefer victims from groups OVER their share, so one tenant's scan
  storm cannot evict another tenant's warm state.

``ResourceGroup`` (flat gate) and ``ResourceGroupManager`` (per-user
subgroup manager) remain as the blocking-submit compatibility surface
for callers that inject their own admission gate; a coordinator built
without one runs the tree.

This module is import-clean standalone (stdlib only at import time) so
the docs gate (``tools/check_resource_group_docs.py``) can load it
without the package/jax; metric fan-out imports lazily inside methods.
"""
from __future__ import annotations

import collections
import contextvars
import json
import os
import re
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

ENV_CONFIG = "TRINO_TPU_RESOURCE_GROUPS_CONFIG"

# the typed failure code a query ages out of its group queue with
# (reference: StandardErrorCode.EXCEEDED_QUEUE_TIMEOUT? no — QUERY_QUEUE_FULL
# covers rejection; the queue-timeout failure is EXCEEDED_TIME_LIMIT's
# admission sibling). Clients match on this token in the failure message.
EXCEEDED_QUEUE_TIMEOUT = "EXCEEDED_QUEUE_TIMEOUT"

# the ${USER} template segment of group paths (per-user instantiation)
USER_TEMPLATE = "${USER}"

# every selector field a config may use; tools/check_resource_group_docs.py
# requires each to be documented in README's "Resource groups" section
SELECTOR_FIELDS = ("user", "source", "session_property", "group")

# every per-group limit knob a config may set; same docs-gate contract
GROUP_KNOBS = ("name", "hard_concurrency_limit", "max_queued",
               "memory_limit_bytes", "weight", "cache_share",
               "queue_timeout_ms", "sub_groups")


# --------------------------------------------------------------- config
class ConfigError(ValueError):
    """Invalid resource-group configuration — raised at parse/validation
    time (server start), never at query time."""


class GroupSpec:
    """One declared group: limits + optional sub-group specs. A spec whose
    name is ``${USER}`` is a TEMPLATE: matching queries instantiate one
    runtime node per user with this spec's limits."""

    def __init__(self, name: str, hard_concurrency_limit: int = 16,
                 max_queued: int = 200,
                 memory_limit_bytes: Optional[int] = None,
                 weight: int = 1, cache_share: Optional[float] = None,
                 queue_timeout_ms: Optional[int] = None,
                 sub_groups: Optional[List["GroupSpec"]] = None):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self.memory_limit_bytes = memory_limit_bytes
        self.weight = weight
        self.cache_share = cache_share
        self.queue_timeout_ms = queue_timeout_ms
        self.sub_groups = list(sub_groups or [])

    @classmethod
    def from_dict(cls, d: dict, path: str = "") -> "GroupSpec":
        if not isinstance(d, dict):
            raise ConfigError(f"group at '{path or '<root>'}' must be an "
                              f"object, got {type(d).__name__}")
        unknown = set(d) - set(GROUP_KNOBS)
        if unknown:
            raise ConfigError(
                f"group '{path or d.get('name', '?')}': unknown knob(s) "
                f"{sorted(unknown)} (known: {', '.join(GROUP_KNOBS)})")
        name = d.get("name")
        if not name or not isinstance(name, str):
            raise ConfigError(f"group under '{path or '<root>'}' needs a "
                              "non-empty string 'name'")
        if name != USER_TEMPLATE and not re.fullmatch(r"[A-Za-z0-9_\-]+",
                                                      name):
            raise ConfigError(
                f"group name '{name}' must be alphanumeric/_/- or the "
                f"{USER_TEMPLATE} template")
        full = f"{path}.{name}" if path else name

        def _int(knob, default, minimum):
            v = d.get(knob, default)
            if v is None and default is None:
                return None
            if not isinstance(v, int) or isinstance(v, bool) or v < minimum:
                raise ConfigError(f"group '{full}': {knob} must be an "
                                  f"integer >= {minimum}, got {v!r}")
            return v

        share = d.get("cache_share")
        if share is not None and (not isinstance(share, (int, float))
                                  or isinstance(share, bool)
                                  or not 0.0 <= float(share) <= 1.0):
            raise ConfigError(f"group '{full}': cache_share must be a "
                              f"fraction in [0, 1], got {share!r}")
        subs = d.get("sub_groups") or []
        if not isinstance(subs, list):
            raise ConfigError(f"group '{full}': sub_groups must be a list")
        spec = cls(
            name=name,
            hard_concurrency_limit=_int("hard_concurrency_limit", 16, 1),
            max_queued=_int("max_queued", 200, 0),
            memory_limit_bytes=_int("memory_limit_bytes", None, 1),
            weight=_int("weight", 1, 1),
            cache_share=float(share) if share is not None else None,
            queue_timeout_ms=_int("queue_timeout_ms", None, 1),
            sub_groups=[cls.from_dict(s, full) for s in subs],
        )
        seen = set()
        for s in spec.sub_groups:
            if s.name in seen:
                raise ConfigError(f"group '{full}': duplicate sub-group "
                                  f"'{s.name}'")
            seen.add(s.name)
        return spec


class SelectorSpec:
    """One selector of the first-match chain: optional ``user`` /
    ``source`` regexes (full-match) + optional ``session_property``
    ``{"name": ..., "value": ...}`` equality, mapping to a declared
    ``group`` path (segments may be ``${USER}``)."""

    def __init__(self, group: str, user: Optional[str] = None,
                 source: Optional[str] = None,
                 session_property: Optional[dict] = None):
        self.group = group
        self.user_re = re.compile(user) if user else None
        self.source_re = re.compile(source) if source else None
        self.session_property = session_property

    @classmethod
    def from_dict(cls, d: dict, index: int) -> "SelectorSpec":
        if not isinstance(d, dict):
            raise ConfigError(f"selector #{index} must be an object")
        unknown = set(d) - set(SELECTOR_FIELDS)
        if unknown:
            raise ConfigError(
                f"selector #{index}: unknown field(s) {sorted(unknown)} "
                f"(known: {', '.join(SELECTOR_FIELDS)})")
        group = d.get("group")
        if not group or not isinstance(group, str):
            raise ConfigError(f"selector #{index} needs a 'group' path")
        for field in ("user", "source"):
            v = d.get(field)
            if v is not None:
                if not isinstance(v, str):
                    raise ConfigError(f"selector #{index}: {field} must "
                                      "be a regex string")
                try:
                    re.compile(v)
                except re.error as e:
                    raise ConfigError(
                        f"selector #{index}: bad {field} regex: {e}")
        sp = d.get("session_property")
        if sp is not None and (not isinstance(sp, dict)
                               or not isinstance(sp.get("name"), str)
                               or "value" not in sp):
            raise ConfigError(
                f"selector #{index}: session_property must be "
                '{"name": <property>, "value": <expected>}')
        return cls(group=group, user=d.get("user"), source=d.get("source"),
                   session_property=sp)

    def matches(self, user: str, source: str, properties: dict) -> bool:
        if self.user_re is not None and not self.user_re.fullmatch(user):
            return False
        if self.source_re is not None and not self.source_re.fullmatch(
                source or ""):
            return False
        if self.session_property is not None:
            got = properties.get(self.session_property["name"])
            if got is None or str(got) != str(
                    self.session_property["value"]):
                return False
        return True


# the zero-config default: one root group, everyone maps to it — the
# exact admission behavior of the flat pre-tree gate
DEFAULT_CONFIG = {
    "root_groups": [
        {"name": "global", "hard_concurrency_limit": 16,
         "max_queued": 200},
    ],
    "selectors": [{"group": "global"}],
}


def parse_config(doc: dict) -> Tuple[List[GroupSpec], List[SelectorSpec]]:
    """Validated (root specs, selector chain) from a config document.
    Every selector's group path must resolve through declared specs
    (template segments match ``${USER}`` specs)."""
    if not isinstance(doc, dict):
        raise ConfigError("resource-group config must be a JSON object")
    unknown = set(doc) - {"root_groups", "selectors"}
    if unknown:
        raise ConfigError(f"unknown top-level key(s) {sorted(unknown)} "
                          "(known: root_groups, selectors)")
    roots_doc = doc.get("root_groups")
    if not isinstance(roots_doc, list) or not roots_doc:
        raise ConfigError("config needs a non-empty root_groups list")
    roots = [GroupSpec.from_dict(g) for g in roots_doc]
    seen = set()
    for r in roots:
        if r.name == USER_TEMPLATE:
            raise ConfigError("a root group cannot be the ${USER} template")
        if r.name in seen:
            raise ConfigError(f"duplicate root group '{r.name}'")
        seen.add(r.name)
    selectors_doc = doc.get("selectors")
    if not isinstance(selectors_doc, list) or not selectors_doc:
        raise ConfigError("config needs a non-empty selectors list")
    selectors = [SelectorSpec.from_dict(s, i)
                 for i, s in enumerate(selectors_doc)]
    for i, sel in enumerate(selectors):
        if _spec_for_path(roots, sel.group.split(".")) is None:
            raise ConfigError(
                f"selector #{i}: group '{sel.group}' does not match any "
                "declared group path")
    total_share = _sum_shares(roots)
    if total_share > 1.0 + 1e-9:
        raise ConfigError(
            f"cache_share fractions sum to {total_share:g} > 1.0")
    return roots, selectors


def _sum_shares(specs: List[GroupSpec]) -> float:
    total = 0.0
    for s in specs:
        if s.cache_share:
            total += s.cache_share
        total += _sum_shares(s.sub_groups)
    return total


def _spec_for_path(roots: List[GroupSpec],
                   segments: List[str]) -> Optional[GroupSpec]:
    level = roots
    spec = None
    for seg in segments:
        spec = None
        for cand in level:
            if cand.name == seg or cand.name == USER_TEMPLATE:
                spec = cand
                break
        if spec is None:
            return None
        level = spec.sub_groups
    return spec


def load_config_file(path: str) -> Tuple[List[GroupSpec],
                                         List[SelectorSpec]]:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise ConfigError(f"cannot read resource-group config {path}: {e}")
    except json.JSONDecodeError as e:
        raise ConfigError(f"resource-group config {path} is not valid "
                          f"JSON: {e}")
    return parse_config(doc)


def config_from_env() -> Tuple[List[GroupSpec], List[SelectorSpec]]:
    """The server-start entry point: the file named by
    ``TRINO_TPU_RESOURCE_GROUPS_CONFIG``, else the zero-config default."""
    path = os.environ.get(ENV_CONFIG)
    if path:
        return load_config_file(path)
    return parse_config(DEFAULT_CONFIG)


# ---------------------------------------------------- cache carve-outs
# the current query's resource group, set by the executor lane around
# execution (and by the dispatch thread around an index serve): cache
# tiers read it at admission time to tag entries with their owner group
_CURRENT_GROUP: contextvars.ContextVar = contextvars.ContextVar(
    "trino_tpu_resource_group", default=None)


def set_current_group(name: Optional[str]):
    """Bind the calling context's resource group; returns the reset
    token (pass to :func:`reset_current_group`)."""
    return _CURRENT_GROUP.set(name)


def reset_current_group(token) -> None:
    _CURRENT_GROUP.reset(token)


def current_group() -> Optional[str]:
    return _CURRENT_GROUP.get()


class CacheShares:
    """Per-group cache carve-out fractions, one registry per process
    (every cache tier consults the same shares). A group's share is the
    fraction of a tier's byte budget it is entitled to KEEP under
    pressure: the eviction loops prefer victims from groups holding
    more than ``share × max_bytes``; groups without a configured share
    split the unreserved remainder."""

    def __init__(self):
        self._lock = threading.Lock()
        self._shares: Dict[str, float] = {}
        self._default = 1.0

    def configure(self, shares: Dict[str, float]) -> None:
        with self._lock:
            self._shares = dict(shares)
            reserved = sum(self._shares.values())
            self._default = max(0.05, 1.0 - reserved)

    def share_for(self, group: Optional[str]) -> float:
        with self._lock:
            if group is not None and group in self._shares:
                return self._shares[group]
            return self._default

    def over_share(self, group: Optional[str], group_bytes: int,
                   max_bytes: int) -> bool:
        """Is ``group`` holding more than its carve-out of a tier whose
        budget is ``max_bytes``? Ungrouped bytes count against the
        unreserved remainder."""
        return group_bytes > self.share_for(group) * max_bytes

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._shares)


# the process-wide registry (mirrors DEVICE_CACHE / MEMORY_LEDGER):
# ResourceGroupTree.configure_cache_shares() fills it at server start
CACHE_SHARES = CacheShares()


# ----------------------------------------------------------- runtime tree
class _GroupNode:
    """One runtime group: live counters + the per-group queue (leaf
    groups queue queries; intermediate groups only aggregate). All
    mutation happens under the owning tree's lock."""

    __slots__ = ("name", "segment", "spec", "parent", "children", "queue",
                 "running", "served", "deficit", "query_ids",
                 "dequeued", "timed_out")

    def __init__(self, name: str, segment: str, spec: GroupSpec,
                 parent: Optional["_GroupNode"]):
        self.name = name          # full dotted path
        self.segment = segment    # last path segment (template-expanded)
        self.spec = spec
        self.parent = parent
        self.children: "collections.OrderedDict[str, _GroupNode]" = (
            collections.OrderedDict())
        self.queue: Deque[dict] = collections.deque()
        self.running = 0          # queries running in this subtree
        self.served = 0           # serving-index hits (concurrency-free)
        self.deficit = 0.0        # weighted-fair deficit counter
        self.query_ids: set = set()   # running query ids in this subtree
        self.dequeued = 0
        self.timed_out = 0

    def chain(self) -> List["_GroupNode"]:
        nodes = []
        node = self
        while node is not None:
            nodes.append(node)
            node = node.parent
        return nodes


class ResourceGroupTree:
    """The hierarchical admission runtime the dispatcher drains.

    The dispatch thread classifies (``select``) and parks
    (``enqueue``); executor lanes pull (``dequeue``) — a weighted-fair
    pick that walks the tree top-down choosing among ELIGIBLE children
    by deficit counter (each candidate's deficit grows by its weight
    each round; the winner pays the round's total weight), so siblings
    with weights 3:1 drain 3:1 under sustained load instead of global
    FIFO order. Eligibility at every level = concurrency headroom AND
    memory headroom (live per-query bytes from the memory probe under
    ``memory_limit_bytes``) along the whole ancestor chain.
    """

    def __init__(self, roots: Optional[List[GroupSpec]] = None,
                 selectors: Optional[List[SelectorSpec]] = None):
        if roots is None or selectors is None:
            roots, selectors = parse_config(DEFAULT_CONFIG)
        self._specs = roots
        self._selectors = selectors
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._nodes: Dict[str, _GroupNode] = {}
        self._roots: List[_GroupNode] = [
            self._instantiate(spec, None, spec.name) for spec in roots]
        self._query_groups: Dict[str, _GroupNode] = {}
        # live per-query bytes source (the memory ledger / cluster memory
        # manager): () -> {query_id: bytes}
        self._memory_probe: Optional[Callable[[], Dict[str, int]]] = None
        # recent dequeue timestamps — the drain-rate estimator behind
        # honest Retry-After values (satellite: no more constant 1.0)
        self._drains: Deque[float] = collections.deque(maxlen=64)
        self._closed = False
        self.configure_cache_shares()

    # ------------------------------------------------------------ build
    def _instantiate(self, spec: GroupSpec, parent: Optional[_GroupNode],
                     segment: str) -> _GroupNode:
        name = (f"{parent.name}.{segment}" if parent else segment)
        node = _GroupNode(name, segment, spec, parent)
        self._nodes[name] = node
        if parent is not None:
            parent.children[segment] = node
        for sub in spec.sub_groups:
            if sub.name != USER_TEMPLATE:
                self._instantiate(sub, node, sub.name)
        return node

    def configure_cache_shares(self) -> None:
        """Publish every configured ``cache_share`` (template shares
        publish lazily as their per-user nodes instantiate)."""
        shares = {name: node.spec.cache_share
                  for name, node in self._nodes.items()
                  if node.spec.cache_share}
        CACHE_SHARES.configure(shares)

    def set_memory_probe(
            self, probe: Callable[[], Dict[str, int]]) -> None:
        self._memory_probe = probe

    # ---------------------------------------------------------- selection
    def select(self, user: str = "anonymous", source: str = "",
               session_properties: Optional[dict] = None) -> str:
        """First-match selector chain -> full group path, instantiating
        ``${USER}`` template nodes on first use. Unmatched queries fall
        into the first root group (admission must never be undefined)."""
        props = session_properties or {}
        target = None
        for sel in self._selectors:
            if sel.matches(user, source, props):
                target = sel.group
                break
        if target is None:
            target = self._specs[0].name
        path = target.replace(USER_TEMPLATE, _safe_segment(user))
        template_path = target
        with self._lock:
            node = self._nodes.get(path)
            if node is None:
                node = self._materialize(template_path, path)
        return node.name

    def _materialize(self, template_path: str, path: str) -> _GroupNode:
        """Create the runtime node(s) for a template-expanded path
        (lock held)."""
        t_segments = template_path.split(".")
        segments = path.split(".")
        node = None
        prefix = ""
        new_share = False
        for t_seg, seg in zip(t_segments, segments):
            prefix = f"{prefix}.{seg}" if prefix else seg
            existing = self._nodes.get(prefix)
            if existing is None:
                parent_specs = (self._specs if node is None
                                else node.spec.sub_groups)
                spec = None
                for cand in parent_specs:
                    if cand.name == t_seg:
                        spec = cand
                        break
                if spec is None:
                    raise KeyError(
                        f"resource group path '{path}' does not resolve "
                        f"at '{prefix}'")
                existing = _GroupNode(prefix, seg, spec, node)
                self._nodes[prefix] = existing
                if node is not None:
                    node.children[seg] = existing
                else:
                    self._roots.append(existing)
                if spec.cache_share:
                    new_share = True
            node = existing
        if new_share:
            self.configure_cache_shares()
        return node

    # ---------------------------------------------------------- admission
    def queue_state(self, group: str) -> Tuple[int, int]:
        """(queued, max_queued) for the group — the precheck read."""
        with self._lock:
            node = self._nodes.get(group)
            if node is None:
                return (0, 0)
            return (len(node.queue), node.spec.max_queued)

    def enqueue(self, group: str, query_id: str, item,
                now: Optional[float] = None) -> int:
        """Park one query in its group queue; returns the number queued
        AHEAD of it. Raises ``IndexError`` (typed by the dispatch
        adapter) when the group queue is at ``max_queued``."""
        now = time.time() if now is None else now
        with self._lock:
            node = self._nodes[group]
            ahead = len(node.queue)
            if ahead >= node.spec.max_queued:
                raise IndexError(ahead)
            node.queue.append({"query_id": query_id, "item": item,
                               "enqueued_at": now})
            self._cond.notify()
        self._set_depth_gauge(group)
        return ahead

    def dequeue(self, timeout: float = 0.5):
        """The weighted-fair drain step one executor lane runs: returns
        ``("run", item, group, waited_s)`` for the next admitted query,
        ``("aged", item, group, waited_s)`` for a query parked past its
        group's ``queue_timeout_ms`` (the caller fails it typed), or
        ``None`` on timeout/close."""
        deadline = time.monotonic() + timeout
        result = None
        gauges = None
        with self._lock:
            while True:
                aged = self._sweep_aged_locked()
                if aged is not None:
                    entry, node, waited = aged
                    node.timed_out += 1
                    result = ("aged", entry["item"], node.name, waited)
                    gauges = (node.name, len(node.queue), node.running)
                    break
                picked = self._pick_locked()
                if picked is not None:
                    entry, node, waited = picked
                    node.dequeued += 1
                    for anc in node.chain():
                        anc.running += 1
                        anc.query_ids.add(entry["query_id"])
                    self._query_groups[entry["query_id"]] = node
                    self._drains.append(time.time())
                    result = ("run", entry["item"], node.name, waited)
                    gauges = (node.name, len(node.queue), node.running)
                    # cascade: finish()/enqueue() wake ONE lane; if more
                    # work is still parked, pass the baton so a second
                    # admittable query (memory freed, sibling slot) is
                    # picked without waiting out the take timeout
                    if any(n.queue for n in self._nodes.values()):
                        self._cond.notify()
                    break
                if self._closed:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                # lint: allow(blocking-under-lock) Condition.wait RELEASES the lock while parked
                self._cond.wait(remaining)
        # metric fan-out OUTSIDE the lock (lock-discipline gate)
        self._publish_gauges(*gauges)
        try:
            from trino_tpu.obs import metrics as M

            M.RESOURCE_GROUP_QUEUE_SECONDS.observe(result[3], result[2])
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass
        return result

    def _sweep_aged_locked(self):
        now = time.time()
        for node in self._nodes.values():
            tmo = node.spec.queue_timeout_ms
            if tmo is None or not node.queue:
                continue
            head = node.queue[0]
            waited = now - head["enqueued_at"]
            if waited * 1000.0 >= tmo:
                node.queue.popleft()
                return (head, node, waited)
        return None

    def _pick_locked(self):
        """One weighted-fair pick: walk from the root level down,
        choosing among eligible siblings by deficit counter."""
        level = self._roots
        while True:
            candidates = [n for n in level if self._eligible_locked(n)]
            if not candidates:
                return None
            if len(candidates) == 1:
                chosen = candidates[0]
            else:
                for c in candidates:
                    c.deficit += c.spec.weight
                chosen = max(candidates, key=lambda c: (c.deficit, c.name))
                chosen.deficit -= sum(c.spec.weight for c in candidates)
            if chosen.queue:
                entry = chosen.queue.popleft()
                return (entry, chosen,
                        time.time() - entry["enqueued_at"])
            level = list(chosen.children.values())

    def _eligible_locked(self, node: _GroupNode) -> bool:
        """Can this subtree start one more query right now? Concurrency
        and memory headroom at this node, and EITHER a queued query here
        or an eligible child — fully recursive, so a pick never descends
        into a subtree that cannot admit (a group over its
        ``memory_limit_bytes`` queues; it never fails the query)."""
        if node.running >= node.spec.hard_concurrency_limit:
            return False
        if node.spec.memory_limit_bytes is not None:
            if self._subtree_bytes_locked(node) >= \
                    node.spec.memory_limit_bytes:
                return False
        if node.queue:
            return True
        return any(self._eligible_locked(c)
                   for c in node.children.values())

    def _subtree_bytes_locked(self, node: _GroupNode) -> int:
        probe = self._memory_probe
        if probe is None or not node.query_ids:
            return 0
        try:
            by_query = probe()
        except Exception:  # noqa: BLE001 — a broken probe never wedges
            return 0      # admission (memory gate degrades open)
        return sum(int(by_query.get(qid, 0)) for qid in node.query_ids)

    def finish(self, query_id: str) -> None:
        """Terminal hook: release the query's slot along its group chain
        and wake the drain loop (a freed slot may admit a sibling)."""
        with self._lock:
            node = self._query_groups.pop(query_id, None)
            if node is None:
                return
            for anc in node.chain():
                anc.running = max(0, anc.running - 1)
                anc.query_ids.discard(query_id)
            # ONE waiter: a freed slot admits at most one parked query
            # directly; dequeue cascades a further notify while queued
            # work remains. notify_all() here woke EVERY idle lane per
            # completion — measurably slower serving on small machines
            # (8 wakeups + tree scans per query for nothing).
            self._cond.notify()
        self._set_gauges(node)

    def note_served(self, group: str) -> None:
        """A serving-index hit for this group: concurrency-free, but it
        must be auditable (the fairness story covers cached repeats)."""
        with self._lock:
            node = self._nodes.get(group)
            if node is None:
                return
            for anc in node.chain():
                anc.served += 1
        try:
            from trino_tpu.obs import metrics as M

            M.RESOURCE_GROUP_SERVED.inc(1, group)
        except Exception:  # noqa: BLE001 — accounting never fails serving
            pass

    # ------------------------------------------------------- retry-after
    def drain_rate(self) -> float:
        """Recent queue drain rate in queries/second (0.0 = no recent
        drains observed)."""
        with self._lock:
            drains = list(self._drains)
        if len(drains) < 2:
            return 0.0
        window = drains[-1] - drains[0]
        if window <= 0:
            return 0.0
        return (len(drains) - 1) / window

    def retry_after_s(self, queued_ahead: int,
                      fallback: float = 1.0) -> float:
        """Honest Retry-After: the time the drain rate needs to clear
        the queue ahead (clamped to [0.1, 30]); the fallback covers a
        queue that has never drained."""
        rate = self.drain_rate()
        if rate <= 0.0:
            return fallback
        return min(30.0, max(0.1, (queued_ahead + 1) / rate))

    # ------------------------------------------------------------- reads
    def total_queued(self) -> int:
        with self._lock:
            return sum(len(n.queue) for n in self._nodes.values())

    def group_of(self, query_id: str) -> Optional[str]:
        with self._lock:
            node = self._query_groups.get(query_id)
            return node.name if node is not None else None

    def state_of(self, node: _GroupNode) -> str:
        """can-run | full | blocked-memory (lock held by callers that
        iterate; reads are plain attribute loads)."""
        if node.running >= node.spec.hard_concurrency_limit:
            return "full"
        if node.spec.memory_limit_bytes is not None and \
                self._subtree_bytes_locked(node) >= \
                node.spec.memory_limit_bytes:
            return "blocked-memory"
        return "can-run"

    def table_rows(self) -> List[tuple]:
        """``system.runtime.resource_groups`` rows, column order matched
        to connector/system/schemas.py."""
        with self._lock:
            rows = []
            for name in sorted(self._nodes):
                n = self._nodes[name]
                rows.append((
                    n.name, self.state_of(n), len(n.queue), n.running,
                    n.served, n.spec.hard_concurrency_limit,
                    n.spec.max_queued, n.spec.memory_limit_bytes,
                    self._subtree_bytes_locked(n), n.spec.weight,
                    n.spec.cache_share, n.spec.queue_timeout_ms,
                ))
        return rows

    def info(self) -> dict:
        """The flat-gate-compatible rollup (the /ui header), plus the
        per-group breakdown."""
        with self._lock:
            root = self._roots[0] if self._roots else None
            return {
                "name": root.name if root else "global",
                "running": sum(r.running for r in self._roots),
                "queued": sum(len(n.queue)
                              for n in self._nodes.values()),
                "hardConcurrencyLimit": (
                    root.spec.hard_concurrency_limit if root else 0),
                "groups": {
                    n.name: {"running": n.running,
                             "queued": len(n.queue),
                             "served": n.served,
                             "weight": n.spec.weight,
                             "state": self.state_of(n)}
                    for n in self._nodes.values()
                },
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._cond.notify_all()

    # ----------------------------------------------------------- metrics
    def _set_depth_gauge(self, group: str) -> None:
        with self._lock:
            node = self._nodes.get(group)
            depth = len(node.queue) if node is not None else 0
            running = node.running if node is not None else 0
        self._publish_gauges(group, depth, running)

    def _set_gauges(self, node: _GroupNode) -> None:
        with self._lock:
            depth, running = len(node.queue), node.running
        self._publish_gauges(node.name, depth, running)

    def _publish_gauges(self, group: str, depth: int,
                        running: int) -> None:
        try:
            from trino_tpu.obs import metrics as M

            M.RESOURCE_GROUP_QUEUED.set(depth, group)
            M.RESOURCE_GROUP_RUNNING.set(running, group)
        except Exception:  # noqa: BLE001 — accounting never fails work
            pass


def _safe_segment(user: str) -> str:
    """A user name as a group path segment (dots would split the path)."""
    return re.sub(r"[^A-Za-z0-9_\-]", "_", user or "anonymous")


# ------------------------------------------------- legacy (flat) gates
class ResourceGroup:
    """Bounded-concurrency admission gate with a FIFO queue — the flat
    blocking-submit compatibility surface (callers that inject their own
    gate into CoordinatorServer keep this contract; the default
    coordinator runs :class:`ResourceGroupTree`)."""

    def __init__(self, name: str = "global", hard_concurrency_limit: int = 16,
                 max_queued: int = 200):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self._lock = threading.Lock()
        self._running = 0
        self._queue: Deque[threading.Event] = collections.deque()

    def submit(self, timeout: Optional[float] = None,
               user: str = "anonymous") -> bool:
        """Block until admitted (True) or rejected/timed out (False).
        Rejection happens immediately when the queue is full (the
        reference's QUERY_QUEUE_FULL error). ``user`` is ignored by the
        flat group (one queue for everyone); ResourceGroupManager routes
        it to the per-user subgroup."""
        with self._lock:
            if self._running < self.hard_concurrency_limit and not self._queue:
                self._running += 1
                return True
            if len(self._queue) >= self.max_queued:
                return False
            gate = threading.Event()
            self._queue.append(gate)
        if not gate.wait(timeout):
            with self._lock:
                try:
                    self._queue.remove(gate)
                except ValueError:
                    return True  # raced with finish(): already admitted
            return False
        return True

    def finish(self, user: str = "anonymous") -> None:
        with self._lock:
            if self._queue:
                gate = self._queue.popleft()
                gate.set()  # hand the slot over; _running unchanged
            else:
                self._running = max(0, self._running - 1)

    def info(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "running": self._running,
                "queued": len(self._queue),
                "hardConcurrencyLimit": self.hard_concurrency_limit,
            }


class ResourceGroupManager:
    """Per-user subgroup tree under one root: global.user:<name>.

    Admission needs a slot in BOTH the user's subgroup and the root; when a
    query finishes, the freed root slot goes to the queued subgroup with
    the smallest running/weight ratio (weighted fair scheduling,
    reference: InternalResourceGroup.internalStartNext + the weighted
    scheduling policy). Subgroups are created on first use from a template
    (the ``user.${USER}`` expansion of resource-group config files).

    Compatibility surface like :class:`ResourceGroup`; the default
    coordinator's selector-driven tree is :class:`ResourceGroupTree`."""

    def __init__(self, root_concurrency_limit: int = 16,
                 per_user_concurrency_limit: int = 8,
                 per_user_max_queued: int = 100,
                 user_weights: Optional[Dict[str, int]] = None):
        self.root_limit = root_concurrency_limit
        self.user_limit = per_user_concurrency_limit
        self.user_max_queued = per_user_max_queued
        self.user_weights = dict(user_weights or {})
        self._lock = threading.Lock()
        self._root_running = 0
        # user -> state
        self._groups: Dict[str, dict] = {}

    # compatibility with the flat ResourceGroup surface (coordinator calls
    # submit()/finish() without a user for internal work)
    def submit(self, timeout: Optional[float] = None, user: str = "anonymous") -> bool:
        g = self._group(user)
        with self._lock:
            if self._can_start(g):
                self._start(g)
                return True
            if len(g["queue"]) >= self.user_max_queued:
                return False
            gate = threading.Event()
            g["queue"].append(gate)
        if not gate.wait(timeout):
            with self._lock:
                try:
                    g["queue"].remove(gate)
                except ValueError:
                    return True  # raced with a dispatch: already admitted
            return False
        return True

    def finish(self, user: str = "anonymous") -> None:
        with self._lock:
            g = self._groups.get(user)
            if g is not None:
                g["running"] = max(0, g["running"] - 1)
            self._root_running = max(0, self._root_running - 1)
            self._dispatch_next()

    def info(self) -> dict:
        with self._lock:
            return {
                "name": "global",
                "running": self._root_running,
                "queued": sum(len(g["queue"]) for g in self._groups.values()),
                "hardConcurrencyLimit": self.root_limit,
                "subgroups": {
                    u: {"running": g["running"], "queued": len(g["queue"]),
                        "weight": g["weight"]}
                    for u, g in sorted(self._groups.items())
                },
            }

    # ----------------------------------------------------------- internals
    def _group(self, user: str) -> dict:
        with self._lock:
            g = self._groups.get(user)
            if g is None:
                g = {"running": 0, "queue": collections.deque(),
                     "weight": max(1, int(self.user_weights.get(user, 1)))}
                self._groups[user] = g
            return g

    def _can_start(self, g: dict) -> bool:
        return (g["running"] < self.user_limit
                and self._root_running < self.root_limit)

    def _start(self, g: dict) -> None:
        g["running"] += 1
        self._root_running += 1

    def _dispatch_next(self) -> None:
        """Weighted fair pick among queued subgroups with capacity: the
        eligible group with the smallest running/weight starts next."""
        while self._root_running < self.root_limit:
            eligible = [
                g for g in self._groups.values()
                if g["queue"] and g["running"] < self.user_limit
            ]
            if not eligible:
                return
            g = min(eligible, key=lambda g: g["running"] / g["weight"])
            gate = g["queue"].popleft()
            self._start(g)
            gate.set()
