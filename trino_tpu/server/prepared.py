"""Prepared statements: the server-side registry + the parameter machinery.

Reference: ``execution/PrepareTask.java`` / ``sql/tree/Parameter`` +
``planner/ParameterRewriter`` — PREPARE stores the statement PARSED,
EXECUTE binds constant-folded ``USING`` values and runs it. The serving
twist (the PR 10 tentpole): the *parameterized* statement plans ONCE into
the coordinator's logical-plan cache with symbolic ``ir.Parameter``
placeholders, and every EXECUTE substitutes its bound constants into a
copy of that cached plan — so a repeated point query pays bind time
(microseconds) instead of parse+analyze+plan+optimize.

Keying contract (ISSUE 10): the plan-cache key fingerprints the
parameterized SHAPE (inner statement + the bound types — one entry serves
all bindings of the same type signature), while the result-cache key is
the fingerprint of the BOUND plan, so every distinct binding caches its
own rows. Access control holds per principal exactly like PR 2: the plan
cache partitions by user (``PlanCache.key_for``), so plan-time permission
checks re-fire for each identity.

The registry is server-global, keyed ``(user, name)`` — one user's
PREPARE is visible to their later connections (the serving analog of the
reference's session-held map, which our per-query throwaway sessions
cannot hold), never to other principals. Bounded LRU; surfaced as
``system.runtime.prepared_statements``.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from trino_tpu.sql import ir
from trino_tpu.sql.parser import ast


class PreparedStatementError(ValueError):
    pass


def count_parameters(stmt) -> int:
    """Number of ``?`` markers a parsed statement carries (max index + 1 —
    the parser numbers them left to right)."""
    highest = -1

    def visit(node):
        nonlocal highest
        if isinstance(node, ast.Parameter):
            highest = max(highest, node.index)
        elif isinstance(node, (tuple, list)):
            for x in node:
                visit(x)
        elif dataclasses.is_dataclass(node) and not isinstance(node, type):
            for f in dataclasses.fields(node):
                visit(getattr(node, f.name))

    visit(stmt)
    return highest + 1


@dataclasses.dataclass
class PreparedStatement:
    """One registered statement: the parsed inner AST plus bookkeeping the
    ``system.runtime.prepared_statements`` table surfaces."""

    user: str
    name: str
    statement: ast.Statement  # the inner (post-FROM) statement, parsed
    sql: str                  # inner statement text (display/debug)
    param_count: int
    created_at: float
    executions: int = 0
    last_executed_at: Optional[float] = None

    def plan_cache_sql(self, ptypes: Tuple) -> str:
        """The plan-cache key text for one type signature: the
        parameterized statement's canonical (repr) shape + the bound
        types. All bindings of one signature share ONE plan entry; a
        binding with different types plans its own (the analyzer inferred
        different expression types, so it IS a different plan)."""
        sig = ",".join(str(t) for t in ptypes)
        return f"EXECUTE::{self.sql.strip()}::types[{sig}]"


class PreparedStatementRegistry:
    """Server-wide LRU of prepared statements keyed ``(user, name)``.

    Bounded so an EXECUTE-less client loop cannot grow coordinator
    memory; eviction is LRU over PREPARE/EXECUTE touches, with a
    PER-USER sub-bound so one principal's PREPARE volume evicts its own
    oldest statements, never another user's live ones (the registry is
    shared state, like the query-history ring's grow-only clamp).
    Thread-safe: every query thread races through it."""

    MAX_STATEMENTS = 512
    MAX_PER_USER = 128

    def __init__(self, max_statements: int = MAX_STATEMENTS,
                 max_per_user: int = MAX_PER_USER):
        self.max_statements = max_statements
        self.max_per_user = max_per_user
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str], PreparedStatement]" = \
            OrderedDict()

    def _set_gauge(self) -> None:
        from trino_tpu.obs import metrics as M

        M.PREPARED_STATEMENTS.set(len(self._entries))

    def put(self, user: str, name: str, statement: ast.Statement,
            sql: str) -> PreparedStatement:
        entry = PreparedStatement(
            user=user, name=name, statement=statement, sql=sql,
            param_count=count_parameters(statement),
            created_at=time.time())
        with self._lock:
            self._entries[(user, name)] = entry
            self._entries.move_to_end((user, name))
            # per-user bound first: the offender evicts its own oldest
            mine = [k for k in self._entries if k[0] == user]
            for k in mine[: max(0, len(mine) - self.max_per_user)]:
                del self._entries[k]
            while len(self._entries) > self.max_statements:
                self._entries.popitem(last=False)
            self._set_gauge()
        return entry

    def get(self, user: str, name: str) -> Optional[PreparedStatement]:
        with self._lock:
            entry = self._entries.get((user, name))
            if entry is not None:
                self._entries.move_to_end((user, name))
            return entry

    def remove(self, user: str, name: str) -> bool:
        with self._lock:
            found = self._entries.pop((user, name), None) is not None
            self._set_gauge()
            return found

    def touch(self, user: str, name: str) -> None:
        """Record one EXECUTE against the statement (executions counter +
        last-executed timestamp, read by the system table)."""
        with self._lock:
            entry = self._entries.get((user, name))
            if entry is not None:
                entry.executions += 1
                entry.last_executed_at = time.time()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> List[PreparedStatement]:
        """Newest-touched-last entry list (rows for
        ``system.runtime.prepared_statements``)."""
        with self._lock:
            return [dataclasses.replace(e) for e in self._entries.values()]


def fold_execute_args(params) -> List[ir.Constant]:
    """Constant-fold the EXECUTE ... USING expressions to typed values
    (reference: the reference engine requires EXECUTE arguments to be
    constant expressions; they analyze against an empty scope)."""
    from trino_tpu.sql.analyzer.expr_analyzer import ExprAnalyzer
    from trino_tpu.sql.analyzer.scope import Scope
    from trino_tpu.sql.planner.planner import _fold_constant

    analyzer = ExprAnalyzer(Scope([], None))
    values: List[ir.Constant] = []
    for i, e in enumerate(params):
        c = _fold_constant(analyzer.analyze(e))
        if c is None:
            raise PreparedStatementError(
                f"EXECUTE parameter {i + 1} must be a constant expression")
        values.append(c)
    return values


def check_arity(prepared: PreparedStatement, values) -> None:
    if len(values) != prepared.param_count:
        raise PreparedStatementError(
            f"prepared statement '{prepared.name}' expects "
            f"{prepared.param_count} parameters, but EXECUTE supplied "
            f"{len(values)}")


def bind_plan_parameters(root, values: List[ir.Constant]):
    """Substitute bound constants for every ``ir.Parameter`` in the
    cached optimized plan (the ParameterRewriter analog, run on the plan
    IR instead of the AST so planning itself is skipped).

    Copy-on-write: only nodes on a path to a parameter are rebuilt
    (``dataclasses.replace`` with the original node id restored —
    ``replace`` would re-run the id factory and break the
    ``dynamic_filters`` join-id references and stats keying); every
    parameter-free subtree is SHARED with the cached plan, which is never
    mutated — bind cost scales with parameter count, not plan size.
    Sharing is safe because nothing executes a plan destructively: the
    local executors only read it and ``fragment_plan`` deepcopies before
    cutting. Types need no coercion here: the plan-cache key includes the
    binding's type signature, so a cached plan's parameter types always
    equal the bound constants' types by construction."""
    from trino_tpu.sql.planner import plan as P

    def expr_has_param(e) -> bool:
        return any(isinstance(x, ir.Parameter) for x in ir.walk(e))

    def rewrite_expr(e):
        if isinstance(e, ir.Parameter):
            if e.index >= len(values):
                raise PreparedStatementError(
                    f"unbound parameter ?{e.index + 1}")
            return ir.Constant(e.type, values[e.index].value)
        if not expr_has_param(e):
            return e
        if isinstance(e, ir.Call):
            return ir.Call(e.type, e.name,
                           tuple(rewrite_expr(a) for a in e.args))
        if isinstance(e, ir.Case):
            return ir.Case(
                e.type,
                tuple((rewrite_expr(c), rewrite_expr(v))
                      for c, v in e.whens),
                rewrite_expr(e.default) if e.default is not None else None)
        if isinstance(e, ir.Cast):
            return ir.Cast(e.type, rewrite_expr(e.value))
        if isinstance(e, ir.Lambda):
            return ir.Lambda(e.type, rewrite_expr(e.body), e.n_params)
        return e

    def rewrite_value(v):
        if isinstance(v, ir.Expr):
            return rewrite_expr(v)
        if isinstance(v, P.PlanNode):
            return rebuild(v)
        if isinstance(v, list):
            nl = [rewrite_value(x) for x in v]
            return nl if any(a is not b for a, b in zip(nl, v)) else v
        if isinstance(v, tuple):
            nt = tuple(rewrite_value(x) for x in v)
            return nt if any(a is not b for a, b in zip(nt, v)) else v
        return v

    def rebuild(node):
        changes = {}
        for f in dataclasses.fields(node):
            if f.name == "id":
                continue
            v = getattr(node, f.name)
            nv = rewrite_value(v)
            if nv is not v:
                changes[f.name] = nv
        if not changes:
            return node
        new = dataclasses.replace(node, **changes)
        new.id = node.id  # keep plan-node identity (see docstring)
        return new

    return rebuild(root)


def plan_has_parameters(root) -> bool:
    """True when any expression in the plan still holds an
    ``ir.Parameter`` (tests + the bind pass's own sanity)."""
    from trino_tpu.sql.planner import plan as P

    def expr_has(e) -> bool:
        return any(isinstance(x, ir.Parameter) for x in ir.walk(e))

    def value_has(v) -> bool:
        if isinstance(v, ir.Expr):
            return expr_has(v)
        if isinstance(v, (list, tuple)):
            return any(value_has(x) for x in v)
        return False

    for node in P.walk_plan(root):
        for f in dataclasses.fields(node):
            if f.name in ("id", "source", "left", "right", "sources_"):
                continue
            if value_has(getattr(node, f.name)):
                return True
    return False
