"""Streaming shuffle consumer: pull pages from upstream task buffers.

Reference: ``operator/DirectExchangeClient.java:56`` (``pollPage`` :221,
``scheduleRequestIfNecessary`` :269) + ``HttpPageBufferClient.java:98`` —
one puller per upstream location, token-acknowledged at-least-once pulls,
client-side sequence de-dup, bounded client buffer for backpressure.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

from trino_tpu.data.page import Page
from trino_tpu.data.serde import deserialize_page
from trino_tpu.obs import metrics as M
from trino_tpu.obs import trace as tracing
from trino_tpu.obs.flowledger import FLOW_LEDGER
from trino_tpu.server import wire


class TaskLocation:
    """Address of one upstream task's output buffer."""

    def __init__(self, base_url: str, task_id: str, buffer_id: int = 0):
        self.base_url = base_url.rstrip("/")
        self.task_id = task_id
        self.buffer_id = buffer_id

    def results_url(self, token: int) -> str:
        return f"{self.base_url}/v1/task/{self.task_id}/results/{self.buffer_id}/{token}"

    def __repr__(self):
        return f"TaskLocation({self.base_url}, {self.task_id})"


class ExchangeClient:
    """Pulls every upstream location to completion into a bounded queue.

    ``max_buffered_pages`` is the backpressure bound (the reference's
    ``exchange.max-buffer-size``): pullers block once the local queue is
    full, which stalls their token advance, which leaves pages queued in the
    upstream OutputBuffer — backpressure propagates through the token
    protocol with no extra machinery.
    """

    def __init__(self, locations: List[TaskLocation], max_buffered_pages: int = 64,
                 tracer: Optional["tracing.Tracer"] = None,
                 owner: Optional[str] = None, stall_key=None):
        self._locations = list(locations)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_buffered_pages)
        self._remaining = len(self._locations)
        self._lock = threading.Lock()
        self._failure: Optional[str] = None
        # flow-ledger attribution: who these pulled bytes belong to
        # (task:<id> on workers, query:<id> on the coordinator gather) and
        # the (stage, partition) the empty-poll stall samples label
        self._owner = owner or "exchange"
        self._stall_key = stall_key if stall_key is not None else (None, None)
        # per-client ledger totals (task stats: transferS / stallS)
        self.pulled_seconds = 0.0
        self.stalled_seconds = 0.0
        # span context is captured AT CONSTRUCTION (the consumer's thread):
        # puller threads record their exchange spans under the span that
        # created the client (task body / root-fragment execute). With no
        # explicit tracer the ambient context is adopted — call sites that
        # tests replace with fakes stay signature-compatible.
        if tracer is None:
            ambient = tracing.current()
            if ambient is not None:
                tracer = ambient[0]
        self._tracer = tracer
        self._parent_span_id = (
            tracer.current_span_id() if tracer is not None else None)
        self._threads = [
            threading.Thread(target=self._pull, args=(loc,), daemon=True)
            for loc in self._locations
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    MAX_ATTEMPTS = 4

    def _request_with_retry(self, loc: TaskLocation, token: int):
        """Retry transient failures with the SAME token — the at-least-once
        window makes re-reads of un-acked tokens safe (reference:
        HttpPageBufferClient's Backoff); only the token advance is an ack.

        Returns ``(body, headers, attempts, last_status)`` so the pull
        loop's flow record carries the retry count and terminal status.
        The per-attempt history (status or exception, with the backoff it
        paid) rides the terminal "retries exhausted" error, so a failed
        exchange names every attempt instead of just the last."""
        delay = 0.2
        history: List[str] = []
        last_status: Optional[str] = None
        trace_headers = (
            {tracing.TRACEPARENT_HEADER:
             self._tracer.traceparent(self._parent_span_id)}
            if self._tracer is not None else None)
        for attempt in range(self.MAX_ATTEMPTS):
            M.EXCHANGE_REQUESTS.inc()
            if attempt:
                M.EXCHANGE_RETRIES.inc()
            t0 = time.perf_counter()
            try:
                status, body, headers = wire.http_request(
                    "GET", loc.results_url(token), timeout=120.0,
                    headers=trace_headers,
                )
            except Exception as e:  # noqa: BLE001 — socket-level failure
                last_status = type(e).__name__
                history.append(
                    f"#{attempt + 1} {last_status} after "
                    f"{time.perf_counter() - t0:.3f}s: {str(e)[:120]}")
                if attempt == self.MAX_ATTEMPTS - 1:
                    raise RuntimeError(
                        f"exchange pull {loc}: retries exhausted after "
                        f"{len(history)} attempts [{'; '.join(history)}]"
                    ) from e
                time.sleep(delay)
                delay *= 2
                continue
            last_status = str(status)
            history.append(
                f"#{attempt + 1} HTTP {status} after "
                f"{time.perf_counter() - t0:.3f}s")
            if status >= 500 and attempt < self.MAX_ATTEMPTS - 1:
                time.sleep(delay)
                delay *= 2
                continue
            if status >= 400:
                raise RuntimeError(
                    f"exchange pull {loc} -> {status}: {body[:300].decode(errors='replace')}"
                )
            return body, headers, attempt + 1, last_status
        raise RuntimeError(
            f"exchange pull {loc}: retries exhausted after "
            f"{len(history)} attempts [{'; '.join(history)}]")

    def _read_spool(self, loc: TaskLocation) -> bool:
        """Fallback for an unreachable/failed producer: read its spooled
        output from the shared spool directory (reference: FTE consumers
        read ExchangeSource files, not live task buffers —
        FileSystemExchange.java:70). Returns True when served from spool."""
        import os

        from trino_tpu.server.task import spool_directory

        spool_dir = spool_directory()
        if not spool_dir:
            return False
        # partitioned producers spool one file per partition (= buffer id)
        path = os.path.join(
            spool_dir, f"{loc.task_id}.p{loc.buffer_id}.pages")
        if not os.path.exists(path):
            path = os.path.join(spool_dir, f"{loc.task_id}.pages")
        if not os.path.exists(path):
            return False
        sp = (self._tracer.start_span(
                  "spool/read", parent_id=self._parent_span_id,
                  task=loc.task_id, path=path)
              if self._tracer is not None else tracing.NOOP_SPAN)
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                body = f.read()
            M.SPOOL_READS.inc()
            # disk reads, NOT exchange bytes: trino_tpu_exchange_bytes_total
            # stays a network-throughput signal
            M.SPOOL_BYTES.inc(len(body))
            pages = wire.unframe_pages(body)
            sp.set("bytes", len(body))
            sp.set("pages", len(pages))
        except Exception as e:  # a truncated spool file must not leave the
            sp.set("error", str(e)[:300])  # span dangling open
            raise
        finally:
            if self._tracer is not None:
                self._tracer.end_span(sp)
        spool_s = time.perf_counter() - t0
        self.pulled_seconds += spool_s
        FLOW_LEDGER.record_transfer(
            "exchange-pull", self._owner, len(body), spool_s,
            pages=len(pages), src=f"spool:{loc.task_id}",
            dst=FLOW_LEDGER.node_id or None, status="spool")
        for pb in pages:
            self._queue.put(deserialize_page(pb))
        # final ack to the live buffer (if the producer still exists) so it
        # releases the in-memory copy — the spool is the durable one
        try:
            wire.http_request(
                "DELETE", loc.results_url(len(pages)), timeout=5.0)
        except Exception:  # noqa: BLE001 — producer may be gone; that's fine
            pass
        return True

    def _pull(self, loc: TaskLocation) -> None:
        token = 0
        # one span per upstream location covering its whole pull stream
        # (reference: DirectExchangeClient's per-client otel spans)
        sp = (self._tracer.start_span(
                  "exchange/pull", parent_id=self._parent_span_id,
                  task=loc.task_id, buffer=loc.buffer_id)
              if self._tracer is not None else tracing.NOOP_SPAN)
        pulled_bytes = 0
        pulled_pages = 0
        pull_seconds = 0.0
        pull_retries = 0
        last_status: Optional[str] = None
        streamed = False
        try:
            if self._read_spool(loc):
                sp.set("spooled", True)
                return
            streamed = True
            while True:
                t0 = time.perf_counter()
                body, headers, attempts, last_status = (
                    self._request_with_retry(loc, token))
                waited = time.perf_counter() - t0
                pull_seconds += waited
                pull_retries += attempts - 1
                failed = headers.get(wire.H_TASK_FAILED)
                if failed:
                    raise RuntimeError(f"upstream task {loc.task_id} failed: {failed}")
                M.EXCHANGE_BYTES.inc(len(body))
                pulled_bytes += len(body)
                n_before = pulled_pages
                for pb in wire.unframe_pages(body):
                    pulled_pages += 1
                    self._queue.put(deserialize_page(pb))
                if pulled_pages == n_before:
                    # empty poll: the producer had nothing ready — a
                    # consumer-starved backpressure sample
                    stage, partition = self._stall_key
                    FLOW_LEDGER.record_stall(
                        "exchange-poll", stage, partition, waited)
                    self.stalled_seconds += waited
                token = int(headers.get(wire.H_NEXT_TOKEN, token))
                if headers.get(wire.H_BUFFER_COMPLETE) == "true":
                    # final ack so the upstream buffer can be destroyed
                    wire.http_request("DELETE", loc.results_url(token), timeout=10.0)
                    break
        except Exception as e:  # noqa: BLE001 — surfaced to the consumer
            sp.set("error", str(e)[:300])
            with self._lock:
                if self._failure is None:
                    self._failure = str(e)
        finally:
            sp.set("bytes", pulled_bytes)
            sp.set("pages", pulled_pages)
            if self._tracer is not None:
                self._tracer.end_span(sp)
            if streamed:
                # one flow record per pull stream (not per request): the
                # whole conversation with this upstream location
                self.pulled_seconds += pull_seconds
                FLOW_LEDGER.record_transfer(
                    "exchange-pull", self._owner, pulled_bytes, pull_seconds,
                    pages=pulled_pages, src=loc.base_url,
                    dst=FLOW_LEDGER.node_id or None,
                    retries=pull_retries, status=last_status)
            with self._lock:
                self._remaining -= 1
            self._queue.put(None)  # wake the consumer

    def iter_pages(self):
        """Yield pages in arrival order WHILE upstreams are still producing
        — the WorkProcessor-style pull surface (reference:
        operator/WorkProcessor.java:31; Driver.java:449's blocked-future
        loop is the bounded queue block here). The consumer's memory bound
        is max_buffered_pages + whatever it holds per yielded page."""
        done = 0
        total = len(self._locations)
        while done < total:
            item = self._queue.get()
            if item is None:
                done += 1
                with self._lock:
                    if self._failure is not None:
                        raise RuntimeError(self._failure)
                continue
            yield item

    def pages(self) -> List[Page]:
        """Block until every upstream completes; return all pages in arrival
        order (the bulk-synchronous path: fragment bodies that need their
        whole input — joins, final aggregations, sorts)."""
        return list(self.iter_pages())
