"""SQL routines (user-defined scalar functions).

Reference: ``sql/routine/SqlRoutineCompiler.java`` + the CREATE FUNCTION
task family (``execution/CreateFunctionTask``) — the reference compiles
routine ASTs to bytecode per call site. TPU-first redesign: a scalar
routine's body is a SQL expression, so the "compiler" is CALL-SITE
INLINING — every invocation expands to the body AST with parameters
substituted (wrapped in casts to the declared types), then flows through
the normal analyzer/lowering into the same traced XLA program as any
other expression. No interpretation, no per-row dispatch: an inlined
routine fuses with its surrounding operators exactly like hand-written
SQL.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

from trino_tpu.sql.parser import ast

MAX_EXPANSION_DEPTH = 16  # recursion guard (reference: routines are non-recursive)


@dataclasses.dataclass(frozen=True)
class UdfDef:
    """One registered scalar routine."""

    name: str
    params: Tuple[Tuple[str, str], ...]  # (param name, type string)
    returns: str  # type string
    body: ast.Expression


class RoutineError(ValueError):
    pass


def validate(udf: UdfDef) -> None:
    """CREATE-time validation: the body must analyze against a scope of
    exactly the declared parameters (catches unknown columns/functions
    before any query uses the routine — CreateFunctionTask's analysis)."""
    from trino_tpu import types as T
    from trino_tpu.sql.analyzer.expr_analyzer import ExprAnalyzer
    from trino_tpu.sql.analyzer.scope import Field, Scope

    fields = [Field(p, T.parse_type(t), None) for p, t in udf.params]
    out = ExprAnalyzer(Scope(fields, None)).analyze(udf.body)
    ret = T.parse_type(udf.returns)
    if T.common_super_type(out.type, ret) is None:
        raise RoutineError(
            f"function {udf.name} body type {out.type} does not coerce to "
            f"declared RETURNS {ret}")


# --------------------------------------------------------- AST expansion


def _rewrite_value(v, fn):
    if isinstance(v, tuple):
        return tuple(_rewrite_value(x, fn) for x in v)
    if isinstance(v, list):
        return [_rewrite_value(x, fn) for x in v]
    if isinstance(v, dict):  # e.g. TableFunctionCall.named_args
        return {k: _rewrite_value(x, fn) for k, x in v.items()}
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return _rewrite_node(v, fn)
    return v


def _rewrite_node(node, fn):
    """Generic bottom-up rewrite over the frozen AST dataclasses."""
    changed = {}
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        nv = _rewrite_value(v, fn)
        if nv is not v and nv != v:
            changed[f.name] = nv
    out = dataclasses.replace(node, **changed) if changed else node
    if isinstance(out, ast.Expression):
        return fn(out)
    return out


def _substitute_params(body: ast.Expression, mapping: Dict[str, ast.Expression]):
    def sub(e: ast.Expression):
        if isinstance(e, ast.Identifier) and len(e.parts) == 1 \
                and e.name.lower() in mapping:
            return mapping[e.name.lower()]
        return e

    return _rewrite_node(body, sub)


def expand_udfs(stmt, udfs: Dict[str, UdfDef], depth: int = 0):
    """Inline every registered-routine call in ``stmt`` (any AST node).
    Nested routine calls expand recursively up to MAX_EXPANSION_DEPTH."""
    if not udfs:
        return stmt
    if depth > MAX_EXPANSION_DEPTH:
        raise RoutineError("function expansion too deep (recursive routine?)")

    def expand_call(e: ast.Expression):
        if not isinstance(e, ast.FunctionCall):
            return e
        udf = udfs.get(e.name.lower())
        if udf is None:
            return e
        if len(e.args) != len(udf.params):
            raise RoutineError(
                f"function {udf.name} expects {len(udf.params)} arguments, "
                f"got {len(e.args)}")
        mapping = {
            p.lower(): ast.Cast(arg, t)  # coerce args to declared types
            for (p, t), arg in zip(udf.params, e.args)
        }
        inlined = _substitute_params(udf.body, mapping)
        # the body may itself call routines
        inlined = expand_udfs(inlined, udfs, depth + 1)
        return ast.Cast(inlined, udf.returns)

    return _rewrite_node(stmt, expand_call)
