"""Name-resolution scopes.

Reference: ``core/trino-main/.../sql/analyzer/Scope.java`` — a scope is an
ordered list of fields, each optionally qualified by a relation alias;
identifier resolution tries (alias, name) then bare name, erroring on
ambiguity. Correlated references resolve through the parent scope chain.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from trino_tpu import types as T


class AnalysisError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Field:
    name: Optional[str]  # None for anonymous (expression) fields
    type: T.Type
    relation_alias: Optional[str] = None  # the qualifier, if any


@dataclasses.dataclass
class Scope:
    fields: List[Field]
    parent: Optional["Scope"] = None

    def resolve(self, parts: Tuple[str, ...]) -> Tuple[int, Field, int]:
        """Resolve a (possibly qualified) name.

        Returns (channel, field, depth) where depth=0 means this scope,
        1 = parent (a correlated reference), etc.
        """
        matches = self._match(parts)
        if len(matches) > 1:
            raise AnalysisError(f"column reference is ambiguous: {'.'.join(parts)}")
        if matches:
            i = matches[0]
            return i, self.fields[i], 0
        if self.parent is not None:
            ch, f, d = self.parent.resolve(parts)
            return ch, f, d + 1
        raise AnalysisError(f"column cannot be resolved: {'.'.join(parts)}")

    def _match(self, parts: Tuple[str, ...]) -> List[int]:
        name = parts[-1].lower()
        qualifier = parts[-2].lower() if len(parts) >= 2 else None
        out = []
        for i, f in enumerate(self.fields):
            if f.name is None or f.name.lower() != name:
                continue
            if qualifier is not None and (
                f.relation_alias is None or f.relation_alias.lower() != qualifier
            ):
                continue
            out.append(i)
        return out

    def channels_of_alias(self, alias: str) -> List[int]:
        return [
            i
            for i, f in enumerate(self.fields)
            if f.relation_alias is not None and f.relation_alias.lower() == alias.lower()
        ]
