"""Expression analysis: parser AST -> typed IR.

Reference: ``core/trino-main/.../sql/analyzer/ExpressionAnalyzer.java``
(3,954 lines) — name resolution against scopes, literal typing, operator
type derivation (decimal precision/scale rules verified against
``io/trino/type/DecimalOperators.java:75,156,236,319,489``), coercion
insertion, and aggregate-call detection.
"""
from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Tuple

from trino_tpu import types as T
from trino_tpu.sql import ir
from trino_tpu.sql.analyzer.scope import AnalysisError, Field, Scope
from trino_tpu.sql.parser import ast

# prepared-statement parameter types, scoped to one planning run
# (sql/parser Parameter nodes carry only an index; the types come from the
# EXECUTE binding that triggered planning — server/prepared.py /
# exec/query.py set them around Planner.plan). A contextvar, not a
# constructor argument: ExprAnalyzer is instantiated at dozens of planner
# sites and every one of them must see the same binding.
import contextlib
import contextvars

_PARAM_TYPES: "contextvars.ContextVar[Optional[Tuple[T.Type, ...]]]" = \
    contextvars.ContextVar("prepared_parameter_types", default=None)


@contextlib.contextmanager
def parameter_types(types):
    """Make prepared-statement parameter types visible to every
    ExprAnalyzer created inside the block (one planning run)."""
    token = _PARAM_TYPES.set(tuple(types))
    try:
        yield
    finally:
        _PARAM_TYPES.reset(token)


AGGREGATE_FUNCTIONS = {
    "count", "sum", "avg", "min", "max",
    "stddev", "stddev_samp", "stddev_pop",
    "variance", "var_samp", "var_pop",
    "approx_distinct",
    "approx_percentile",
    "array_agg",
    "bool_and", "bool_or", "every",
    "count_if",
    "arbitrary", "any_value",
    "geometric_mean",
    "checksum",
    "min_by", "max_by",
    "corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept",
    "histogram", "map_agg",
}

_MONTH_UNITS = {"year": 12, "month": 1}
_DAY_UNITS = {"day": 1}
_SECOND_UNITS = {"day": 86_400, "hour": 3_600, "minute": 60, "second": 1}


def _zone_offset_seconds(zone: str) -> int:
    """Fixed-offset zone id -> seconds east of UTC. 'UTC'/'Z' and
    '[+-]HH:MM' are supported; region ids with DST rules would need
    per-value offsets (documented limitation)."""
    z = zone.strip().upper()
    if z in ("UTC", "Z", "+00:00", "-00:00"):
        return 0
    import re as _re

    m = _re.fullmatch(r"([+-])(\d{2}):(\d{2})", z)
    if not m:
        raise AnalysisError(
            f"unsupported time zone {zone!r} (fixed offsets and UTC only)")
    sign = 1 if m.group(1) == "+" else -1
    return sign * (int(m.group(2)) * 3600 + int(m.group(3)) * 60)


def _timestamp_literal(text: str) -> ir.Constant:
    """TIMESTAMP 'YYYY-MM-DD hh:mm:ss[.fff][+HH:MM]' — precision inferred
    from the fractional digits (0 -> 0, <=3 -> 3, <=6 -> 6, else 9); a
    trailing offset makes it WITH TIME ZONE, normalized to UTC storage
    (reference: TimestampType literal analysis)."""
    s = text.strip().replace(" ", "T", 1) if " " in text.strip() else text.strip()
    frac = ""
    parse_s = s
    dot = s.find(".")
    if dot > 0:
        head, tail = s[:dot], s[dot + 1:]
        rest = ""
        for i, c in enumerate(tail):
            if not c.isdigit():
                frac, rest = tail[:i], tail[i:]
                break
        else:
            frac = tail
        if frac:
            # SQL allows 1..12 fractional digits but Python 3.10's
            # fromisoformat accepts exactly 3 or 6 — normalize for the
            # parse only; `frac` keeps the written digits for precision
            # inference (and the p=9 sub-microsecond remainder below)
            norm = frac[:6].ljust(6 if len(frac) > 3 else 3, "0")
            parse_s = f"{head}.{norm}{rest}"
    try:
        v = datetime.datetime.fromisoformat(parse_s)
    except ValueError:
        raise AnalysisError(f"invalid timestamp literal {text!r}") from None
    p = 0 if not frac else (3 if len(frac) <= 3 else (6 if len(frac) <= 6 else 9))
    with_tz = v.tzinfo is not None
    if with_tz:
        v = v.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    epoch = datetime.datetime(1970, 1, 1)
    delta = v - epoch
    micros = (delta.days * 86_400_000_000 + delta.seconds * 1_000_000
              + delta.microseconds)
    unit = 10 ** p
    extra = 0
    if p == 9 and len(frac) > 6:
        extra = int(frac[6:9].ljust(3, "0"))
    value = micros * unit // 1_000_000 + extra
    return ir.Constant(T.timestamp(p, with_tz), value)


def analyze_literal(lit: ast.Literal) -> ir.Constant:
    if lit.kind == "null":
        return ir.Constant(T.UNKNOWN, None)
    if lit.kind == "boolean":
        return ir.Constant(T.BOOLEAN, bool(lit.value))
    if lit.kind == "string":
        return ir.Constant(T.varchar(), lit.value)
    if lit.kind == "date":
        days = (datetime.date.fromisoformat(lit.value) - datetime.date(1970, 1, 1)).days
        return ir.Constant(T.DATE, days)
    if lit.kind == "timestamp":
        return _timestamp_literal(str(lit.value))
    if lit.kind == "varbinary":
        hexs = str(lit.value).replace(" ", "").lower()
        try:
            bytes.fromhex(hexs)
        except ValueError:
            raise AnalysisError(f"invalid varbinary literal X'{lit.value}'") from None
        # dictionary repr is the hex string (see types.VARBINARY)
        return ir.Constant(T.VARBINARY, hexs)
    if lit.kind == "number":
        text = str(lit.value)
        if "e" in text.lower():
            return ir.Constant(T.DOUBLE, float(text))
        if "." in text:
            intpart, frac = text.split(".")
            scale = len(frac)
            digits = len((intpart.lstrip("-").lstrip("0") or "")) + scale
            digits = max(digits, scale + 1 if intpart.strip("-0") == "" else digits)
            p = max(1, min(38, digits))
            return ir.Constant(T.decimal(p, scale), int(round(float(text) * 10**scale)))
        v = int(text)
        typ = T.INTEGER if -(2**31) <= v < 2**31 else T.BIGINT
        return ir.Constant(typ, v)
    raise AnalysisError(f"unsupported literal kind {lit.kind}")


def arithmetic_result_type(op: str, a: T.Type, b: T.Type) -> T.Type:
    if a == T.UNKNOWN:
        a = b
    if b == T.UNKNOWN:
        b = a
    if a == T.DATE or b == T.DATE:
        # date +/- integer days
        if op in ("+", "-") and (a == T.DATE) != (b == T.DATE):
            return T.DATE
        if op == "-" and a == T.DATE and b == T.DATE:
            return T.BIGINT  # day difference (Trino returns interval day)
        raise AnalysisError(f"cannot apply {op} to {a}, {b}")
    if a.is_floating or b.is_floating:
        return T.DOUBLE if T.DOUBLE in (a, b) or a.is_decimal or b.is_decimal else T.REAL
    if a.is_decimal or b.is_decimal:
        pa, sa = _prec_scale(a)
        pb, sb = _prec_scale(b)
        # verified against reference DecimalOperators.java result signatures
        if op in ("+", "-"):
            s = max(sa, sb)
            return T.decimal(min(38, max(pa - sa, pb - sb) + s + 1), s)
        if op == "*":
            return T.decimal(min(38, pa + pb), sa + sb)
        if op == "/":
            return T.decimal(min(38, pa + sb + max(0, sb - sa)), max(sa, sb))
        if op == "%":
            return T.decimal(min(pb - sb, pa - sa) + max(sa, sb), max(sa, sb))
    out = T.common_super_type(a, b)
    if out is None or not out.is_numeric:
        raise AnalysisError(f"cannot apply {op} to {a}, {b}")
    return out


def _prec_scale(t: T.Type) -> Tuple[int, int]:
    if isinstance(t, T.DecimalType):
        return t.precision, t.scale
    return {"tinyint": 3, "smallint": 5, "integer": 10, "bigint": 19}[t.name], 0


def aggregate_result_type(
    fn: str, arg: Optional[T.Type], arg2: Optional[T.Type] = None
) -> T.Type:
    """Reference: operator/aggregation function signatures."""
    if fn == "count":
        return T.BIGINT
    assert arg is not None
    if fn in ("bool_and", "bool_or", "every"):
        if arg != T.BOOLEAN:
            raise AnalysisError(f"{fn}() expects a boolean argument")
        return T.BOOLEAN
    if fn == "count_if":
        if arg != T.BOOLEAN:
            raise AnalysisError("count_if() expects a boolean argument")
        return T.BIGINT
    if fn in ("arbitrary", "any_value"):
        return arg
    if fn == "geometric_mean":
        if not arg.is_numeric:
            raise AnalysisError(f"geometric_mean() not defined for {arg}")
        return T.DOUBLE
    if fn == "checksum":
        return T.BIGINT
    if fn in ("min_by", "max_by"):
        assert arg2 is not None
        if not arg2.orderable:
            raise AnalysisError(f"{fn}() ordering argument {arg2} is not orderable")
        return arg
    if fn in ("corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept"):
        assert arg2 is not None
        if not (arg.is_numeric and arg2.is_numeric):
            raise AnalysisError(f"{fn}() expects numeric arguments")
        return T.DOUBLE
    if fn == "histogram":
        if not arg.comparable:
            raise AnalysisError(f"histogram() argument {arg} is not comparable")
        return T.map_of(arg, T.BIGINT)
    if fn == "map_agg":
        assert arg2 is not None
        return T.map_of(arg, arg2)
    if fn == "sum":
        if arg.is_decimal:
            return T.decimal(38, arg.scale)
        if arg.is_floating:
            return T.DOUBLE
        if arg.is_integer_kind:
            return T.BIGINT
        raise AnalysisError(f"sum() not defined for {arg}")
    if fn == "avg":
        if arg.is_decimal:
            return arg
        return T.DOUBLE
    if fn in ("min", "max"):
        return arg
    if fn in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
        if not arg.is_numeric:
            raise AnalysisError(f"{fn}() not defined for {arg}")
        return T.DOUBLE
    if fn == "approx_distinct":
        return T.BIGINT
    if fn == "approx_percentile":
        if not arg.is_numeric:
            raise AnalysisError(f"approx_percentile() not defined for {arg}")
        return arg
    if fn == "array_agg":
        return T.array_of(arg)
    raise AnalysisError(f"unknown aggregate {fn}")


_COMPARISON_OPS = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}
_ARITH_OPS = {"+": "add", "-": "sub", "*": "mul", "/": "div", "%": "mod"}


class ExprAnalyzer:
    """Analyzes one expression against a scope.

    ``replacements`` maps AST subtrees (by structural equality) to
    pre-computed IR — used by the planner to substitute group-by keys and
    aggregate calls with their output channels in post-aggregation
    expressions (reference: QueryPlanner's TranslationMap).
    """

    def __init__(
        self,
        scope: Scope,
        replacements: Optional[Dict[ast.Expression, ir.Expr]] = None,
        allow_aggregates: bool = False,
    ):
        self.scope = scope
        self.replacements = replacements or {}
        self.allow_aggregates = allow_aggregates
        self.outer_refs: List[ir.OuterRef] = []  # correlated refs seen
        self.subqueries: List[Tuple[ast.Expression, object]] = []

    def analyze(self, e: ast.Expression) -> ir.Expr:
        if e in self.replacements:
            return self.replacements[e]
        return self._analyze(e)

    def _analyze(self, e: ast.Expression) -> ir.Expr:
        if isinstance(e, ast.Literal):
            return analyze_literal(e)
        if isinstance(e, ast.Parameter):
            types = _PARAM_TYPES.get()
            if types is None:
                raise AnalysisError(
                    "parameter markers (?) are only valid inside a prepared "
                    "statement executed with EXECUTE ... USING")
            if e.index >= len(types):
                raise AnalysisError(
                    f"prepared statement requires at least {e.index + 1} "
                    f"parameters, but EXECUTE supplied {len(types)}")
            return ir.Parameter(types[e.index], e.index)
        if isinstance(e, ast.Identifier):
            try:
                ch, field, depth = self.scope.resolve(e.parts)
            except AnalysisError as err:
                # niladic datetime keywords (reference: CURRENT_DATE et al
                # parse as parenless function invocations): a bare name
                # matching NO column resolves as the function instead —
                # strictly the not-found case; ambiguity errors (a real
                # column named `now` on both join sides) must propagate
                if (len(e.parts) == 1
                        and e.parts[0].lower() in ("current_date",
                                                   "current_timestamp",
                                                   "localtimestamp", "now")
                        and "cannot be resolved" in str(err)):
                    return self._analyze_function(
                        ast.FunctionCall(e.parts[0].lower(), ()))
                raise
            if depth == 0:
                return ir.ColumnRef(field.type, ch, field.name or "")
            if depth == 1:
                ref = ir.OuterRef(field.type, ch, field.name or "")
                self.outer_refs.append(ref)
                return ref
            raise AnalysisError("correlation depth > 1 not supported")
        if isinstance(e, ast.Comparison):
            left = self.analyze(e.left)
            right = self.analyze(e.right)
            self._check_comparable(left.type, right.type, e.op)
            return ir.Call(T.BOOLEAN, _COMPARISON_OPS[e.op], (left, right))
        if isinstance(e, ast.Arithmetic):
            return self._analyze_arithmetic(e)
        if isinstance(e, ast.Negative):
            v = self.analyze(e.value)
            if isinstance(v, ir.Constant) and v.type.is_numeric:
                return ir.Constant(v.type, -v.value)
            return ir.Call(v.type, "negate", (v,))
        if isinstance(e, ast.LogicalBinary):
            left = self.analyze(e.left)
            right = self.analyze(e.right)
            return ir.Call(T.BOOLEAN, e.op, (left, right))
        if isinstance(e, ast.Not):
            return ir.Call(T.BOOLEAN, "not", (self.analyze(e.value),))
        if isinstance(e, ast.IsNull):
            out = ir.Call(T.BOOLEAN, "is_null", (self.analyze(e.value),))
            if e.negated:
                out = ir.Call(T.BOOLEAN, "not", (out,))
            return out
        if isinstance(e, ast.Between):
            out = ir.Call(
                T.BOOLEAN,
                "between",
                (self.analyze(e.value), self.analyze(e.low), self.analyze(e.high)),
            )
            if e.negated:
                out = ir.Call(T.BOOLEAN, "not", (out,))
            return out
        if isinstance(e, ast.InList):
            args = (self.analyze(e.value),) + tuple(self.analyze(x) for x in e.items)
            out = ir.Call(T.BOOLEAN, "in_list", args)
            if e.negated:
                out = ir.Call(T.BOOLEAN, "not", (out,))
            return out
        if isinstance(e, ast.Like):
            pat = self.analyze(e.pattern)
            args = (self.analyze(e.value), pat)
            out = ir.Call(T.BOOLEAN, "like", args)
            if e.negated:
                out = ir.Call(T.BOOLEAN, "not", (out,))
            return out
        if isinstance(e, ast.SearchedCase):
            whens = tuple(
                (self.analyze(c), self.analyze(v)) for c, v in e.whens
            )
            default = self.analyze(e.default) if e.default is not None else None
            out_type = _case_type([v for _, v in whens], default)
            return ir.Case(out_type, whens, default)
        if isinstance(e, ast.SimpleCase):
            operand = e.operand
            whens = tuple(
                (self.analyze(ast.Comparison("=", operand, c)), self.analyze(v))
                for c, v in e.whens
            )
            default = self.analyze(e.default) if e.default is not None else None
            out_type = _case_type([v for _, v in whens], default)
            return ir.Case(out_type, whens, default)
        if isinstance(e, ast.Cast):
            target = T.parse_type(e.type_name)
            inner = self.analyze(e.value)
            if (target == T.DATE and isinstance(inner, ir.Constant)
                    and inner.type.is_varchar and inner.value is not None):
                # fold cast('1999-2-01' as date) at analysis time — the
                # runtime lowering is dictionary-code based and cannot
                # parse dates (reference: constant folding in
                # IrExpressionInterpreter)
                import datetime

                try:
                    y, m, d = (int(p) for p in str(inner.value).split("-"))
                    days = (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days
                except ValueError:
                    raise AnalysisError(
                        f"cannot cast {inner.value!r} to date") from None
                return ir.Constant(T.DATE, days)
            return ir.Cast(target, inner)
        if isinstance(e, ast.Extract):
            v = self.analyze(e.value)
            time_fields = ("hour", "minute", "second")
            if e.field in time_fields:
                if not isinstance(v.type, T.TimestampType):
                    raise AnalysisError(f"EXTRACT({e.field}) needs a timestamp")
            elif e.field not in ("year", "month", "day", "quarter"):
                raise AnalysisError(f"EXTRACT({e.field}) not supported yet")
            return ir.Call(T.BIGINT, f"extract_{e.field}", (v,))
        if isinstance(e, ast.AtTimeZone):
            v = self.analyze(e.value)
            _zone_offset_seconds(e.zone.strip())  # validate the zone id
            if isinstance(v.type, T.TimestampType) and v.type.with_tz:
                # instant unchanged; zone is rendering metadata (UTC here)
                return v
            if v.type == T.DATE:
                v = ir.Cast(T.timestamp(0), v)
            if not isinstance(v.type, T.TimestampType):
                raise AnalysisError("AT TIME ZONE needs a timestamp")
            # Reference semantics (DateTimeFunctions.atTimeZone): the plain
            # timestamp is a wall-clock reading in the SESSION zone (UTC
            # here), so the INSTANT is unchanged — only the rendering zone
            # becomes `zone`, and this engine renders tz values in UTC.
            p = v.type.precision
            return ir.Cast(T.timestamp(p, True), v)
        if isinstance(e, ast.ArrayConstructor):
            items = tuple(self.analyze(x) for x in e.items)
            et = T.UNKNOWN
            for it in items:
                et2 = T.common_super_type(et, it.type)
                if et2 is None:
                    raise AnalysisError(
                        f"ARRAY elements incompatible: {et} vs {it.type}")
                et = et2
            if et == T.UNKNOWN:
                et = T.BIGINT  # empty / all-null literal defaults
            items = tuple(
                ir.Cast(et, it) if it.type not in (et, T.UNKNOWN) else it for it in items
            )
            return ir.Call(T.array_of(et), "array_ctor", items)
        if isinstance(e, ast.Subscript):
            base = self.analyze(e.base)
            idx = self.analyze(e.index)
            if isinstance(base.type, T.ArrayType):
                if not idx.type.is_integer_kind:
                    raise AnalysisError("array subscript must be an integer")
                return ir.Call(base.type.element, "subscript", (base, idx))
            if isinstance(base.type, T.MapType):
                self._check_comparable(base.type.key, idx.type, "[]")
                return ir.Call(base.type.value, "map_subscript", (base, idx))
            if isinstance(base.type, T.RowType):
                # row[i]: 1-based CONSTANT field ordinal (reference:
                # RowType subscript / DereferenceExpression)
                if not isinstance(idx, ir.Constant) or idx.value is None:
                    raise AnalysisError("row subscript must be a constant")
                i = int(idx.value)
                if not 1 <= i <= len(base.type.field_types):
                    raise AnalysisError(f"row field index {i} out of range")
                return ir.Call(base.type.field_types[i - 1], "row_field",
                               (base, ir.Constant(T.INTEGER, i)))
            raise AnalysisError(f"cannot subscript {base.type}")
        if isinstance(e, ast.FunctionCall):
            return self._analyze_function(e)
        if isinstance(e, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
            raise AnalysisError(
                "subquery expression must be planned by the query planner "
                "(appears in unsupported position)"
            )
        raise AnalysisError(f"unsupported expression: {type(e).__name__}")

    def _analyze_arithmetic(self, e: ast.Arithmetic) -> ir.Expr:
        # date +/- interval
        for left_ast, right_ast, sign in ((e.left, e.right, 1), (e.right, e.left, 1)):
            if isinstance(right_ast, ast.IntervalLiteral):
                base = self.analyze(left_ast)
                iv = right_ast
                mult = iv.sign * (1 if e.op == "+" else -1)
                is_ts = isinstance(base.type, T.TimestampType)
                if base.type != T.DATE and not is_ts:
                    raise AnalysisError("interval arithmetic requires a date/timestamp")
                if iv.unit in _MONTH_UNITS:
                    months = iv.value * _MONTH_UNITS[iv.unit] * mult
                    return ir.Call(
                        base.type, "date_add_months", (base, ir.Constant(T.INTEGER, months))
                    )
                if is_ts and iv.unit in _SECOND_UNITS:
                    # day-time intervals over timestamps add in storage units
                    n = (iv.value * _SECOND_UNITS[iv.unit] * mult
                         * 10 ** base.type.precision)
                    return ir.Call(
                        base.type, "add", (base, ir.Constant(T.BIGINT, n)))
                if iv.unit == "day":
                    return ir.Call(
                        base.type,
                        "add",
                        (base, ir.Constant(T.INTEGER, iv.value * mult)),
                    )
                raise AnalysisError(f"interval unit {iv.unit} on date")
        left = self.analyze(e.left)
        right = self.analyze(e.right)
        out = arithmetic_result_type(e.op, left.type, right.type)
        return ir.Call(out, _ARITH_OPS[e.op], (left, right))

    def _analyze_function(self, e: ast.FunctionCall) -> ir.Expr:
        name = e.name
        if name in AGGREGATE_FUNCTIONS:
            raise AnalysisError(
                f"aggregate function {name}() in a non-aggregate context"
                if not self.allow_aggregates
                else f"aggregate {name}() must be substituted by the planner"
            )
        # higher-order array functions take a lambda argument (reference:
        # operator/scalar/ArrayTransformFunction, ArrayAnyMatchFunction, ...)
        if name in ("transform", "any_match", "all_match", "none_match"):
            if len(e.args) != 2 or not isinstance(e.args[1], ast.Lambda):
                raise AnalysisError(f"{name}(array, x -> expression)")
            arr = self.analyze(e.args[0])
            if not isinstance(arr.type, T.ArrayType):
                raise AnalysisError(f"{name}() expects an array")
            lam = e.args[1]
            if len(lam.params) != 1:
                raise AnalysisError(f"{name}() lambda takes one parameter")
            elem_scope = Scope([Field(lam.params[0], arr.type.element)], None)
            body = ExprAnalyzer(elem_scope).analyze(lam.body)
            lam_ir = ir.Lambda(body.type, body, 1)
            if name == "transform":
                return ir.Call(T.array_of(body.type), "transform", (arr, lam_ir))
            if body.type != T.BOOLEAN:
                raise AnalysisError(f"{name}() lambda must return boolean")
            return ir.Call(T.BOOLEAN, name, (arr, lam_ir))
        if any(isinstance(a, ast.Lambda) for a in e.args):
            raise AnalysisError(f"{name}() does not take a lambda argument")
        args = tuple(self.analyze(a) for a in e.args)
        if name == "coalesce":
            t = args[0].type
            for a in args[1:]:
                t2 = T.common_super_type(t, a.type)
                if t2 is None:
                    raise AnalysisError("COALESCE operands are incompatible")
                t = t2
            return ir.Call(t, "coalesce", args)
        if name == "nullif":
            return ir.Call(args[0].type, "nullif", args)
        if name == "abs":
            return ir.Call(args[0].type, "abs", args)
        if name in ("substring", "substr"):
            return ir.Call(T.varchar(), "substring", args)
        if name == "concat":
            return ir.Call(T.varchar(), "concat", args)
        if name in ("lower", "upper", "trim", "ltrim", "rtrim"):
            return ir.Call(T.varchar(), name, args)
        if name == "length":
            return ir.Call(T.BIGINT, "length", args)
        if name in ("to_hex", "from_utf8"):
            if len(args) != 1 or not args[0].type.is_varbinary:
                raise AnalysisError(f"{name}(varbinary)")
            return ir.Call(T.varchar(), name, args)
        if name in ("from_hex", "to_utf8"):
            if len(args) != 1 or not args[0].type.is_varchar \
                    or args[0].type.is_varbinary:
                raise AnalysisError(f"{name}(varchar)")
            return ir.Call(T.VARBINARY, name, args)
        if name in ("md5", "sha256"):
            if len(args) != 1 or not args[0].type.is_varbinary:
                raise AnalysisError(f"{name}(varbinary)")
            return ir.Call(T.VARBINARY, name, args)
        if name in ("round", "ceil", "ceiling", "floor"):
            return ir.Call(args[0].type if args[0].type.is_decimal else T.DOUBLE if args[0].type.is_floating else T.BIGINT, name, args)
        if name in ("sqrt", "cbrt", "ln", "log2", "log10", "exp"):
            if len(args) != 1:
                raise AnalysisError(f"{name}() expects 1 argument")
            return ir.Call(T.DOUBLE, name, args)
        if name == "log":
            if len(args) != 2:
                raise AnalysisError("log(base, x) expects 2 arguments")
            return ir.Call(T.DOUBLE, "log_b", args)
        if name in ("power", "pow"):
            return ir.Call(T.DOUBLE, "power", args)
        if name == "sign":
            t = args[0].type
            return ir.Call(T.DOUBLE if t.is_floating else T.BIGINT, "sign", args)
        if name in ("greatest", "least"):
            t = args[0].type
            for a in args[1:]:
                t2 = T.common_super_type(t, a.type)
                if t2 is None:
                    raise AnalysisError(f"{name} operands are incompatible")
                t = t2
            args = tuple(ir.Cast(t, a) if a.type != t else a for a in args)
            return ir.Call(t, name, args)
        if name == "year":
            return ir.Call(T.BIGINT, "extract_year", args)
        if name == "month":
            return ir.Call(T.BIGINT, "extract_month", args)
        if name == "day":
            return ir.Call(T.BIGINT, "extract_day", args)
        if name in ("day_of_week", "dow"):
            return ir.Call(T.BIGINT, "extract_dow", args)
        if name in ("day_of_year", "doy"):
            return ir.Call(T.BIGINT, "extract_doy", args)
        if name == "week":
            return ir.Call(T.BIGINT, "extract_week", args)
        if name == "date_diff":
            # date_diff(unit, from, to) -> bigint (reference:
            # DateTimeFunctions.diffDate/diffTimestamp)
            if len(args) != 3 or not isinstance(args[0], ir.Constant):
                raise AnalysisError("date_diff('unit', from, to)")
            unit = str(args[0].value).lower()
            a, b = args[1], args[2]
            ts_units = {"second": 1, "minute": 60, "hour": 3600,
                        "day": 86_400, "week": 7 * 86_400}
            date_units = {"day": 1, "week": 7}
            both_date = a.type == T.DATE and b.type == T.DATE
            if both_date and unit in date_units:
                return ir.Call(T.BIGINT, "date_diff_days",
                               (a, b, ir.Constant(T.INTEGER, date_units[unit])))
            if unit in ts_units:
                p = max(t.precision if isinstance(t, T.TimestampType) else 0
                        for t in (a.type, b.type))
                tt = T.timestamp(p)
                return ir.Call(
                    T.BIGINT, "ts_diff_units",
                    (ir.Cast(tt, a), ir.Cast(tt, b),
                     ir.Constant(T.BIGINT, ts_units[unit] * 10 ** p)))
            if unit in ("month", "year"):
                mul = 12 if unit == "year" else 1
                da = a if a.type == T.DATE else ir.Cast(T.DATE, a)
                db = b if b.type == T.DATE else ir.Cast(T.DATE, b)
                return ir.Call(T.BIGINT, "months_between",
                               (da, db, ir.Constant(T.INTEGER, mul)))
            raise AnalysisError(f"date_diff: unsupported unit {unit!r}")
        if name == "date_add":
            # date_add(unit, value, x) (reference: DateTimeFunctions.addDate)
            if len(args) != 3 or not isinstance(args[0], ir.Constant):
                raise AnalysisError("date_add('unit', value, x)")
            unit = str(args[0].value).lower()
            n, x = args[1], args[2]
            if unit in ("month", "year"):
                mul = ir.Constant(T.INTEGER, 12 if unit == "year" else 1)
                months = ir.Call(T.INTEGER, "mul", [n, mul])
                return ir.Call(x.type, "date_add_months", (x, months))
            ts_units = {"second": 1, "minute": 60, "hour": 3600,
                        "day": 86_400, "week": 7 * 86_400}
            if unit not in ts_units:
                raise AnalysisError(f"date_add: unsupported unit {unit!r}")
            if x.type == T.DATE:
                if unit in ("day", "week"):
                    days = ir.Call(T.BIGINT, "mul", [
                        n, ir.Constant(T.INTEGER, ts_units[unit] // 86_400)])
                    return ir.Call(T.DATE, "add", (x, days))
                x = ir.Cast(T.timestamp(0), x)
            if not isinstance(x.type, T.TimestampType):
                raise AnalysisError("date_add over non-temporal value")
            step = ir.Constant(
                T.BIGINT, ts_units[unit] * 10 ** x.type.precision)
            return ir.Call(x.type, "add",
                           (x, ir.Call(T.BIGINT, "mul", [n, step])))
        if name == "to_unixtime":
            if len(args) != 1:
                raise AnalysisError("to_unixtime(timestamp)")
            arg = args[0]
            if arg.type == T.DATE:
                arg = ir.Cast(T.timestamp(0), arg)
            if not isinstance(arg.type, T.TimestampType):
                raise AnalysisError("to_unixtime(timestamp)")
            p = arg.type.precision
            return ir.Call(T.DOUBLE, "div",
                           (ir.Cast(T.DOUBLE, arg),
                            ir.Constant(T.DOUBLE, float(10 ** p))))
        if name == "from_unixtime":
            if len(args) != 1:
                raise AnalysisError("from_unixtime(seconds)")
            return ir.Call(T.timestamp(3), "seconds_to_ts3",
                           (ir.Cast(T.DOUBLE, args[0]),))
        if name == "date_trunc":
            if len(args) != 2 or args[1].type != T.DATE:
                raise AnalysisError("date_trunc(unit, date) expects a date")
            return ir.Call(T.DATE, "date_trunc", args)
        if name == "replace":
            if len(args) not in (2, 3):
                raise AnalysisError("replace(string, search[, replace])")
            return ir.Call(T.varchar(), "replace", args)
        if name == "reverse":
            return ir.Call(T.varchar(), "reverse", args)
        if name in ("strpos", "position"):
            return ir.Call(T.BIGINT, "strpos", args)
        if name == "starts_with":
            return ir.Call(T.BOOLEAN, "starts_with", args)
        if name in ("sin", "cos", "tan", "asin", "acos", "atan",
                    "sinh", "cosh", "tanh", "degrees", "radians"):
            if len(args) != 1:
                raise AnalysisError(f"{name}() expects 1 argument")
            return ir.Call(T.DOUBLE, name, args)
        if name == "atan2":
            if len(args) != 2:
                raise AnalysisError("atan2(y, x) expects 2 arguments")
            return ir.Call(T.DOUBLE, "atan2", args)
        # --- non-deterministic functions (reference: MathFunctions.random /
        # DateTimeFunctions.now; tagged deterministic=false there). They
        # stay symbolic Calls — never constant-folded — so the cache
        # layer's determinism analysis (trino_tpu/cache/determinism.py)
        # sees them in both the AST and the optimized plan.
        if name in ("random", "rand"):
            if args:
                raise AnalysisError("random() takes no arguments")
            return ir.Call(T.DOUBLE, "random", ())
        if name in ("now", "current_timestamp", "localtimestamp"):
            if args:
                raise AnalysisError(f"{name}() takes no arguments")
            return ir.Call(T.timestamp(3), "now", ())
        if name == "current_date":
            if args:
                raise AnalysisError("current_date() takes no arguments")
            return ir.Call(T.DATE, "current_date", ())
        if name == "pi":
            import math

            return ir.Constant(T.DOUBLE, math.pi)
        if name == "e":
            import math

            return ir.Constant(T.DOUBLE, math.e)
        if name == "truncate":
            if len(args) not in (1, 2):
                raise AnalysisError("truncate(x[, decimal_places])")
            if len(args) == 2 and not isinstance(args[1], ir.Constant):
                raise AnalysisError("truncate scale must be a literal")
            t = args[0].type
            return ir.Call(t if t.is_decimal or t.is_floating else T.BIGINT,
                           "truncate", args)
        if name == "mod":
            if len(args) != 2:
                raise AnalysisError("mod(a, b) expects 2 arguments")
            return ir.Call(
                arithmetic_result_type("%", args[0].type, args[1].type), "mod", args)
        # --- regexp / string breadth (reference: operator/scalar/
        # JoniRegexpFunctions, StringFunctions, PadFunctions) ---
        if name == "regexp_like":
            if len(args) != 2:
                raise AnalysisError("regexp_like(string, pattern)")
            return ir.Call(T.BOOLEAN, "regexp_like", args)
        if name == "regexp_extract":
            if len(args) not in (2, 3):
                raise AnalysisError("regexp_extract(string, pattern[, group])")
            return ir.Call(T.varchar(), "regexp_extract", args)
        if name == "regexp_replace":
            if len(args) not in (2, 3):
                raise AnalysisError("regexp_replace(string, pattern[, replacement])")
            return ir.Call(T.varchar(), "regexp_replace", args)
        if name == "regexp_count":
            if len(args) != 2:
                raise AnalysisError("regexp_count(string, pattern)")
            return ir.Call(T.BIGINT, "regexp_count", args)
        if name in ("lpad", "rpad"):
            if len(args) not in (2, 3):
                raise AnalysisError(f"{name}(string, size[, padstring])")
            return ir.Call(T.varchar(), name, args)
        if name == "split_part":
            if len(args) != 3:
                raise AnalysisError("split_part(string, delimiter, index)")
            return ir.Call(T.varchar(), "split_part", args)
        if name == "translate":
            if len(args) != 3:
                raise AnalysisError("translate(string, from, to)")
            return ir.Call(T.varchar(), "translate", args)
        if name == "repeat" and args and args[0].type.is_varchar:
            return ir.Call(T.varchar(), "repeat_str", args)
        if name == "chr":
            return ir.Call(T.varchar(), "chr", args)
        if name == "codepoint":
            return ir.Call(T.BIGINT, "codepoint", args)
        if name == "hamming_distance":
            return ir.Call(T.BIGINT, "hamming_distance", args)
        if name == "levenshtein_distance":
            return ir.Call(T.BIGINT, "levenshtein_distance", args)
        # --- JSON (reference: operator/scalar/JsonFunctions + JsonPath) ---
        if name == "json_extract_scalar":
            if len(args) != 2:
                raise AnalysisError("json_extract_scalar(json, path)")
            return ir.Call(T.varchar(), "json_extract_scalar", args)
        if name == "json_array_length":
            return ir.Call(T.BIGINT, "json_array_length", args)
        # --- datetime breadth (reference: operator/scalar/DateTimeFunctions) ---
        if name == "date_format":
            if len(args) != 2 or args[0].type not in (T.DATE, T.TIMESTAMP):
                raise AnalysisError("date_format(date, format)")
            return ir.Call(T.varchar(), "date_format", args)
        if name == "date_parse":
            if len(args) != 2:
                raise AnalysisError("date_parse(string, format)")
            return ir.Call(T.DATE, "date_parse", args)
        if name == "day_name":
            return ir.Call(T.varchar(), "day_name", args)
        if name == "month_name":
            return ir.Call(T.varchar(), "month_name", args)
        if name == "last_day_of_month":
            return ir.Call(T.DATE, "last_day_of_month", args)
        # --- bitwise (reference: operator/scalar/BitwiseFunctions) ---
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor",
                    "bitwise_left_shift", "bitwise_right_shift"):
            if len(args) != 2:
                raise AnalysisError(f"{name}(a, b)")
            return ir.Call(T.BIGINT, name, args)
        if name == "bitwise_not":
            return ir.Call(T.BIGINT, "bitwise_not", args)
        if name == "bit_count":
            return ir.Call(T.BIGINT, "bit_count", args)
        # --- float classification / misc ---
        if name == "is_nan":
            return ir.Call(T.BOOLEAN, "is_nan", args)
        if name == "is_finite":
            return ir.Call(T.BOOLEAN, "is_finite", args)
        if name == "is_infinite":
            return ir.Call(T.BOOLEAN, "is_infinite", args)
        if name == "nan":
            return ir.Constant(T.DOUBLE, float("nan"))
        if name == "infinity":
            return ir.Constant(T.DOUBLE, float("inf"))
        if name == "typeof":
            if len(args) != 1:
                raise AnalysisError("typeof(x)")
            return ir.Constant(T.varchar(), str(args[0].type))
        if name == "if":
            if len(args) not in (2, 3):
                raise AnalysisError("if(condition, true_value[, false_value])")
            t = args[1].type
            if len(args) == 3:
                t2 = T.common_super_type(t, args[2].type)
                if t2 is None:
                    raise AnalysisError("IF branches are incompatible")
                t = t2
            whens = ((args[0], args[1]),)
            default = args[2] if len(args) == 3 else None
            return ir.Case(t, whens, default)
        # --- array / map functions (reference: operator/scalar/ArrayFunctions,
        # MapKeys/MapValues/MapSubscript, CardinalityFunction) ---
        if name == "cardinality":
            if len(args) != 1 or not (args[0].type.is_array or args[0].type.is_map):
                raise AnalysisError("cardinality() expects an array or map")
            return ir.Call(T.BIGINT, "cardinality", args)
        if name == "contains":
            if len(args) != 2 or not isinstance(args[0].type, T.ArrayType):
                raise AnalysisError("contains(array, value)")
            self._check_comparable(args[0].type.element, args[1].type, "contains")
            return ir.Call(T.BOOLEAN, "contains", args)
        if name == "array_position":
            if len(args) != 2 or not isinstance(args[0].type, T.ArrayType):
                raise AnalysisError("array_position(array, value)")
            self._check_comparable(args[0].type.element, args[1].type, "array_position")
            return ir.Call(T.BIGINT, "array_position", args)
        if name == "element_at":
            if len(args) != 2:
                raise AnalysisError("element_at(container, key)")
            if isinstance(args[0].type, T.ArrayType):
                if not args[1].type.is_integer_kind:
                    raise AnalysisError("element_at(array, index) index must be an integer")
                return ir.Call(args[0].type.element, "element_at", args)
            if isinstance(args[0].type, T.MapType):
                self._check_comparable(args[0].type.key, args[1].type, "element_at")
                return ir.Call(args[0].type.value, "map_element_at", args)
            raise AnalysisError("element_at() expects an array or map")
        if name in ("array_min", "array_max"):
            if len(args) != 1 or not isinstance(args[0].type, T.ArrayType):
                raise AnalysisError(f"{name}(array)")
            return ir.Call(args[0].type.element, name, args)
        if name in ("array_sum",):
            if len(args) != 1 or not isinstance(args[0].type, T.ArrayType):
                raise AnalysisError("array_sum(array)")
            return ir.Call(aggregate_result_type("sum", args[0].type.element), name, args)
        if name == "map_keys":
            if len(args) != 1 or not isinstance(args[0].type, T.MapType):
                raise AnalysisError("map_keys(map)")
            return ir.Call(T.array_of(args[0].type.key), "map_keys", args)
        if name == "map_values":
            if len(args) != 1 or not isinstance(args[0].type, T.MapType):
                raise AnalysisError("map_values(map)")
            return ir.Call(T.array_of(args[0].type.value), "map_values", args)
        if name == "map":
            if len(args) != 2 or not all(isinstance(a.type, T.ArrayType) for a in args):
                raise AnalysisError("map(key_array, value_array)")
            return ir.Call(
                T.map_of(args[0].type.element, args[1].type.element), "map_ctor", args
            )
        if name == "row":
            if not args:
                raise AnalysisError("row() needs at least one field")
            if any(a.type == T.UNKNOWN for a in args):
                raise AnalysisError("row() fields must be typed (cast NULLs)")
            return ir.Call(
                T.row_of([(None, a.type) for a in args]), "row_ctor", args)
        raise AnalysisError(f"unknown function: {name}")

    @staticmethod
    def _check_comparable(a: T.Type, b: T.Type, op: str):
        t = T.common_super_type(a, b)
        if t is None or not t.comparable:
            raise AnalysisError(f"cannot compare {a} {op} {b}")
        if op in ("<", "<=", ">", ">=") and not t.orderable:
            raise AnalysisError(f"type {t} is not orderable for {op}")


def _case_type(values: List[ir.Expr], default: Optional[ir.Expr]) -> T.Type:
    t = T.UNKNOWN
    for v in list(values) + ([default] if default is not None else []):
        t2 = T.common_super_type(t, v.type)
        if t2 is None:
            raise AnalysisError(f"CASE branches incompatible: {t} vs {v.type}")
        t = t2
    return t


def find_aggregates(e: ast.Expression) -> List[ast.FunctionCall]:
    """Collect aggregate FunctionCall subtrees (no nesting inside them).
    Descends into window functions: ``rank() over (order by sum(x))`` uses
    the grouped aggregate as a window input."""
    out: List[ast.FunctionCall] = []

    def visit(x):
        if isinstance(x, ast.FunctionCall) and x.name in AGGREGATE_FUNCTIONS:
            out.append(x)
            return  # don't descend: nested aggregates are invalid anyway
        if isinstance(x, ast.WindowFunction):
            for a in x.args:
                visit(a)
            for p in x.partition_by:
                visit(p)
            for s in x.order_by:
                visit(s.expr)
            return
        if isinstance(x, tuple):
            for y in x:
                visit(y)
            return
        if hasattr(x, "__dataclass_fields__"):
            for f in x.__dataclass_fields__:
                v = getattr(x, f)
                if isinstance(v, (ast.Expression, tuple)):
                    visit(v)

    visit(e)
    return out


WINDOW_ONLY_FUNCTIONS = {
    "rank", "dense_rank", "row_number", "lag", "lead",
    "first_value", "last_value",
    "ntile", "percent_rank", "cume_dist", "nth_value",
}


def find_windows(e: ast.Expression) -> List[ast.WindowFunction]:
    """Collect window-function subtrees (no window nesting)."""
    out: List[ast.WindowFunction] = []

    def visit(x):
        if isinstance(x, ast.WindowFunction):
            out.append(x)
            return
        if isinstance(x, tuple):
            for y in x:
                visit(y)
            return
        if hasattr(x, "__dataclass_fields__"):
            for f in x.__dataclass_fields__:
                v = getattr(x, f)
                if isinstance(v, (ast.Expression, tuple)):
                    visit(v)

    visit(e)
    return out


def window_result_type(fn: str, arg: Optional[T.Type]) -> T.Type:
    """Reference: window function signatures (window/ + ranking fns)."""
    if fn in ("rank", "dense_rank", "row_number", "ntile"):
        return T.BIGINT
    if fn in ("percent_rank", "cume_dist"):
        return T.DOUBLE
    if fn in ("lag", "lead", "first_value", "last_value", "nth_value"):
        assert arg is not None
        return arg
    return aggregate_result_type(fn, arg)
