"""SQL lexer.

Reference: the lexer rules at the bottom of
``core/trino-grammar/src/main/antlr4/io/trino/grammar/sql/SqlBase.g4``
(IDENTIFIER / QUOTED_IDENTIFIER / STRING / number / comment rules). Hand
written here: tokens carry position for error messages.
"""
from __future__ import annotations

import dataclasses
from typing import List

KEYWORDS = {
    # kept to what the round-1 grammar understands; grows with the grammar
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "exists", "between", "like", "escape",
    "is", "null", "true", "false", "case", "when", "then", "else", "end",
    "cast", "extract", "interval", "date", "timestamp", "join", "inner",
    "left", "right", "full", "outer", "cross", "on", "using", "union",
    "intersect", "except", "all", "distinct", "with", "asc", "desc",
    "nulls", "first", "last", "explain", "analyze", "show", "tables",
    "schemas", "columns", "describe", "values", "substring", "for", "year",
    "month", "day", "hour", "minute", "second", "quarter", "set", "reset",
    "session", "create", "insert", "into", "drop", "if", "table",
}


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str  # 'kw' | 'ident' | 'number' | 'string' | 'op' | 'eof'
    text: str
    pos: int

    @property
    def lower(self) -> str:
        return self.text.lower()


class LexError(ValueError):
    pass


_OPS = [
    "<>", "!=", ">=", "<=", "||", "=>", "->", "=", "<", ">", "+", "-", "*",
    "/", "%", "(", ")", "[", "]", ",", ".", ";", "?",
]


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i)
            if j < 0:
                raise LexError(f"unterminated block comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise LexError(f"unterminated string literal at {i}")
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":  # escaped ''
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            out.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise LexError(f"unterminated quoted identifier at {i}")
            out.append(Token("ident", sql[i + 1 : j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    if j + 1 < n and (sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                        seen_exp = True
                        j += 2
                    else:
                        break
                else:
                    break
            out.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            text = sql[i:j]
            kind = "kw" if text.lower() in KEYWORDS else "ident"
            out.append(Token(kind, text, i))
            i = j
            continue
        for op in _OPS:
            if sql.startswith(op, i):
                out.append(Token("op", op, i))
                i += len(op)
                break
        else:
            raise LexError(f"unexpected character {c!r} at position {i}")
    out.append(Token("eof", "", n))
    return out
