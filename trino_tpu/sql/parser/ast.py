"""Parser AST.

Reference: ``core/trino-parser/src/main/java/io/trino/sql/tree/`` (289 node
classes). This is the *parser* AST — distinct from the post-analysis IR in
``trino_tpu.sql.ir``, mirroring the reference's AST/IR split. Round-1 scope:
the query surface TPC-H/TPC-DS need (SELECT/joins/subqueries/CTEs/CASE/
EXISTS/IN/aggregates/window-less) plus EXPLAIN.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


class Node:
    pass


class Expression(Node):
    pass


class Relation(Node):
    pass


class Statement(Node):
    pass


# --- expressions -----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Literal(Expression):
    kind: str  # 'number' | 'string' | 'boolean' | 'null' | 'date' | 'timestamp'
    value: object


@dataclasses.dataclass(frozen=True)
class IntervalLiteral(Expression):
    value: int
    unit: str  # 'year' | 'month' | 'day' | 'hour' | 'minute' | 'second'
    sign: int = 1


@dataclasses.dataclass(frozen=True)
class Identifier(Expression):
    parts: Tuple[str, ...]  # possibly qualified: (table, column) or (column,)

    @property
    def name(self) -> str:
        return self.parts[-1]


@dataclasses.dataclass(frozen=True)
class Star(Expression):
    qualifier: Optional[Tuple[str, ...]] = None  # t.* has qualifier ('t',)


@dataclasses.dataclass(frozen=True)
class FunctionCall(Expression):
    name: str
    args: Tuple[Expression, ...]
    distinct: bool = False
    is_star: bool = False  # count(*)


@dataclasses.dataclass(frozen=True)
class WindowFunction(Expression):
    """fn(args) OVER (PARTITION BY ... ORDER BY ... [frame]).

    Reference: sql/tree/WindowSpecification + FunctionCall.window. ``frame``
    is (mode, start_bound, end_bound) as lowercase strings, None = default
    (RANGE UNBOUNDED PRECEDING -> CURRENT ROW when ORDER BY present, whole
    partition otherwise)."""

    name: str
    args: Tuple[Expression, ...]
    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple["SortItem", ...] = ()
    is_star: bool = False  # count(*) over (...)
    frame: Optional[Tuple[str, str, str]] = None


@dataclasses.dataclass(frozen=True)
class Arithmetic(Expression):
    op: str  # + - * / %
    left: Expression
    right: Expression


@dataclasses.dataclass(frozen=True)
class Negative(Expression):
    value: Expression


@dataclasses.dataclass(frozen=True)
class Comparison(Expression):
    op: str  # = <> < <= > >=
    left: Expression
    right: Expression


@dataclasses.dataclass(frozen=True)
class LogicalBinary(Expression):
    op: str  # and | or
    left: Expression
    right: Expression


@dataclasses.dataclass(frozen=True)
class Not(Expression):
    value: Expression


@dataclasses.dataclass(frozen=True)
class IsNull(Expression):
    value: Expression
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Between(Expression):
    value: Expression
    low: Expression
    high: Expression
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList(Expression):
    value: Expression
    items: Tuple[Expression, ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InSubquery(Expression):
    value: Expression
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Exists(Expression):
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Expression):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Like(Expression):
    value: Expression
    pattern: Expression
    escape: Optional[Expression] = None
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class SearchedCase(Expression):
    whens: Tuple[Tuple[Expression, Expression], ...]
    default: Optional[Expression]


@dataclasses.dataclass(frozen=True)
class SimpleCase(Expression):
    operand: Expression
    whens: Tuple[Tuple[Expression, Expression], ...]
    default: Optional[Expression]


@dataclasses.dataclass(frozen=True)
class Cast(Expression):
    value: Expression
    type_name: str


@dataclasses.dataclass(frozen=True)
class Extract(Expression):
    field: str  # year month day quarter ...
    value: Expression


@dataclasses.dataclass(frozen=True)
class ArrayConstructor(Expression):
    items: Tuple[Expression, ...]


@dataclasses.dataclass(frozen=True)
class Lambda(Expression):
    """x -> expr (reference: sql/tree/LambdaExpression.java); valid only as
    an argument of the higher-order array functions."""

    params: Tuple[str, ...]
    body: Expression


@dataclasses.dataclass(frozen=True)
class Subscript(Expression):
    base: Expression
    index: Expression


@dataclasses.dataclass(frozen=True)
class AtTimeZone(Expression):
    """value AT TIME ZONE 'zone' (reference: grammar atTimeZone +
    DateTimeFunctions.timeZone*)."""

    value: Expression
    zone: str


# --- relations -------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Table(Relation):
    parts: Tuple[str, ...]  # catalog.schema.table, schema.table, or table


@dataclasses.dataclass(frozen=True)
class AliasedRelation(Relation):
    relation: Relation
    alias: str
    column_aliases: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class Join(Relation):
    join_type: str  # inner | left | right | full | cross | implicit
    left: Relation
    right: Relation
    on: Optional[Expression] = None
    using: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class SubqueryRelation(Relation):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class TableFunctionCall(Relation):
    """TABLE(fn(args...)) — polymorphic table function invocation
    (reference: sql/tree/TableFunctionInvocation + spi/function/table/)."""

    name: str
    args: Tuple[Expression, ...]
    named_args: dict = None

    def __hash__(self):  # dict field: hash by identity-relevant parts
        return hash((self.name, self.args, tuple(sorted(
            (self.named_args or {}).items(), key=lambda kv: kv[0]))))


@dataclasses.dataclass(frozen=True)
class MatchRecognize(Relation):
    """relation MATCH_RECOGNIZE (...) (reference: grammar
    patternRecognition + sql/tree/PatternRecognitionRelation). Subset:
    ONE ROW PER MATCH, AFTER MATCH SKIP PAST LAST ROW / TO NEXT ROW,
    concatenation patterns with ?/*/+ quantifiers."""

    input: Relation
    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple[Tuple[Expression, bool], ...] = ()  # (expr, ascending)
    measures: Tuple[Tuple[Expression, str], ...] = ()
    after_match: str = "past_last"  # past_last | next_row
    pattern: Tuple[Tuple[str, str], ...] = ()  # (variable, quantifier)
    defines: Tuple[Tuple[str, Expression], ...] = ()

    def __hash__(self):
        return hash((self.input, self.partition_by, self.pattern))


@dataclasses.dataclass(frozen=True)
class Unnest(Relation):
    """UNNEST(e1, e2, ...) [WITH ORDINALITY] — a lateral relation whose
    argument expressions may reference columns of the preceding FROM items.
    Reference: SqlBase.g4 unnest rule + RelationPlanner.visitUnnest."""

    exprs: Tuple[Expression, ...]
    ordinality: bool = False


# --- query structure -------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SelectItem(Node):
    expr: Expression
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SortItem(Node):
    expr: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = default (last for asc, first for desc)


@dataclasses.dataclass(frozen=True)
class QuerySpec(Node):
    select_items: Tuple[SelectItem, ...]
    distinct: bool
    from_: Optional[Relation]
    where: Optional[Expression]
    group_by: Tuple[Expression, ...]
    having: Optional[Expression]
    # GROUPING SETS / ROLLUP / CUBE: a tuple of grouping sets (each a tuple
    # of expressions); the planner expands them (reference: GroupIdNode)
    grouping_sets: Optional[Tuple[Tuple[Expression, ...], ...]] = None


@dataclasses.dataclass(frozen=True)
class SetOperation(Node):
    op: str  # union | intersect | except
    all: bool
    left: "QueryBody"
    right: "QueryBody"


@dataclasses.dataclass(frozen=True)
class Values(Node):
    """VALUES (e, ...), ... as a query body (reference: sql/tree/Values)."""

    rows: Tuple[Tuple[Expression, ...], ...]


QueryBody = object  # QuerySpec | SetOperation | Values | Query (parenthesized)


@dataclasses.dataclass(frozen=True)
class WithQuery(Node):
    name: str
    query: "Query"
    column_aliases: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class Query(Statement):
    body: QueryBody
    with_queries: Tuple[WithQuery, ...] = ()
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class Explain(Statement):
    statement: Statement
    analyze: bool = False
    mode: str = "logical"  # logical | distributed
    fmt: str = "text"
    # EXPLAIN ANALYZE VERBOSE: add device detail (output/peak bytes,
    # compile-cache disposition, spill counts) to the annotated plan
    verbose: bool = False


@dataclasses.dataclass(frozen=True)
class CreateTable(Statement):
    """CREATE TABLE name (col type, ...) (reference: sql/tree/CreateTable)."""

    name: tuple  # qualified name parts
    columns: tuple  # ((name, type_text), ...)
    not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateTableAs(Statement):
    """CREATE TABLE name AS query (reference: sql/tree/CreateTableAsSelect)."""

    name: tuple
    query: "Query" = None
    not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateMaterializedView(Statement):
    """CREATE [OR REPLACE] MATERIALIZED VIEW [IF NOT EXISTS] name AS query
    (reference: sql/tree/CreateMaterializedView + the connector SPI's
    getMaterializedView/MaterializedViewFreshness flow)."""

    name: tuple  # qualified name parts
    query: "Query" = None
    not_exists: bool = False
    or_replace: bool = False


@dataclasses.dataclass(frozen=True)
class RefreshMaterializedView(Statement):
    """REFRESH MATERIALIZED VIEW name (reference:
    sql/tree/RefreshMaterializedView + RefreshMaterializedViewTask)."""

    name: tuple


@dataclasses.dataclass(frozen=True)
class DropMaterializedView(Statement):
    name: tuple
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Insert(Statement):
    """INSERT INTO name [(cols)] query (VALUES arrives as a Values query
    body; reference: sql/tree/Insert)."""

    name: tuple
    columns: tuple  # () = table order
    query: "Query" = None


@dataclasses.dataclass(frozen=True)
class DropTable(Statement):
    name: tuple
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Delete(Statement):
    """DELETE FROM t [WHERE pred] (reference: sql/tree/Delete +
    execution via connector row-change machinery)."""

    name: tuple
    where: Expression = None


@dataclasses.dataclass(frozen=True)
class Update(Statement):
    """UPDATE t SET c = e, ... [WHERE pred] (reference: sql/tree/Update)."""

    name: tuple
    assignments: tuple  # ((column, Expression), ...)
    where: Expression = None


@dataclasses.dataclass(frozen=True)
class CreateFunction(Statement):
    """CREATE [OR REPLACE] FUNCTION name(p type, ...) RETURNS t RETURN expr
    (reference: sql/tree/CreateFunction + CreateFunctionTask)."""

    name: tuple
    params: tuple  # ((name, type string), ...)
    returns: str
    body: Expression
    or_replace: bool = False


@dataclasses.dataclass(frozen=True)
class DropFunction(Statement):
    name: tuple
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Parameter(Expression):
    """A ``?`` placeholder in a prepared statement (reference:
    sql/tree/Parameter.java); bound at EXECUTE ... USING time."""

    index: int


@dataclasses.dataclass(frozen=True)
class Prepare(Statement):
    name: str
    statement: "Statement"


@dataclasses.dataclass(frozen=True)
class ExecutePrepared(Statement):
    name: str
    params: Tuple[Expression, ...] = ()


@dataclasses.dataclass(frozen=True)
class Deallocate(Statement):
    name: str


@dataclasses.dataclass(frozen=True)
class StartTransaction(Statement):
    pass


@dataclasses.dataclass(frozen=True)
class Commit(Statement):
    pass


@dataclasses.dataclass(frozen=True)
class Rollback(Statement):
    pass


@dataclasses.dataclass(frozen=True)
class Call(Statement):
    """CALL catalog.schema.procedure(arg, ...) (reference: sql/tree/Call +
    execution/CallTask routing to the connector procedure SPI). Arguments
    must be constant expressions."""

    name: Tuple[str, ...]
    args: Tuple[Expression, ...] = ()


@dataclasses.dataclass(frozen=True)
class SetSession(Statement):
    """SET SESSION name = value (reference: sql/tree/SetSession.java)."""

    name: str
    value: object


@dataclasses.dataclass(frozen=True)
class ResetSession(Statement):
    name: str


@dataclasses.dataclass(frozen=True)
class ShowSession(Statement):
    pass


@dataclasses.dataclass(frozen=True)
class ShowTables(Statement):
    schema: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ShowSchemas(Statement):
    catalog: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ShowColumns(Statement):
    table: Tuple[str, ...] = ()
