"""Recursive-descent SQL parser.

Reference: ``core/trino-grammar/.../SqlBase.g4`` (ANTLR4, 1420 lines) +
``core/trino-parser/.../AstBuilder.java:369``. Hand-written Pratt-style
parser over the same query surface (round-1 scope: SELECT queries with
joins/subqueries/CTEs/set-ops, EXPLAIN, SHOW).

Grammar precedence (low to high):
  OR < AND < NOT < predicate (comparison, BETWEEN, IN, LIKE, IS) <
  || (concat) < + - < * / % < unary - < primary
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from trino_tpu.sql.parser import ast
from trino_tpu.sql.parser.lexer import Token, tokenize


class ParseError(ValueError):
    pass


def parse_statement(sql: str) -> ast.Statement:
    p = Parser(tokenize(sql))
    stmt = p.statement()
    p.expect_kinds("eof", ";")
    return stmt


def parse_query(sql: str) -> ast.Query:
    stmt = parse_statement(sql)
    if not isinstance(stmt, ast.Query):
        raise ParseError("expected a query")
    return stmt


class Parser:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.i = 0

    # --- token helpers ---
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def advance(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.kind == "kw" and t.lower in kws

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.text in ops

    def at_soft(self, *kws: str, ahead: int = 0) -> bool:
        """Non-reserved (soft) keyword test: matches ident or kw tokens —
        OVER/PARTITION/ROWS/... stay usable as identifiers elsewhere
        (reference: SqlBase.g4 nonReserved rule)."""
        t = self.peek(ahead)
        return t.kind in ("kw", "ident") and t.lower in kws

    def accept_soft(self, *kws: str) -> bool:
        if self.at_soft(*kws):
            self.advance()
            return True
        return False

    def expect_soft(self, kw: str) -> Token:
        if not self.at_soft(kw):
            t = self.peek()
            raise ParseError(f"expected {kw!r}, got {t.text!r} at {t.pos}")
        return self.advance()

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.advance()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.advance()
            return True
        return False

    def expect_kw(self, kw: str) -> Token:
        if not self.at_kw(kw):
            raise ParseError(f"expected {kw.upper()} but got {self.peek().text!r} at {self.peek().pos}")
        return self.advance()

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            raise ParseError(f"expected {op!r} but got {self.peek().text!r} at {self.peek().pos}")
        return self.advance()

    def expect_kinds(self, *ok) -> None:
        t = self.peek()
        if t.kind == "eof" and "eof" in ok:
            return
        if t.kind == "op" and t.text in ok:
            self.advance()
            if self.peek().kind != "eof":
                raise ParseError(f"trailing input at {self.peek().pos}")
            return
        raise ParseError(f"unexpected input {t.text!r} at {t.pos}")

    def identifier(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            return self.advance().text
        # contextual keywords usable as identifiers (e.g. a column named "year")
        if t.kind == "kw" and t.lower in ("year", "month", "day", "date", "first", "last", "tables", "schemas", "columns", "values", "quarter", "hour", "minute", "second", "if", "session", "set", "reset"):
            return self.advance().text
        raise ParseError(f"expected identifier but got {t.text!r} at {t.pos}")

    # --- statements ---
    def statement(self) -> ast.Statement:
        if self.at_soft("start") and self.at_soft("transaction", ahead=1):
            self.advance()
            self.advance()
            return ast.StartTransaction()
        if self.at_soft("begin") and (
            self.peek(1).kind == "eof" or self.peek(1).text == ";"
        ):
            self.advance()
            return ast.StartTransaction()
        if self.at_soft("prepare") and self.peek(1).kind == "ident":
            self.advance()
            name = self.identifier()
            self.expect_kw("from")
            self._param_counter = 0
            return ast.Prepare(name.lower(), self.statement())
        if self.at_soft("execute") and self.peek(1).kind == "ident":
            self.advance()
            name = self.identifier()
            params: List[ast.Expression] = []
            if self.at_soft("using"):
                self.advance()
                params.append(self.expr())
                while self.accept_op(","):
                    params.append(self.expr())
            return ast.ExecutePrepared(name.lower(), tuple(params))
        if self.at_soft("deallocate"):
            self.advance()
            self.accept_soft("prepare")
            return ast.Deallocate(self.identifier().lower())
        if self.at_soft("call") and self.peek(1).kind in ("ident", "kw"):
            # CALL catalog.schema.procedure(args...) (reference:
            # SqlBase.g4 call rule + sql/tree/Call)
            self.advance()
            name = tuple(self.qualified_name())
            self.expect_op("(")
            args: List[ast.Expression] = []
            if not self.at_op(")"):
                args.append(self.expr())
                while self.accept_op(","):
                    args.append(self.expr())
            self.expect_op(")")
            return ast.Call(name, tuple(args))
        if self.at_soft("commit"):
            self.advance()
            return ast.Commit()
        if self.at_soft("rollback"):
            self.advance()
            return ast.Rollback()
        if self.accept_kw("explain"):
            analyze = bool(self.accept_kw("analyze"))
            verbose = analyze and bool(self.accept_soft("verbose"))
            mode, fmt = "distributed", "text"
            if self.accept_op("("):
                while True:
                    opt = self.identifier().lower()
                    if opt == "type":
                        mode = self.identifier().lower()
                    elif opt == "format":
                        fmt = self.identifier().lower()
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            return ast.Explain(self.statement(), analyze=analyze, mode=mode,
                               fmt=fmt, verbose=verbose)
        if self.accept_kw("create"):
            or_replace = False
            if self.accept_kw("or"):
                if not self.accept_soft("replace"):
                    raise ParseError("expected REPLACE after CREATE OR")
                or_replace = True
            if self.accept_soft("function"):
                # CREATE [OR REPLACE] FUNCTION name(p type, ...) RETURNS
                # type RETURN expr (reference: CreateFunctionTask; body is
                # a scalar SQL expression routine)
                name = tuple(self.qualified_name())
                self.expect_op("(")
                params = []
                if not self.at_op(")"):
                    while True:
                        pname = self.identifier()
                        ptype = self.type_name()
                        params.append((pname.lower(), ptype))
                        if not self.accept_op(","):
                            break
                self.expect_op(")")
                if not self.accept_soft("returns"):
                    raise ParseError("expected RETURNS in CREATE FUNCTION")
                rtype = self.type_name()
                if not self.accept_soft("return"):
                    raise ParseError("expected RETURN <expression> body")
                body = self.expr()
                return ast.CreateFunction(
                    name, tuple(params), rtype, body, or_replace)
            if self.accept_soft("materialized"):
                # CREATE [OR REPLACE] MATERIALIZED VIEW [IF NOT EXISTS]
                # name AS query (reference: SqlBase.g4 createMaterializedView)
                self.expect_soft("view")
                not_exists = False
                if self.accept_kw("if"):
                    self.expect_kw("not")
                    self.expect_kw("exists")
                    not_exists = True
                name = tuple(self.qualified_name())
                self.expect_kw("as")
                return ast.CreateMaterializedView(
                    name, self.query(), not_exists, or_replace)
            if or_replace:
                # accepting-and-ignoring OR REPLACE on tables would
                # silently change semantics
                raise ParseError(
                    "expected FUNCTION or MATERIALIZED VIEW after "
                    "CREATE OR REPLACE")
            self.expect_kw("table")
            not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                not_exists = True
            name = tuple(self.qualified_name())
            if self.accept_kw("as"):
                return ast.CreateTableAs(name, self.query(), not_exists)
            self.expect_op("(")
            columns = [self._column_def()]
            while self.accept_op(","):
                columns.append(self._column_def())
            self.expect_op(")")
            return ast.CreateTable(name, tuple(columns), not_exists)
        if self.accept_kw("insert"):
            self.expect_kw("into")
            name = tuple(self.qualified_name())
            columns = ()
            # a '(' here is a column list only if NOT opening a query body
            # (a query must start with SELECT/WITH/VALUES or '('); contextual
            # keywords remain usable as column names, matching CREATE TABLE
            if self.at_op("(") and not (
                self.at_kw("select", "with", "values", ahead=1)
                or (self.peek(1).kind == "op" and self.peek(1).text == "(")
            ):
                self.advance()
                cols = [self.identifier()]
                while self.accept_op(","):
                    cols.append(self.identifier())
                self.expect_op(")")
                columns = tuple(cols)
            return ast.Insert(name, columns, self.query())
        if self.accept_soft("delete"):
            self.expect_kw("from")
            name = tuple(self.qualified_name())
            where = self.expr() if self.accept_kw("where") else None
            return ast.Delete(name, where)
        if self.accept_soft("update"):
            name = tuple(self.qualified_name())
            self.expect_kw("set")
            assigns = []
            while True:
                col = self.identifier()
                self.expect_op("=")
                assigns.append((col, self.expr()))
                if not self.accept_op(","):
                    break
            where = self.expr() if self.accept_kw("where") else None
            return ast.Update(name, tuple(assigns), where)
        if self.at_soft("refresh") and self.at_soft("materialized", ahead=1):
            self.advance()
            self.advance()
            self.expect_soft("view")
            return ast.RefreshMaterializedView(tuple(self.qualified_name()))
        if self.accept_kw("drop"):
            if self.accept_soft("function"):
                if_exists = False
                if self.accept_kw("if"):
                    self.expect_kw("exists")
                    if_exists = True
                return ast.DropFunction(tuple(self.qualified_name()), if_exists)
            if self.accept_soft("materialized"):
                self.expect_soft("view")
                if_exists = False
                if self.accept_kw("if"):
                    self.expect_kw("exists")
                    if_exists = True
                return ast.DropMaterializedView(
                    tuple(self.qualified_name()), if_exists)
            self.expect_kw("table")
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            return ast.DropTable(tuple(self.qualified_name()), if_exists)
        if self.accept_kw("set"):
            self.expect_kw("session")
            name = self.identifier()
            self.expect_op("=")
            return ast.SetSession(name, self._property_value())
        if self.accept_kw("reset"):
            self.expect_kw("session")
            return ast.ResetSession(self.identifier())
        if self.accept_kw("show"):
            if self.accept_kw("session"):
                return ast.ShowSession()
            if self.accept_kw("tables"):
                schema = None
                if self.accept_kw("from", "in"):
                    schema = tuple(self.qualified_name())
                return ast.ShowTables(schema)
            if self.accept_kw("schemas"):
                catalog = None
                if self.accept_kw("from", "in"):
                    catalog = self.identifier()
                return ast.ShowSchemas(catalog)
            if self.accept_kw("columns"):
                self.expect_kw("from")
                return ast.ShowColumns(tuple(self.qualified_name()))
            raise ParseError(f"unsupported SHOW at {self.peek().pos}")
        if self.accept_kw("describe"):
            return ast.ShowColumns(tuple(self.qualified_name()))
        return self.query()

    def _column_def(self):
        """name type — type text is ident plus optional (n[,m]) suffix."""
        name = self.identifier()
        t = self.peek()
        if t.kind not in ("ident", "kw"):
            raise ParseError(f"expected column type at {t.pos}")
        type_text = self.advance().text
        if self.accept_op("("):
            args = [self.advance().text]
            while self.accept_op(","):
                args.append(self.advance().text)
            self.expect_op(")")
            type_text += "(" + ",".join(args) + ")"
        return (name, type_text)

    def _property_value(self):
        """Literal value of SET SESSION: string | number | boolean."""
        t = self.peek()
        if t.kind == "string":
            return self.advance().text
        if t.kind == "number":
            text = self.advance().text
            return float(text) if "." in text or "e" in text.lower() else int(text)
        if t.kind == "kw" and t.lower in ("true", "false"):
            return self.advance().lower == "true"
        raise ParseError(f"expected literal session value at {t.pos}")

    # --- queries ---
    def query(self) -> ast.Query:
        with_queries: List[ast.WithQuery] = []
        if self.accept_kw("with"):
            while True:
                name = self.identifier()
                col_aliases = None
                if self.accept_op("("):
                    cols = [self.identifier()]
                    while self.accept_op(","):
                        cols.append(self.identifier())
                    self.expect_op(")")
                    col_aliases = tuple(cols)
                self.expect_kw("as")
                self.expect_op("(")
                q = self.query()
                self.expect_op(")")
                with_queries.append(ast.WithQuery(name, q, col_aliases))
                if not self.accept_op(","):
                    break
        body = self.query_body()
        order_by: Tuple[ast.SortItem, ...] = ()
        limit = None
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = tuple(self.sort_items())
        if self.accept_kw("limit"):
            t = self.advance()
            if t.kind == "kw" and t.lower == "all":
                limit = None
            else:
                limit = int(t.text)
        return ast.Query(body, tuple(with_queries), order_by, limit)

    def sort_items(self) -> List[ast.SortItem]:
        items = []
        while True:
            e = self.expr()
            asc = True
            if self.accept_kw("asc"):
                asc = True
            elif self.accept_kw("desc"):
                asc = False
            nulls_first = None
            if self.accept_kw("nulls"):
                if self.accept_kw("first"):
                    nulls_first = True
                else:
                    self.expect_kw("last")
                    nulls_first = False
            items.append(ast.SortItem(e, asc, nulls_first))
            if not self.accept_op(","):
                return items

    def query_body(self):
        left = self.query_term()
        while self.at_kw("union", "except"):
            op = self.advance().lower
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self.query_term()
            left = ast.SetOperation(op, all_, left, right)
        return left

    def query_term(self):
        left = self.query_primary()
        while self.at_kw("intersect"):
            self.advance()
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self.query_primary()
            left = ast.SetOperation("intersect", all_, left, right)
        return left

    def query_primary(self):
        if self.accept_op("("):
            q = self.query()
            self.expect_op(")")
            return q
        if self.accept_kw("values"):
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return ast.Values(tuple(rows))
        return self.query_spec()

    def _values_row(self):
        if self.accept_op("("):
            row = [self.expr()]
            while self.accept_op(","):
                row.append(self.expr())
            self.expect_op(")")
            return tuple(row)
        return (self.expr(),)  # single-column row without parens

    def query_spec(self) -> ast.QuerySpec:
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        else:
            self.accept_kw("all")
        items = [self.select_item()]
        while self.accept_op(","):
            items.append(self.select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self.relation()
        where = self.expr() if self.accept_kw("where") else None
        group_by: Tuple[ast.Expression, ...] = ()
        grouping_sets = None
        if self.accept_kw("group"):
            self.expect_kw("by")
            if self.at_soft("grouping") and self.at_soft("sets", ahead=1):
                self.advance()
                self.advance()
                self.expect_op("(")
                grouping_sets = [self._grouping_set()]
                while self.accept_op(","):
                    grouping_sets.append(self._grouping_set())
                self.expect_op(")")
            elif self.at_soft("rollup") and self.peek(1).text == "(":
                self.advance()
                self.advance()
                cols = [self.expr()]
                while self.accept_op(","):
                    cols.append(self.expr())
                self.expect_op(")")
                # ROLLUP(a,b) == GROUPING SETS ((a,b),(a),())
                grouping_sets = [tuple(cols[:k]) for k in range(len(cols), -1, -1)]
            elif self.at_soft("cube") and self.peek(1).text == "(":
                self.advance()
                self.advance()
                cols = [self.expr()]
                while self.accept_op(","):
                    cols.append(self.expr())
                self.expect_op(")")
                import itertools as _it

                grouping_sets = [
                    tuple(c for c, keep in zip(cols, mask) if keep)
                    for mask in _it.product([True, False], repeat=len(cols))
                ]
            else:
                gb = [self.expr()]
                while self.accept_op(","):
                    gb.append(self.expr())
                group_by = tuple(gb)
        having = self.expr() if self.accept_kw("having") else None
        return ast.QuerySpec(
            tuple(items), distinct, from_, where, group_by, having,
            grouping_sets=tuple(grouping_sets) if grouping_sets is not None else None,
        )

    def _grouping_set(self) -> tuple:
        if self.accept_op("("):
            if self.at_op(")"):
                self.advance()
                return ()
            cols = [self.expr()]
            while self.accept_op(","):
                cols.append(self.expr())
            self.expect_op(")")
            return tuple(cols)
        return (self.expr(),)

    def select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.advance()
            return ast.SelectItem(ast.Star())
        # qualified star: ident . *
        if self.peek().kind == "ident" and self.peek(1).kind == "op" and self.peek(1).text == "." \
                and self.peek(2).kind == "op" and self.peek(2).text == "*":
            q = self.advance().text
            self.advance()
            self.advance()
            return ast.SelectItem(ast.Star(qualifier=(q,)))
        e = self.expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.identifier()
        elif self.peek().kind == "ident":
            alias = self.advance().text
        return ast.SelectItem(e, alias)

    # --- relations ---
    def relation(self) -> ast.Relation:
        left = self.joined_relation()
        while self.accept_op(","):
            right = self.joined_relation()
            left = ast.Join("implicit", left, right)
        return left

    def joined_relation(self) -> ast.Relation:
        left = self.table_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.table_primary()
                left = ast.Join("cross", left, right)
                continue
            jt = None
            if self.at_kw("join"):
                jt = "inner"
            elif self.at_kw("inner") and self.at_kw("join", ahead=1):
                self.advance()
                jt = "inner"
            elif self.at_kw("left", "right", "full"):
                jt = self.peek().lower
                self.advance()
                self.accept_kw("outer")
            if jt is None:
                return left
            self.expect_kw("join")
            right = self.table_primary()
            if self.accept_kw("using"):
                self.expect_op("(")
                cols = [self.identifier()]
                while self.accept_op(","):
                    cols.append(self.identifier())
                self.expect_op(")")
                left = ast.Join(jt, left, right, using=tuple(cols))
            else:
                self.expect_kw("on")
                left = ast.Join(jt, left, right, on=self.expr())

    def table_primary(self) -> ast.Relation:
        if self.at_kw("table") and self.peek(1).text == "(":
            # TABLE(fn(arg [, ...])) — polymorphic table function invocation
            # (reference: grammar tableFunctionInvocation +
            # operator/table/). Arguments may be positional or named
            # (name => expr).
            self.advance()
            self.advance()  # (
            fn = self.identifier().lower()
            self.expect_op("(")
            args, named = [], {}
            if not self.at_op(")"):
                while True:
                    if (self.peek().kind == "ident"
                            and self.peek(1).kind == "op"
                            and self.peek(1).text == "=>"):
                        n = self.advance().text.lower()
                        self.advance()  # =>
                        named[n] = self.expr()
                    else:
                        args.append(self.expr())
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            self.expect_op(")")
            rel: ast.Relation = ast.TableFunctionCall(fn, tuple(args), named)
            return self._maybe_aliased(rel)
        if self.at_soft("unnest") and self.peek(1).text == "(":
            self.advance()
            self.advance()  # (
            exprs = [self.expr()]
            while self.accept_op(","):
                exprs.append(self.expr())
            self.expect_op(")")
            ordinality = False
            if self.at_kw("with") and self.at_soft("ordinality", ahead=1):
                self.advance()
                self.advance()
                ordinality = True
            rel: ast.Relation = ast.Unnest(tuple(exprs), ordinality)
            return self._maybe_aliased(rel)
        if self.accept_op("("):
            if self.at_kw("select", "with", "values"):
                q = self.query()
                self.expect_op(")")
                rel: ast.Relation = ast.SubqueryRelation(q)
            else:
                rel = self.relation()
                self.expect_op(")")
        else:
            rel = ast.Table(tuple(self.qualified_name()))
        return self._maybe_aliased(rel)

    def _maybe_aliased(self, rel: ast.Relation) -> ast.Relation:
        if self.at_soft("match_recognize") and self.peek(1).text == "(":
            # MATCH_RECOGNIZE over the bare relation, then maybe aliased
            return self._maybe_aliased(self._match_recognize(rel))
        alias = None
        col_aliases = None
        if self.accept_kw("as"):
            alias = self.identifier()
        elif self.peek().kind == "ident" and not self.at_soft("match_recognize"):
            alias = self.advance().text
        if alias is not None and self.accept_op("("):
            cols = [self.identifier()]
            while self.accept_op(","):
                cols.append(self.identifier())
            self.expect_op(")")
            col_aliases = tuple(cols)
        if alias is not None:
            rel = ast.AliasedRelation(rel, alias, col_aliases)
        if self.at_soft("match_recognize") and self.peek(1).text == "(":
            # aliasedRelation MATCH_RECOGNIZE (...) [AS m] — the reference
            # grammar's patternRecognition position
            return self._maybe_aliased(self._match_recognize(rel))
        return rel

    def _match_recognize(self, input_rel: ast.Relation) -> ast.Relation:
        """MATCH_RECOGNIZE ( [PARTITION BY ...] [ORDER BY ...]
        [MEASURES e AS n, ...] [ONE ROW PER MATCH]
        [AFTER MATCH SKIP (PAST LAST ROW | TO NEXT ROW)]
        PATTERN (A B+ C*) DEFINE A AS pred, ... )"""
        self.advance()  # match_recognize
        self.expect_op("(")
        partition_by: List[ast.Expression] = []
        order_by: List = []
        measures: List = []
        after_match = "past_last"
        if self.accept_soft("partition"):
            self.expect_kw("by")
            partition_by.append(self.expr())
            while self.accept_op(","):
                partition_by.append(self.expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.expr()
                asc = True
                if self.accept_kw("desc"):
                    asc = False
                else:
                    self.accept_kw("asc")
                order_by.append((e, asc))
                if not self.accept_op(","):
                    break
        if self.accept_soft("measures"):
            while True:
                e = self.expr()
                self.expect_kw("as")
                measures.append((e, self.identifier()))
                if not self.accept_op(","):
                    break
        if self.accept_soft("one"):
            if not (self.accept_soft("row") and self.accept_soft("per")
                    and self.accept_soft("match")):
                raise ParseError("expected ONE ROW PER MATCH")
        if self.accept_soft("after"):
            if not (self.accept_soft("match") and self.accept_soft("skip")):
                raise ParseError("expected AFTER MATCH SKIP")
            if self.accept_soft("past"):
                if not (self.accept_soft("last") and self.accept_soft("row")):
                    raise ParseError("expected PAST LAST ROW")
                after_match = "past_last"
            elif self.accept_soft("to"):
                if not (self.accept_soft("next") and self.accept_soft("row")):
                    raise ParseError(
                        "only SKIP PAST LAST ROW / SKIP TO NEXT ROW supported")
                after_match = "next_row"
            else:
                raise ParseError("expected PAST LAST ROW or TO NEXT ROW")
        if not self.accept_soft("pattern"):
            raise ParseError("MATCH_RECOGNIZE requires PATTERN (...)")
        self.expect_op("(")
        pattern: List = []
        while not self.at_op(")"):
            var = self.identifier().lower()
            quant = "1"
            if self.at_op("*", "+", "?"):
                quant = self.advance().text
            pattern.append((var, quant))
        self.expect_op(")")
        if not pattern:
            raise ParseError("empty PATTERN")
        if not self.accept_soft("define"):
            raise ParseError("MATCH_RECOGNIZE requires DEFINE")
        defines: List = []
        while True:
            var = self.identifier().lower()
            self.expect_kw("as")
            defines.append((var, self.expr()))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return ast.MatchRecognize(
            input_rel, tuple(partition_by), tuple(order_by), tuple(measures),
            after_match, tuple(pattern), tuple(defines))

    def qualified_name(self) -> List[str]:
        parts = [self.identifier()]
        while self.at_op(".") and self.peek(1).kind in ("ident", "kw"):
            self.advance()
            parts.append(self.identifier())
        return parts

    # --- expressions ---
    def expr(self) -> ast.Expression:
        return self.or_expr()

    def or_expr(self) -> ast.Expression:
        left = self.and_expr()
        while self.accept_kw("or"):
            left = ast.LogicalBinary("or", left, self.and_expr())
        return left

    def and_expr(self) -> ast.Expression:
        left = self.not_expr()
        while self.accept_kw("and"):
            left = ast.LogicalBinary("and", left, self.not_expr())
        return left

    def not_expr(self) -> ast.Expression:
        if self.accept_kw("not"):
            return ast.Not(self.not_expr())
        return self.predicate()

    def predicate(self) -> ast.Expression:
        left = self.additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.advance().text
                if op == "!=":
                    op = "<>"
                right = self.additive()
                left = ast.Comparison(op, left, right)
                continue
            negated = False
            if self.at_kw("not") and self.at_kw("between", "in", "like", ahead=1):
                self.advance()
                negated = True
            if self.accept_kw("between"):
                lo = self.additive()
                self.expect_kw("and")
                hi = self.additive()
                left = ast.Between(left, lo, hi, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.query()
                    self.expect_op(")")
                    left = ast.InSubquery(left, q, negated)
                else:
                    items = [self.expr()]
                    while self.accept_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = ast.InList(left, tuple(items), negated)
                continue
            if self.accept_kw("like"):
                pattern = self.additive()
                escape = None
                if self.accept_kw("escape"):
                    escape = self.additive()
                left = ast.Like(left, pattern, escape, negated)
                continue
            if self.accept_kw("is"):
                neg = bool(self.accept_kw("not"))
                self.expect_kw("null")
                left = ast.IsNull(left, negated=neg)
                continue
            if negated:
                raise ParseError(f"dangling NOT at {self.peek().pos}")
            return left

    def additive(self) -> ast.Expression:
        left = self.multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.advance().text
                left = ast.Arithmetic(op, left, self.multiplicative())
            elif self.at_op("||"):
                self.advance()
                left = ast.FunctionCall("concat", (left, self.multiplicative()))
            else:
                return left

    def multiplicative(self) -> ast.Expression:
        left = self.unary()
        while self.at_op("*", "/", "%"):
            op = self.advance().text
            left = ast.Arithmetic(op, left, self.unary())
        return left

    def unary(self) -> ast.Expression:
        if self.accept_op("-"):
            return ast.Negative(self.unary())
        self.accept_op("+")
        return self.primary()

    def primary(self) -> ast.Expression:
        e = self._primary_base()
        while True:
            if self.at_op("["):
                self.advance()
                idx = self.expr()
                self.expect_op("]")
                e = ast.Subscript(e, idx)
                continue
            if (self.at_kw("at") or self.at_soft("at")) \
                    and self.at_soft("time", ahead=1) \
                    and self.at_soft("zone", ahead=2):
                self.advance()
                self.advance()
                self.advance()
                z = self.advance()
                if z.kind != "string":
                    raise ParseError(f"expected time zone string at {z.pos}")
                e = ast.AtTimeZone(e, z.text)
                continue
            return e

    def _primary_base(self) -> ast.Expression:
        t = self.peek()
        if self.at_op("?"):
            self.advance()
            idx = getattr(self, "_param_counter", 0)
            self._param_counter = idx + 1
            return ast.Parameter(idx)
        if self.at_soft("array") and self.peek(1).text == "[":
            self.advance()
            self.advance()  # [
            items: List[ast.Expression] = []
            if not self.at_op("]"):
                items.append(self.expr())
                while self.accept_op(","):
                    items.append(self.expr())
            self.expect_op("]")
            return ast.ArrayConstructor(tuple(items))
        if t.kind == "number":
            self.advance()
            return ast.Literal("number", t.text)
        if t.kind == "string":
            self.advance()
            return ast.Literal("string", t.text)
        if self.at_kw("null"):
            self.advance()
            return ast.Literal("null", None)
        if self.at_kw("true", "false"):
            self.advance()
            return ast.Literal("boolean", t.lower == "true")
        if self.at_kw("date") and self.peek(1).kind == "string":
            self.advance()
            return ast.Literal("date", self.advance().text)
        if self.at_kw("timestamp") and self.peek(1).kind == "string":
            self.advance()
            return ast.Literal("timestamp", self.advance().text)
        if t.kind == "ident" and t.lower == "x" and self.peek(1).kind == "string":
            self.advance()
            return ast.Literal("varbinary", self.advance().text)
        if self.at_kw("interval"):
            self.advance()
            sign = 1
            if self.accept_op("-"):
                sign = -1
            else:
                self.accept_op("+")
            v = self.advance()
            if v.kind != "string":
                raise ParseError(f"expected interval string at {v.pos}")
            unit_tok = self.advance()
            unit = unit_tok.lower
            if unit not in ("year", "month", "day", "hour", "minute", "second"):
                raise ParseError(f"bad interval unit {unit_tok.text!r}")
            return ast.IntervalLiteral(int(v.text), unit, sign)
        if self.at_kw("case"):
            return self.case_expr()
        if self.at_kw("cast"):
            self.advance()
            self.expect_op("(")
            value = self.expr()
            self.expect_kw("as")
            type_name = self.type_name()
            self.expect_op(")")
            return ast.Cast(value, type_name)
        if self.at_kw("extract"):
            self.advance()
            self.expect_op("(")
            field = self.advance().lower
            self.expect_kw("from")
            value = self.expr()
            self.expect_op(")")
            return ast.Extract(field, value)
        if self.at_kw("substring"):
            self.advance()
            self.expect_op("(")
            value = self.expr()
            if self.accept_kw("from"):
                start = self.expr()
                if self.accept_kw("for"):
                    length = self.expr()
                    self.expect_op(")")
                    return ast.FunctionCall("substring", (value, start, length))
                self.expect_op(")")
                return ast.FunctionCall("substring", (value, start))
            args = [value]
            while self.accept_op(","):
                args.append(self.expr())
            self.expect_op(")")
            return ast.FunctionCall("substring", tuple(args))
        if self.at_kw("exists"):
            self.advance()
            self.expect_op("(")
            q = self.query()
            self.expect_op(")")
            return ast.Exists(q)
        if self.accept_op("("):
            if self.at_kw("select", "with"):
                q = self.query()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "ident" or (t.kind == "kw" and t.lower in (
            "year", "month", "day", "date", "first", "last", "quarter", "values",
            "if", "session", "set", "reset",
        )):
            # function call or (qualified) identifier
            if self.peek(1).kind == "op" and self.peek(1).text == "(":
                name = self.advance().text
                self.advance()  # (
                if self.accept_op("*"):
                    self.expect_op(")")
                    if self.at_soft("filter") and self.peek(1).text == "(":
                        self.advance()
                        self.advance()
                        self.expect_kw("where")
                        cond = self.expr()
                        self.expect_op(")")
                        return ast.FunctionCall("count_if", (cond,))
                    if self.at_soft("over") and self.peek(1).text == "(":
                        return self.window_suffix(name.lower(), (), is_star=True)
                    return ast.FunctionCall(name.lower(), (), is_star=True)
                distinct = bool(self.accept_kw("distinct"))
                self.accept_kw("all")
                args: List[ast.Expression] = []
                if not self.at_op(")"):
                    args.append(self._arg_or_lambda())
                    while self.accept_op(","):
                        args.append(self._arg_or_lambda())
                self.expect_op(")")
                # FILTER (WHERE cond) — aggregate filter clause; rewritten
                # at parse time: agg(x) FILTER (WHERE c) == agg(CASE WHEN c
                # THEN x END), count(*) == count_if(c) (reference:
                # AggregationNode.Aggregation's filter symbol; the rewrite
                # is exact because aggregates ignore NULL inputs)
                if self.at_soft("filter") and self.peek(1).text == "(":
                    self.advance()
                    self.advance()
                    self.expect_kw("where")
                    cond = self.expr()
                    self.expect_op(")")
                    fn = name.lower()
                    if fn == "count" and not args:
                        return ast.FunctionCall("count_if", (cond,))
                    if distinct or not args:
                        raise ParseError(
                            "FILTER is supported on single-argument aggregates")
                    filtered = ast.SearchedCase(((cond, args[0]),), None)
                    return ast.FunctionCall(fn, (filtered,) + tuple(args[1:]))
                if self.at_soft("over") and self.peek(1).text == "(":
                    if distinct:
                        raise ParseError("DISTINCT window aggregates not supported")
                    return self.window_suffix(name.lower(), tuple(args))
                return ast.FunctionCall(name.lower(), tuple(args), distinct=distinct)
            parts = self.qualified_name()
            return ast.Identifier(tuple(parts))
        raise ParseError(f"unexpected token {t.text!r} at {t.pos}")

    def _arg_or_lambda(self) -> ast.Expression:
        """A function argument: ``x -> expr`` / ``(x, y) -> expr`` lambdas
        or a plain expression."""
        if self.peek().kind == "ident" and self.peek(1).text == "->":
            p = self.identifier()
            self.advance()  # ->
            return ast.Lambda((p,), self.expr())
        if (self.at_op("(") and self.peek(1).kind == "ident"
                and self.peek(2).text in (",", ")")):
            # lookahead for "(a, b, ...) ->"
            save = self.i
            try:
                self.advance()
                ps = [self.identifier()]
                while self.accept_op(","):
                    ps.append(self.identifier())
                if self.at_op(")") and self.peek(1).text == "->":
                    self.advance()
                    self.advance()
                    return ast.Lambda(tuple(ps), self.expr())
            except ParseError:
                pass
            self.i = save
        return self.expr()

    def window_suffix(self, name, args, is_star=False) -> ast.WindowFunction:
        """OVER ( [PARTITION BY ...] [ORDER BY ...] [frame] )"""
        self.expect_soft("over")
        self.expect_op("(")
        partition_by: List[ast.Expression] = []
        order_by: List[ast.SortItem] = []
        frame = None
        if self.accept_soft("partition"):
            self.expect_kw("by")
            partition_by.append(self.expr())
            while self.accept_op(","):
                partition_by.append(self.expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = self.sort_items()
        if self.at_soft("rows", "range", "groups"):
            mode = self.advance().lower
            if self.accept_kw("between"):
                lo = self._frame_bound()
                self.expect_kw("and")
                hi = self._frame_bound()
            else:
                lo = self._frame_bound()
                hi = "current row"
            frame = (mode, lo, hi)
        self.expect_op(")")
        return ast.WindowFunction(
            name, args, tuple(partition_by), tuple(order_by), is_star, frame
        )

    def _frame_bound(self) -> str:
        if self.accept_soft("unbounded"):
            if self.accept_soft("preceding"):
                return "unbounded preceding"
            self.expect_soft("following")
            return "unbounded following"
        if self.accept_soft("current"):
            self.expect_soft("row")
            return "current row"
        t = self.advance()  # numeric offset
        if self.accept_soft("preceding"):
            return f"{t.text} preceding"
        self.expect_soft("following")
        return f"{t.text} following"

    def case_expr(self) -> ast.Expression:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.expr()
            self.expect_kw("then")
            val = self.expr()
            whens.append((cond, val))
        default = None
        if self.accept_kw("else"):
            default = self.expr()
        self.expect_kw("end")
        if operand is not None:
            return ast.SimpleCase(operand, tuple(whens), default)
        return ast.SearchedCase(tuple(whens), default)

    def type_name(self) -> str:
        base = self.advance().text
        if base.lower() == "row" and self.at_op("("):
            # row fields: [name] type, ...
            self.advance()
            fields = [self._row_field()]
            while self.accept_op(","):
                fields.append(self._row_field())
            self.expect_op(")")
            return f"{base}({', '.join(fields)})"
        if base.lower() in ("array", "map") and self.at_op("("):
            self.advance()
            args = [self.type_name()]
            while self.accept_op(","):
                args.append(self.type_name())
            self.expect_op(")")
            return f"{base}({','.join(args)})"
        parts = [base]
        if self.accept_op("("):
            parts.append("(")
            parts.append(self.advance().text)
            while self.accept_op(","):
                parts.append(",")
                parts.append(self.advance().text)
            self.expect_op(")")
            parts.append(")")
        if base.lower() == "timestamp" and self.at_kw("with"):
            # timestamp [(p)] WITH TIME ZONE
            self.advance()
            if not (self.accept_soft("time") and self.accept_soft("zone")):
                raise ParseError("expected TIME ZONE after WITH")
            parts.append(" with time zone")
        return "".join(parts)

    def _row_field(self) -> str:
        """One ROW type field: ``name type`` or bare ``type``."""
        nxt = self.peek(1)
        if self.peek().kind == "ident" and (
                nxt.kind in ("ident", "kw")
                or (nxt.kind == "op" and nxt.text not in (",", ")", "("))):
            name = self.advance().text
            return f"{name} {self.type_name()}"
        return self.type_name()
