"""Logical plan nodes.

Reference: ``core/trino-main/.../sql/planner/plan/`` (46 concrete node types).
Round-1 subset (~15) covering the TPC-H surface; grows with the engine.
Plans are *channel-positional*: every node exposes ``output_types`` (and
debug ``output_names``); expressions inside a node are IR over the node's
input channels (left channels then right channels for joins, as in the
reference's symbol->channel layout done by LocalExecutionPlanner).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.sql import ir

_next_plan_id = itertools.count()


@dataclasses.dataclass
class PlanNode:
    id: int = dataclasses.field(default_factory=lambda: next(_next_plan_id), init=False)

    @property
    def sources(self) -> Sequence["PlanNode"]:
        return ()

    @property
    def output_types(self) -> List[T.Type]:
        raise NotImplementedError

    @property
    def output_names(self) -> List[str]:
        raise NotImplementedError


@dataclasses.dataclass
class TableScanNode(PlanNode):
    """Reference: plan/TableScanNode.java — here carries the connector handle
    directly (catalog, schema, table) plus the projected column subset."""

    catalog: str
    schema: str
    table: str
    column_names: List[str]
    column_types: List[T.Type]
    table_handle: object = None  # connector-provided
    # Static pushdown (reference: applyFilter/TupleDomain): advisory
    # constraint derived from filter conjuncts; the filter is kept.
    constraint: object = None  # Optional[TupleDomain]
    # Runtime narrowing (reference: DynamicFilterService/DynamicFilter):
    # [(join_node_id, key_index, column_name)] — at execution the scan
    # waits for the named join's build-side key domain.
    dynamic_filters: List = None
    # ACTUAL rows staged for this scan (set by the two-phase compiled path
    # after phase-1 narrowing; reference: AdaptivePlanner's runtime stats) —
    # when present, cardinality estimation starts from truth, not stats.
    runtime_rows: Optional[int] = None
    # set by the materialized-view substitution pass (trino_tpu/matview/):
    # this scan reads the named MV's storage table in place of a matched
    # plan subtree — EXPLAIN renders it as ``[mv: <name>]``
    mv_name: Optional[str] = None

    @property
    def output_types(self):
        return list(self.column_types)

    @property
    def output_names(self):
        return list(self.column_names)


@dataclasses.dataclass
class FilterNode(PlanNode):
    source: PlanNode = None
    predicate: ir.Expr = None

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return self.source.output_types

    @property
    def output_names(self):
        return self.source.output_names


@dataclasses.dataclass
class CompactNode(PlanNode):
    """Squeeze live rows to the front of a smaller static-capacity page.

    TPU-first: filters keep selection masks instead of compacting (static
    shapes), so a selective pipeline drags dead slots through every
    downstream sort/join. When the optimizer's cardinality estimate says
    live rows are far below the slot count, this node pays one stable
    payload-carrying sort (live rows first, original order kept) to shrink
    the working set. Capacity comes from stats (hint key ``cmp:<id>``);
    a too-small estimate raises CAPACITY_EXCEEDED and the bucketed
    recompile loop doubles it. Reference role: the implicit compaction the
    reference gets for free from page-at-a-time operators that drop
    filtered rows (PageProcessor emitting compacted pages)."""

    source: PlanNode = None
    estimated_rows: int = 0  # live-row estimate the capacity hint derives from

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return self.source.output_types

    @property
    def output_names(self):
        return self.source.output_names


@dataclasses.dataclass
class ProjectNode(PlanNode):
    source: PlanNode = None
    expressions: List[ir.Expr] = None
    names: List[str] = None

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return [e.type for e in self.expressions]

    @property
    def output_names(self):
        return list(self.names)

    @staticmethod
    def identity_prefix(source: PlanNode, extra: List[ir.Expr], extra_names: List[str]):
        exprs = [
            ir.ColumnRef(t, i, n)
            for i, (t, n) in enumerate(zip(source.output_types, source.output_names))
        ]
        return ProjectNode(source, exprs + extra, source.output_names + extra_names)


@dataclasses.dataclass
class UnnestNode(PlanNode):
    """Expand array/map-valued expressions into rows, replicating the source
    columns (lateral CROSS JOIN UNNEST semantics; ordinality optional).

    Reference: ``operator/unnest/UnnestOperator.java:41`` — there a
    position-at-a-time block traversal; here one static-shape expansion:
    output capacity = total flat element count, per-output-row parent ids
    come from a searchsorted over the offsets, replicated columns are row
    gathers, unnested columns are the flat children themselves (ops/
    array_ops.py). Rows beyond a row's own length are sel-masked dead."""

    source: PlanNode = None
    unnest_exprs: List[ir.Expr] = None  # array/map-typed, over source channels
    ordinality: bool = False
    # source channels replicated into the output (pruning drops unused ones —
    # critically the unnested array column itself, whose device row-gather
    # would need data-dependent reshaping)
    replicate_channels: List[int] = None

    def __post_init__(self):
        if self.replicate_channels is None:
            self.replicate_channels = list(range(len(self.source.output_types)))

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        out = [self.source.output_types[c] for c in self.replicate_channels]
        for e in self.unnest_exprs:
            t = e.type
            if isinstance(t, T.MapType):
                out.extend([t.key, t.value])
            else:
                out.append(t.element)
        if self.ordinality:
            out.append(T.BIGINT)
        return out

    @property
    def output_names(self):
        out = [self.source.output_names[c] for c in self.replicate_channels]
        for i, e in enumerate(self.unnest_exprs):
            if isinstance(e.type, T.MapType):
                out.extend([f"key_{i}" if i else "key", f"value_{i}" if i else "value"])
            else:
                out.append(f"col_{i}" if i else "col")
        if self.ordinality:
            out.append("ordinality")
        return out


@dataclasses.dataclass(frozen=True)
class AggregateCall:
    function: str  # count | sum | avg | min | max | stddev* | var* | approx_* | bool_* | *_by | corr | ...
    arg_channel: Optional[int]  # None for count(*)
    output_type: T.Type
    distinct: bool = False
    param: Optional[float] = None  # approx_percentile's percentile
    # second argument channel (min_by/max_by key, corr/covar/regr y, map_agg value)
    arg2_channel: Optional[int] = None
    # count(*) counts rows; count(x) counts non-null x

    def __post_init__(self):
        # approx_distinct counts distinct non-null values: it shares the
        # cannot-split-partial/final property of DISTINCT aggregates, so the
        # flag is forced here (every construction site included)
        if self.function == "approx_distinct" and not self.distinct:
            object.__setattr__(self, "distinct", True)


@dataclasses.dataclass
class AggregationNode(PlanNode):
    """Reference: plan/AggregationNode.java + HashAggregationOperator.
    step: 'single' | 'partial' | 'final' (partial/final appear after the
    fragmenter splits the aggregation across an exchange)."""

    source: PlanNode = None
    group_channels: List[int] = None
    aggregates: List[AggregateCall] = None
    step: str = "single"
    names: List[str] = None

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        src = self.source.output_types
        types = [src[c] for c in self.group_channels]
        if self.step == "partial":
            types += [t for agg in self.aggregates for t in _acc_types(agg, src)]
        else:
            types += [a.output_type for a in self.aggregates]
        return types

    @property
    def output_names(self):
        if self.step != "partial":
            return list(self.names)
        # partial output carries one column PER ACCUMULATOR STATE (an avg
        # ships (sum, count)), so names expand to match — the sanity
        # checker's arity invariant (sql/planner/sanity.py) holds on every
        # node, partials included
        k = len(self.group_channels)
        out = list(self.names[:k])
        for name, agg in zip(self.names[k:], self.aggregates):
            n_states = _acc_state_count(agg)
            if n_states == 1:
                out.append(name)
            else:
                out.extend(f"{name}$s{i}" for i in range(n_states))
        return out


def _acc_types(agg: AggregateCall, src_types) -> List[T.Type]:
    """Accumulator (partial-state) types for an aggregate (reference:
    AccumulatorCompiler intermediate state). Length must equal
    ``_acc_state_count(agg)`` — the executor's final step uses that to
    slice gathered state columns."""
    if agg.function in ("count", "count_star"):
        out = [T.BIGINT]
    elif agg.function == "avg":
        # running (sum, count)
        base = src_types[agg.arg_channel]
        out = [T.DOUBLE if base.is_floating else base, T.BIGINT]
    elif agg.function in _VAR_FAMILY:
        # running (count, mean, m2) — the reference's VarianceState layout;
        # merged with the exact multi-way Chan decomposition
        # (ops/aggregate.py combine_var_states)
        out = [T.BIGINT, T.DOUBLE, T.DOUBLE]
    elif agg.function == "sum":
        out = [agg.output_type]
        if _is_long_decimal(agg.output_type):
            # two-limb running sum: (lo bit pattern, hi limb) — exact for
            # the full p38 range across the partial/final split
            # (ops/aggregate.py agg_sum_128; reference: Int128State)
            out.append(T.BIGINT)
    elif agg.function in ("min", "max"):
        out = [src_types[agg.arg_channel]]
    elif agg.function in ("bool_and", "bool_or", "every"):
        out = [T.BOOLEAN]
    elif agg.function == "count_if":
        out = [T.BIGINT]
    elif agg.function == "approx_percentile":
        # mergeable quantile summary (ops/hll.py QUANTILE_SAMPLES values at
        # evenly spaced local ranks) + the live count
        from trino_tpu.ops.hll import QUANTILE_SAMPLES

        out = [src_types[agg.arg_channel]] * QUANTILE_SAMPLES + [T.BIGINT]
    else:
        raise NotImplementedError(agg.function)
    assert len(out) == _acc_state_count(agg)
    return out


_VAR_FAMILY = {"stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"}


# Aggregates whose partial state is the raw rows themselves (variable
# length or pair-valued) — the planner routes them through a gather
# exchange instead of a partial/final split.
_UNSPLITTABLE = {
    "array_agg", "histogram", "map_agg", "min_by", "max_by",
    "corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept",
    "arbitrary", "any_value", "geometric_mean", "checksum",
}


def can_split_aggs(aggregates) -> bool:
    """True when every aggregate has a mergeable partial/final state.
    DISTINCT aggregates must see all raw rows; approx_percentile ships a
    mergeable quantile summary (ops/hll.py percentile_states)."""
    return not any(
        a.distinct or a.function in _UNSPLITTABLE for a in aggregates
    )


def _acc_state_count(agg: AggregateCall) -> int:
    """Number of accumulator state columns an aggregate ships partial->final."""
    if agg.function == "approx_percentile":
        from trino_tpu.ops.hll import QUANTILE_SAMPLES

        return QUANTILE_SAMPLES + 1
    if agg.function in _VAR_FAMILY:
        return 3
    if agg.function == "sum" and _is_long_decimal(agg.output_type):
        return 2
    return 2 if agg.function == "avg" else 1


def _is_long_decimal(t: T.Type) -> bool:
    return isinstance(t, T.DecimalType) and t.precision > 18


_TWO_ARG_AGGS = {
    "min_by", "max_by", "map_agg",
    "corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept",
}


@dataclasses.dataclass
class JoinNode(PlanNode):
    """Reference: plan/JoinNode.java. Output = left channels ++ right channels
    (probe then build). ``distribution``: None until the optimizer picks
    partitioned vs broadcast (AddExchanges analog)."""

    join_type: str = "inner"  # inner | left | semi | anti (right/full: not yet supported)
    left: PlanNode = None
    right: PlanNode = None
    left_keys: List[int] = None
    right_keys: List[int] = None
    filter: Optional[ir.Expr] = None  # over concatenated channels
    distribution: Optional[str] = None  # 'partitioned' | 'broadcast'
    right_unique: bool = False  # build side keys unique (N:1 lookup join)
    singleton: bool = False  # right side is a scalar subquery (exactly 1 row)
    # key indices whose build-side domain some probe scan consumes as a
    # dynamic filter (set by optimizer.plan_dynamic_filters) — the executor
    # extracts domains only for these
    dyn_filter_keys: List[int] = None
    # phase-1 host evaluation produced an EXACT in-set domain that probe
    # scans applied: every surviving probe row has >= 1 build match, so
    # cardinality estimation skips the key-match discount
    df_exact: bool = False

    @property
    def sources(self):
        return (self.left, self.right)

    @property
    def output_types(self):
        if self.join_type in ("semi", "anti"):
            return self.left.output_types
        return self.left.output_types + self.right.output_types

    @property
    def output_names(self):
        if self.join_type in ("semi", "anti"):
            return self.left.output_names
        return self.left.output_names + self.right.output_names


@dataclasses.dataclass(frozen=True)
class WindowCall:
    """One window function evaluation (reference: WindowNode.Function)."""

    function: str  # rank | dense_rank | row_number | sum | count | count_star
    #              | avg | min | max | lag | lead | first_value | last_value
    arg_channel: Optional[int]
    output_type: T.Type = None
    offset: int = 1  # lag/lead distance (static)
    # 'running': RANGE UNBOUNDED PRECEDING..CURRENT ROW (peers included) —
    # the default frame with ORDER BY; 'rows_running': ROWS ..CURRENT ROW;
    # 'partition': whole partition (default without ORDER BY / UNBOUNDED
    # PRECEDING..UNBOUNDED FOLLOWING)
    frame: str = "running"
    # ROWS-frame numeric bounds relative to the current row (frame ==
    # 'rows_offset'): lo = -n for "n PRECEDING", hi = +m for "m FOLLOWING",
    # 0 = CURRENT ROW, None = unbounded on that side
    frame_lo: Optional[int] = None
    frame_hi: Optional[int] = None


@dataclasses.dataclass
class WindowNode(PlanNode):
    """Window functions over sorted partitions; output = source channels ++
    one channel per call. Reference: plan/WindowNode.java +
    operator/WindowOperator.java:69 (redesigned: one fused sort + streaming
    prefix kernels instead of per-partition iteration, ops/window.py)."""

    source: PlanNode = None
    partition_channels: List[int] = None
    order_channels: List[Tuple[int, bool, Optional[bool]]] = None  # (ch, asc, nulls_first)
    calls: List[WindowCall] = None
    names: List[str] = None  # names for the appended channels

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return self.source.output_types + [c.output_type for c in self.calls]

    @property
    def output_names(self):
        return self.source.output_names + list(self.names)


@dataclasses.dataclass
class MatchRecognizeNode(PlanNode):
    """Row pattern matching, ONE ROW PER MATCH (reference:
    plan/PatternRecognitionNode). DEFINE/MEASURES keep their analyzed-AST
    form: the matcher is host-tier (exec/match_recognize.py) — its
    backtracking inner loop is the one operator family that does not
    vectorize onto the device."""

    source: PlanNode = None
    partition_channels: List[int] = None
    sort_channels: List[Tuple[int, bool, Optional[bool]]] = None
    pattern: tuple = ()  # ((variable, quantifier), ...)
    defines: tuple = ()  # ((variable, ast expr), ...)
    measures: tuple = ()  # ((ast expr, name), ...)
    measure_types: List[T.Type] = None
    after_match: str = "past_last"
    # the SCOPE names of the input (aliases applied): DEFINE/MEASURES
    # resolve by these, not by the physical child's debug names
    input_names: List[str] = None

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        src = self.source.output_types
        return [src[c] for c in self.partition_channels] + list(self.measure_types)

    @property
    def output_names(self):
        names = self.input_names or self.source.output_names
        return [names[c] for c in self.partition_channels] + [
            n for _, n in self.measures]


@dataclasses.dataclass
class SortNode(PlanNode):
    source: PlanNode = None
    sort_channels: List[Tuple[int, bool, Optional[bool]]] = None  # (ch, asc, nulls_first)

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return self.source.output_types

    @property
    def output_names(self):
        return self.source.output_names


@dataclasses.dataclass
class TopNNode(PlanNode):
    source: PlanNode = None
    count: int = 0
    sort_channels: List[Tuple[int, bool, Optional[bool]]] = None
    step: str = "single"  # single | partial | final

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return self.source.output_types

    @property
    def output_names(self):
        return self.source.output_names


@dataclasses.dataclass
class LimitNode(PlanNode):
    source: PlanNode = None
    count: int = 0
    step: str = "single"

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return self.source.output_types

    @property
    def output_names(self):
        return self.source.output_names


@dataclasses.dataclass
class OutputNode(PlanNode):
    source: PlanNode = None
    column_names: List[str] = None

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return self.source.output_types

    @property
    def output_names(self):
        return list(self.column_names)


@dataclasses.dataclass
class ValuesNode(PlanNode):
    types: List[T.Type] = None
    names: List[str] = None
    rows: List[tuple] = None

    @property
    def output_types(self):
        return list(self.types)

    @property
    def output_names(self):
        return list(self.names)


@dataclasses.dataclass
class UnionNode(PlanNode):
    """UNION ALL: positional concatenation of same-width sources
    (reference: plan/UnionNode.java; distinct UNION plans as UnionNode +
    grouping AggregationNode, the reference's SetOperationNodeTranslator)."""

    sources_: List[PlanNode] = None
    names: List[str] = None

    @property
    def sources(self):
        return tuple(self.sources_)

    @property
    def output_types(self):
        return self.sources_[0].output_types

    @property
    def output_names(self):
        return list(self.names)


@dataclasses.dataclass
class SetOpNode(PlanNode):
    """INTERSECT/EXCEPT (DISTINCT): whole-row set membership with SQL
    set-operation NULL semantics (NULLs compare equal — the grouping
    equality, not the join equality; reference:
    SetOperationNodeTranslator + distinct aggregations)."""

    op: str = "intersect"  # intersect | except
    left: PlanNode = None
    right: PlanNode = None

    @property
    def sources(self):
        return (self.left, self.right)

    @property
    def output_types(self):
        return self.left.output_types

    @property
    def output_names(self):
        return self.left.output_names


@dataclasses.dataclass
class ExchangeNode(PlanNode):
    """Reference: plan/ExchangeNode.java — the fragmenter cuts plans here
    (PlanFragmenter.java:94). partitioning: 'single' (gather),
    'hash' (repartition on key channels), 'broadcast' (replicate)."""

    source: PlanNode = None
    partitioning: str = "single"
    partition_channels: List[int] = None
    scope: str = "remote"  # remote | local

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_types(self):
        return self.source.output_types

    @property
    def output_names(self):
        return self.source.output_names


def walk_plan(node: PlanNode):
    yield node
    for s in node.sources:
        yield from walk_plan(s)


def uses_expansion_kernel(n: JoinNode) -> bool:
    """True when the executor dispatches this join to the two-pass expansion
    kernel (expand_join / semi_join_filtered), whose static output capacity
    comes from stats (sql/planner/stats.py) with overflow-triggered
    recompiles. Must mirror Executor._exec_JoinNode's dispatch."""
    if n.join_type in ("semi", "anti"):
        return n.filter is not None
    return not n.right_unique and not n.singleton


def kernel_annotations(rows) -> dict:
    """Per-plan-node launch counts + dispatch overhead from kernel-ledger
    rows (obs/devprofiler.py wire shape) — the EXPLAIN ANALYZE VERBOSE
    ``launches=/dispatch_overhead=`` annotation source."""
    out: dict = {}
    for r in rows or ():
        nid = str(r.get("planNodeId", ""))
        agg = out.setdefault(nid, {"launches": 0, "overheadS": 0.0})
        agg["launches"] += int(r.get("launches", 0))
        agg["overheadS"] += max(
            0.0, float(r.get("wallS", 0.0)) - float(r.get("deviceS", 0.0)))
    return out


def format_plan(node: PlanNode, indent: int = 0, executor=None,
                stats=None, verbose: bool = False, kernels=None) -> str:
    """Text plan printer (reference: sql/planner/planprinter/PlanPrinter.java).
    With ``executor`` (a finished eager Executor), renders EXPLAIN ANALYZE:
    per-operator wall time / output rows / scan+spill detail from its stats
    (the role of PlanPrinter's stats injection from OperatorStats). With
    ``stats`` (node id → OperatorStats, e.g. the coordinator's rollup of
    worker-reported task stats), the same annotations render WITHOUT a
    local executor — the distributed EXPLAIN ANALYZE path. ``verbose``
    additionally prints bytes / peak reservation / split counts and the
    kernel ledger's per-node ``launches=/dispatch_overhead=`` line
    (``kernels``: plan-node id → annotation, see kernel_annotations;
    derived from the executor's own kernel stats when not passed)."""
    if verbose and kernels is None and executor is not None:
        kernels = kernel_annotations(
            getattr(executor, "kernel_stats", {}).values())
    pad = "  " * indent
    label = type(node).__name__.replace("Node", "")
    detail = ""
    if isinstance(node, TableScanNode):
        detail = f" {node.catalog}.{node.schema}.{node.table} -> {node.column_names}"
        if node.mv_name is not None:
            detail += f" [mv: {node.mv_name}]"
        if node.constraint is not None:
            detail += f" constraint={node.constraint!r}"
        if node.table_handle is not None:
            detail += f" pushdown={node.table_handle!r}"
        if node.dynamic_filters:
            detail += f" dynamic_filters={[c for _, _, c in node.dynamic_filters]}"
    elif isinstance(node, FilterNode):
        detail = f" {node.predicate!r}"
    elif isinstance(node, ProjectNode):
        detail = f" {[f'{n}:={e!r}' for n, e in zip(node.names, node.expressions)]}"
    elif isinstance(node, AggregationNode):
        detail = f" [{node.step}] keys={node.group_channels} aggs={[a.function for a in self_aggs(node)]}"
    elif isinstance(node, JoinNode):
        detail = (
            f" [{node.join_type}{'/' + node.distribution if node.distribution else ''}]"
            f" L{node.left_keys} = R{node.right_keys}"
            + (f" filter={node.filter!r}" if node.filter is not None else "")
        )
    elif isinstance(node, (SortNode, TopNNode)):
        detail = f" by={node.sort_channels}" + (
            f" count={node.count}" if isinstance(node, TopNNode) else ""
        )
    elif isinstance(node, LimitNode):
        detail = f" {node.count}"
    elif isinstance(node, ExchangeNode):
        detail = f" [{node.scope}/{node.partitioning}] keys={node.partition_channels}"
    elif isinstance(node, OutputNode):
        detail = f" {node.column_names}"
    if executor is not None:
        st = executor.node_stats.get(node.id)
        if st is not None:
            detail += f"  [wall={st.wall_s * 1e3:.1f}ms rows={st.output_rows}]"
            if verbose:
                detail += (f" [bytes={st.output_bytes}"
                           f" peak={st.peak_bytes}]")
        if isinstance(node, TableScanNode) and node.id in executor.scan_stats:
            detail += f" [scanned={executor.scan_stats[node.id]}]"
        for sp in executor.memory.spills:
            if sp.node_id == node.id:
                detail += (
                    f" [spilled: {sp.partitions} passes,"
                    f" {sp.projected_bytes // 1024}KiB projected]"
                )
    elif stats is not None:
        st = stats.get(node.id)
        if st is not None:
            detail += f"  [wall={st.wall_s * 1e3:.1f}ms rows={st.output_rows}]"
            if isinstance(node, TableScanNode) and (st.splits or st.input_rows):
                detail += f" [scanned={st.input_rows} splits={st.splits}]"
            if verbose:
                detail += (f" [bytes={st.output_bytes}"
                           f" peak={st.peak_bytes}"
                           f" calls={st.invocations}]")
    if verbose and kernels:
        kr = kernels.get(str(node.id))
        if kr is not None:
            detail += (f" [launches={kr['launches']}"
                       f" dispatch_overhead={kr['overheadS'] * 1e3:.1f}ms]")
    lines = [f"{pad}- {label}{detail}"]
    for s in node.sources:
        lines.append(format_plan(s, indent + 1, executor, stats, verbose,
                                 kernels))
    return "\n".join(lines)


def self_aggs(node: AggregationNode):
    return node.aggregates or []
