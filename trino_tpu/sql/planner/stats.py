"""Plan cardinality estimates + expansion-join capacity hints.

Reference role: ``core/trino-main/.../cost/`` (StatsCalculator,
FilterStatsCalculator, JoinStatsRule) in miniature. Estimates flow from
connector row counts (``Connector.table_row_count``) through simple
selectivity heuristics. They are NOT trusted for correctness — an expansion
join or hash exchange whose true size exceeds its estimated static capacity
raises a deferred ``CAPACITY_EXCEEDED:<hint-key>`` flag, and the compiled
paths double that bucket and recompile (the bucketed-recompile loop of
SURVEY.md §7.3; the spill-FSM analog of HashBuilderOperator.java:162-177).

Also home to the broadcast-vs-repartition distribution choice (reference:
DetermineJoinDistributionType + AddExchanges.java:138): both the build-time
hint estimation and SpmdExecutor's trace-time dispatch consult the same
predicates, so hints always exist for the exchanges the trace creates.
"""
from __future__ import annotations

from typing import Dict

from trino_tpu.sql.planner import plan as P

# Heuristic fudge factors, biased high — capacity hints should over- rather
# than under-estimate to avoid recompiles. Filters don't discount (the
# reference's FilterStatsCalculator discounts by 0.9 per unknown conjunct;
# a capacity hint must survive the filter being non-selective).
JOIN_FANOUT = 1.25  # M:N fudge over the FK-join output (= probe rows)
MIN_CAPACITY = 1024


def estimate_rows(session, node: P.PlanNode) -> int:
    """Rough output-row estimate per plan node (upper-bound biased)."""
    if isinstance(node, P.TableScanNode):
        conn = session.catalogs.get(node.catalog)
        n = conn.table_row_count(node.schema, node.table) if conn else None
        return int(n) if n else MIN_CAPACITY
    if isinstance(node, P.ValuesNode):
        return max(1, len(node.rows or ()))
    if isinstance(node, (P.LimitNode, P.TopNNode)):
        return min(node.count, estimate_rows(session, node.source))
    if isinstance(node, P.JoinNode):
        left = estimate_rows(session, node.left)
        right = estimate_rows(session, node.right)
        if node.join_type in ("semi", "anti"):
            return left
        if node.singleton:
            return left
        if node.right_unique:
            return left  # N:1 lookup join: output == probe rows
        if not node.left_keys:  # cross join
            return left * right
        return int(max(left, right) * JOIN_FANOUT)
    if isinstance(node, P.AggregationNode):
        # group count <= input rows; the sort-based kernel's capacity is the
        # input row count anyway
        return estimate_rows(session, node.source)
    if isinstance(node, P.UnionNode):
        # UNION ALL output = SUM of branches (the generic max fallback
        # would under-allocate capacity hints by the branch count)
        return sum(estimate_rows(session, s) for s in node.sources_)
    srcs = node.sources
    if not srcs:
        return MIN_CAPACITY
    return max(estimate_rows(session, s) for s in srcs)


def _expansion_capacity(session, node: P.JoinNode) -> int:
    left = estimate_rows(session, node.left)
    right = estimate_rows(session, node.right)
    if not node.left_keys:  # true cross join: exact
        est = left * right
    elif node.join_type in ("semi", "anti"):
        # filtered-semi expansion materializes all key matches
        est = int(max(left, right) * JOIN_FANOUT)
    else:
        est = int(max(left, right) * JOIN_FANOUT)
        if node.join_type == "left":
            est = max(est, left)  # outer emits >= one slot per probe row
    return _pow2(max(est, MIN_CAPACITY))


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length()


def estimate_capacity_hints(session, root: P.PlanNode) -> Dict[str, int]:
    """Static output capacities for every expansion-join node in the plan,
    from stats alone (no eager pre-run)."""
    hints: Dict[str, int] = {}
    for n in P.walk_plan(root):
        if isinstance(n, P.JoinNode) and P.uses_expansion_kernel(n):
            hints[f"join:{n.id}"] = _expansion_capacity(session, n)
    return hints


# ---------------------------------------------------------------- exchanges

# Build sides larger than this repartition instead of broadcasting
# (join_max_broadcast_table_size analog, in rows).
BROADCAST_BUILD_MAX = 1 << 17
# Aggregations whose per-device input exceeds this repartition raw rows by
# group-key hash instead of gathering partial states.
GATHER_AGG_MAX_ROWS_PER_DEVICE = 1 << 16
MIN_EXCHANGE_CAPACITY = 256


def _keys_low_cardinality(node: P.AggregationNode) -> bool:
    """Group keys whose domain is small enough for the gather exchange no
    matter the row count (dictionary codes / booleans — the direct-layout
    grouping fast path)."""
    src_types = node.source.output_types
    for c in node.group_channels:
        t = src_types[c]
        if not (t.is_varchar or t.name == "boolean"):
            return False
    return True


def agg_repartitions(session, node: P.AggregationNode, n_devices: int) -> bool:
    """True when a distributed single-step aggregation should hash-repartition
    raw rows by group key (FIXED_HASH_DISTRIBUTION) instead of gathering
    partial states (the low-cardinality path)."""
    if not node.group_channels:
        return False  # global aggregate: partial states are one row
    if any(c.distinct for c in node.aggregates):
        return False  # distinct fallback gathers raw rows (for now)
    if _keys_low_cardinality(node):
        return False
    rows = estimate_rows(session, node.source)
    return rows // max(n_devices, 1) > GATHER_AGG_MAX_ROWS_PER_DEVICE


def join_repartitions(session, node: P.JoinNode, n_devices: int) -> bool:
    """True when a distributed join should co-partition both sides by key
    hash instead of broadcasting the build side."""
    if not node.left_keys:
        return False  # cross join: broadcast is the only option
    build = estimate_rows(session, node.right)
    return build > BROADCAST_BUILD_MAX


def exchange_capacity(session, source: P.PlanNode, n_devices: int) -> int:
    """Static per-(source device, destination device) block size for a hash
    exchange of ``source``'s rows: ~2x the uniform share, doubled on
    overflow by the recompile loop (skewed keys land here)."""
    rows = estimate_rows(session, source)
    per_block = (2 * rows) // max(n_devices * n_devices, 1)
    return _pow2(max(per_block, MIN_EXCHANGE_CAPACITY))


def estimate_exchange_hints(session, root: P.PlanNode, n_devices: int) -> Dict[str, int]:
    """Capacity hints for every hash exchange the SPMD trace will create —
    consults the same predicates as SpmdExecutor's dispatch."""
    hints: Dict[str, int] = {}
    for n in P.walk_plan(root):
        if isinstance(n, P.AggregationNode) and n.step == "single":
            if agg_repartitions(session, n, n_devices):
                hints[f"xchg:{n.id}"] = exchange_capacity(session, n.source, n_devices)
        elif isinstance(n, P.JoinNode):
            if join_repartitions(session, n, n_devices):
                hints[f"xchgl:{n.id}"] = exchange_capacity(session, n.left, n_devices)
                hints[f"xchgr:{n.id}"] = exchange_capacity(session, n.right, n_devices)
    return hints


CAPACITY_ERROR_PREFIX = "CAPACITY_EXCEEDED:"


def grow_overflowed_hints(hints: Dict[str, int], codes, flags) -> Dict[str, int]:
    """Scan deferred-error (code, flag) pairs; double the bucket of every
    expansion join / exchange whose capacity flag fired (flags may be
    per-device stacks). Returns a new dict, or None when nothing overflowed
    — the shared half of the bucketed-recompile loop (CompiledQuery.run /
    DistributedQuery.run)."""
    import numpy as np

    out = None
    for code, flag in zip(codes, flags):
        if code.startswith(CAPACITY_ERROR_PREFIX) and bool(np.asarray(flag).any()):
            key = code[len(CAPACITY_ERROR_PREFIX):]
            out = dict(hints) if out is None else out
            out[key] = out.get(key, MIN_CAPACITY) * 2
    return out
